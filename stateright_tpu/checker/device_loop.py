"""Device-resident multi-level search loop for ``spawn_tpu``.

The per-level orchestration in `tpu.py` pays one host round trip per BFS
level — fatal when the device is remote (tunneled TPU) and wasteful even
locally. This module compiles the *entire search loop* into one XLA
computation: a ``lax.while_loop`` whose carry holds

  * a FIFO **ring queue** of pending packed states (the device analog of the
    reference's shared ``pending`` deques, `/root/reference/src/checker/bfs.rs:29-30`),
  * the open-addressed visited table (`ops/hashtable.py`),
  * an append-only **log** of (child fp, parent fp) pairs — the complete
    search record from which the host lazily mirrors its
    fingerprint->parent map for trace reconstruction (TLC-style,
    `bfs.rs:314-342`) and checkpointing,
  * sticky per-property discovery registers (first witnessing fingerprint),
  * counters and overflow flags.

Each ``while_loop`` iteration expands up to ``fmax`` queue rows exactly like
the reference's ``check_block`` hot loop (`bfs.rs:165-274`): property
evaluation, action expansion, fingerprinting, dedup-insert, enqueue. The
host re-enters the loop only every ``steps`` iterations (one dispatch per
chunk) to read a handful of scalars — progress, discoveries, growth/exit
conditions.

Queue order is FIFO, so expansion stays level-ordered (BFS) and discovered
witness paths stay shortest, like ``spawn_bfs``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.expand import (discovery_candidates, eventually_indices,
                          expand_frontier)
from ..ops.hashtable import table_insert


class ChunkCarry(NamedTuple):
    q_rows: jax.Array   # uint32[qcap, W] ring queue of pending states
    q_eb: jax.Array     # uint32[qcap]    their eventually-bits
    q_head: jax.Array   # int32[]         ring head index
    q_size: jax.Array   # int32[]         pending count
    key_hi: jax.Array   # uint32[cap]     visited table
    key_lo: jax.Array   # uint32[cap]
    log_chi: jax.Array  # uint32[logcap]  child fp (insertion order)
    log_clo: jax.Array  # uint32[logcap]
    log_phi: jax.Array  # uint32[logcap]  parent fp
    log_plo: jax.Array  # uint32[logcap]
    log_n: jax.Array    # int32[]
    disc_hit: jax.Array  # bool[P]   property discovered?
    disc_hi: jax.Array   # uint32[P] witnessing state fp (sticky first)
    disc_lo: jax.Array   # uint32[P]
    gen: jax.Array      # int32[]  states generated THIS chunk (host accumulates)
    ovf: jax.Array      # bool[]   table probe overflow (should not happen
    #                              below the growth limit)
    xovf: jax.Array     # bool[]   model capacity overflow (fatal)
    steps: jax.Array    # int32[]  remaining step budget for this chunk


def build_chunk_fn(model, qcap: int, capacity: int, fmax: int):
    """Compile the K-level chunk runner for fixed buffer shapes.

    Returned callable: ``chunk(carry, target_remaining, grow_limit) ->
    carry`` where ``target_remaining`` bounds ``gen`` (INT32_MAX when
    unbounded) and ``grow_limit`` is the log length at which the loop exits
    so the host can grow the table.
    """
    assert qcap & (qcap - 1) == 0, "qcap must be a power of two"
    n_actions = model.max_actions
    properties = model.properties()
    prop_count = len(properties)
    eventually_idx = eventually_indices(properties)
    logcap = capacity
    qmask = qcap - 1
    fa = fmax * n_actions

    def cond(state):
        c, target_remaining, grow_limit = state
        go = (c.q_size > 0) & (c.steps > 0) & ~c.ovf & ~c.xovf \
            & (c.gen < target_remaining) \
            & (c.log_n < grow_limit) \
            & (c.q_size <= qcap - fa)
        if prop_count:
            go = go & ~c.disc_hit.all()
        return go

    def body(state):
        c, target_remaining, grow_limit = state
        idxs = (c.q_head + jnp.arange(fmax, dtype=jnp.int32)) & qmask
        frontier = c.q_rows[idxs]
        ebits = c.q_eb[idxs]
        take = jnp.minimum(c.q_size, fmax)
        fvalid = jnp.arange(fmax, dtype=jnp.int32) < take

        # the shared check_block analog (ops/expand.py)
        exp = expand_frontier(model, frontier, fvalid, ebits,
                              eventually_idx)
        inserted, key_hi, key_lo, t_ovf = table_insert(
            c.key_hi, c.key_lo, exp.chi, exp.clo, exp.cvalid)
        cnt = inserted.sum(dtype=jnp.int32)
        pos = jnp.cumsum(inserted.astype(jnp.int32)) - 1

        # enqueue fresh children (ring append)
        qidx = jnp.where(inserted, (c.q_head + c.q_size + pos) & qmask, qcap)
        q_rows = c.q_rows.at[qidx].set(exp.flat, mode="drop")
        ceb = jnp.repeat(exp.ebits, n_actions)
        q_eb = c.q_eb.at[qidx].set(ceb, mode="drop")

        # log (child, parent) fingerprints in insertion order
        lidx = jnp.where(inserted, c.log_n + pos, logcap)
        par_hi = jnp.repeat(exp.phi, n_actions)
        par_lo = jnp.repeat(exp.plo, n_actions)
        log_chi = c.log_chi.at[lidx].set(exp.chi, mode="drop")
        log_clo = c.log_clo.at[lidx].set(exp.clo, mode="drop")
        log_phi = c.log_phi.at[lidx].set(par_hi, mode="drop")
        log_plo = c.log_plo.at[lidx].set(par_lo, mode="drop")

        # sticky discovery registers
        disc_hit, disc_hi, disc_lo = c.disc_hit, c.disc_hi, c.disc_lo
        if prop_count:
            new_hit, cand_hi, cand_lo = discovery_candidates(
                properties, exp, fvalid)
            keep = disc_hit | ~new_hit
            disc_hi = jnp.where(keep, disc_hi, cand_hi)
            disc_lo = jnp.where(keep, disc_lo, cand_lo)
            disc_hit = disc_hit | new_hit

        nc = ChunkCarry(
            q_rows=q_rows, q_eb=q_eb,
            q_head=(c.q_head + take) & qmask,
            q_size=c.q_size - take + cnt,
            key_hi=key_hi, key_lo=key_lo,
            log_chi=log_chi, log_clo=log_clo,
            log_phi=log_phi, log_plo=log_plo,
            log_n=c.log_n + cnt,
            disc_hit=disc_hit, disc_hi=disc_hi, disc_lo=disc_lo,
            gen=c.gen + exp.cvalid.sum(dtype=jnp.int32),
            ovf=c.ovf | t_ovf,
            xovf=c.xovf | exp.xovf,
            steps=c.steps - 1)
        return (nc, target_remaining, grow_limit)

    def chunk(carry: ChunkCarry, target_remaining, grow_limit):
        out, _, _ = jax.lax.while_loop(
            cond, body, (carry, target_remaining, grow_limit))
        return out

    return jax.jit(chunk, donate_argnums=(0,))


def seed_carry(model, qcap: int, capacity: int, init_rows, full_ebits,
               steps: int = 0):
    """Host-side construction of the initial carry (init states enqueued;
    the caller bulk-inserts their fingerprints into the table)."""
    import numpy as np

    width = model.packed_width
    prop_count = len(model.properties())
    q_rows = np.zeros((qcap, width), dtype=np.uint32)
    q_eb = np.zeros((qcap,), dtype=np.uint32)
    for i, row in enumerate(init_rows):
        q_rows[i] = row
        q_eb[i] = full_ebits
    logcap = capacity
    return ChunkCarry(
        q_rows=jnp.asarray(q_rows), q_eb=jnp.asarray(q_eb),
        q_head=jnp.int32(0), q_size=jnp.int32(len(init_rows)),
        key_hi=jnp.zeros((capacity,), jnp.uint32),
        key_lo=jnp.zeros((capacity,), jnp.uint32),
        log_chi=jnp.zeros((logcap,), jnp.uint32),
        log_clo=jnp.zeros((logcap,), jnp.uint32),
        log_phi=jnp.zeros((logcap,), jnp.uint32),
        log_plo=jnp.zeros((logcap,), jnp.uint32),
        log_n=jnp.int32(0),
        disc_hit=jnp.zeros((prop_count,), bool),
        disc_hi=jnp.zeros((prop_count,), jnp.uint32),
        disc_lo=jnp.zeros((prop_count,), jnp.uint32),
        gen=jnp.int32(0), ovf=jnp.bool_(False), xovf=jnp.bool_(False),
        steps=jnp.int32(steps))
