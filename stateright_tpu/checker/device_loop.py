"""Device-resident multi-level search loop for ``spawn_tpu``.

The per-level orchestration in `tpu.py` pays one host round trip per BFS
level — fatal when the device is remote (tunneled TPU) and wasteful even
locally. This module compiles the *entire search loop* into one XLA
computation: a ``lax.while_loop`` whose carry holds

  * an **append-only FIFO queue** of pending packed states (the device
    analog of the reference's shared ``pending`` deques,
    `/root/reference/src/checker/bfs.rs:29-30`). Every state is enqueued
    exactly once (it enters the queue iff it won its visited-table slot),
    so the queue never wraps: the head only advances, and appends are
    contiguous block writes at the tail;
  * the open-addressed visited table (`ops/hashtable.py`),
  * an append-only **log** of (child fp, parent fp) pairs — the complete
    search record from which the host lazily mirrors its
    fingerprint->parent map for trace reconstruction (TLC-style,
    `bfs.rs:314-342`) and checkpointing,
  * sticky per-property discovery registers (first witnessing fingerprint),
  * counters and overflow flags.

Each ``while_loop`` iteration expands up to ``fmax`` queue rows exactly like
the reference's ``check_block`` hot loop (`bfs.rs:165-274`): property
evaluation, action expansion, fingerprinting, dedup-insert, enqueue. The
host re-enters the loop only every ``steps`` iterations (one dispatch per
chunk) to read a handful of scalars — progress, discoveries, growth/exit
conditions.

TPU performance notes (these shaped the design — every lane of a
data-dependent scatter/gather/probe costs real time on TPU, so the body
minimizes both scatter *count* and operating *lane width*):

  * the expansion produces ``fmax * max_actions`` child slots of which
    only the valid fraction matters. Valid children are immediately
    **shrunk to a narrow static buffer of ``kmax`` lanes** with a
    gather-only compaction (binary search over the validity prefix-sum —
    the inverse of the usual cumsum+scatter), and every downstream op
    (table probe, insert, second compaction) runs at ``kmax`` lanes, not
    ``fmax * max_actions``. If a batch produces more valid children than
    ``kmax``, the iteration aborts *before any mutation* and the host
    rebuilds with a doubled ``kmax`` — no work is lost.
  * the body performs **no row scatters at all**: freshly inserted
    children are compacted to a dense prefix (same gather trick) and both
    the queue append and the log append are contiguous
    ``dynamic_update_slice`` block writes at the tail. The garbage rows
    past ``count`` inside an appended block are never observed: the tail
    advances only by ``count``, and the next block write starts there,
    overwriting them.

Queue order is FIFO, so expansion stays level-ordered (BFS) and discovered
witness paths stay shortest, like ``spawn_bfs``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.expand import (assemble_candidates, discovery_candidates,
                          eventually_indices, expand_frontier, pre_dedup)
from ..ops.hash_kernel import fp64_device, fp64_node_device
from ..ops.hashtable import _BUCKET, table_insert


class ChunkCarry(NamedTuple):
    # ONE queue matrix and ONE log matrix: every iteration appends each
    # with a single contiguous block write (and reads the frontier with a
    # single block read). The previous four queue columns + four-to-six
    # log columns cost ~8-10 dynamic_update_slice/dynamic_slice kernels
    # per iteration; sequential op COUNT is the per-iteration cost lever
    # on this platform (NOTES.md).
    q: jax.Array        # uint32[qcap, W+3] append-only queue of pending
    #                     states: packed row (cols 0..W-1), eventually-
    #                     bits (col W), cached STATE fingerprint hi/lo
    #                     (cols W+1, W+2 — canonical under symmetry,
    #                     stored at insert time so expansion never
    #                     re-hashes the frontier)
    q_head: jax.Array   # int32[]         next row to expand
    q_tail: jax.Array   # int32[]         next free row (q_size = tail-head)
    key_hi: jax.Array   # uint32[cap/4, 4] visited table, bucket-major —
    key_lo: jax.Array   #                  kept 2-D across iterations so
    #                                      the probe pays no per-iteration
    #                                      tile-layout conversion
    log: jax.Array      # uint32[logcap, 4|6] insertion-order log: child
    #                     fp hi/lo (cols 0,1 — canonical under symmetry,
    #                     node keys under sound), parent fp hi/lo (2,3),
    #                     child ORIGINAL state fp hi/lo (4,5 — present
    #                     under symmetry/sound only)
    log_n: jax.Array    # int32[]
    disc_hit: jax.Array  # bool[P]   property discovered?
    disc_hi: jax.Array   # uint32[P] witnessing state fp (sticky first)
    disc_lo: jax.Array   # uint32[P]
    gen: jax.Array      # int32[]  states generated THIS chunk (host accumulates)
    ovf: jax.Array      # bool[]   table probe overflow (should not happen
    #                              below the growth limit)
    xovf: jax.Array     # bool[]   model capacity overflow (fatal)
    kovf: jax.Array     # bool[]   kmax candidate-buffer overflow (host
    #                              rebuilds with doubled kmax; no data loss)
    steps: jax.Array    # int32[]  remaining step budget for this chunk
    vmax: jax.Array     # int32[]  max RAW-valid children in one iteration
    #                              this chunk — the host right-sizes kraw
    #                              from it (gather cost scales with it)
    dmax: jax.Array     # int32[]  max post-dedup children in one
    #                              iteration this chunk — sizes kmax (the
    #                              probe/append stage-two buffer)
    rmax: jax.Array     # int32[]  max valid children of a single ROW
    #                              this chunk — sizes hint_eff (the
    #                              per-row compaction width)
    # --- host-property history dedup (models with host_property_indices;
    # 1-element dummies otherwise). The table dedups inserted states by
    # their host-property key columns IN the loop body, so the host's
    # per-chunk work shrinks from a standalone reduction dispatch (the
    # ~0.2-0.3s while_loop dispatch floor, NOTES.md) to one small gather
    # of the fresh representatives.
    hkey_hi: jax.Array  # uint32[hcap | 1]  history-key table
    hkey_lo: jax.Array  # uint32[hcap | 1]
    hidx: jax.Array     # int32[logcap | 1] queue index of each distinct
    #                                       key's first (representative) row
    h_n: jax.Array      # int32[]  representatives logged so far
    hovf: jax.Array     # bool[]   history-table probe overflow: the loop
    #                              exits; the host grows hcap, re-seeds the
    #                              table from hidx, and resumes (no loss —
    #                              the iteration aborts before mutation)
    # --- sound-mode cross-edge log (1-row dummy otherwise): dedup HITS
    # whose child node still has pending eventually-bits, as (parent
    # node key, child node key) rows. Insert edges live in the main log;
    # together they are the full node graph the post-exhaustion lasso
    # sweep (checker/lasso.py) needs for cycle-complete liveness.
    elog: jax.Array     # uint32[ecap | 1, 4]
    e_n: jax.Array      # int32[]  edges logged so far
    # --- per-chunk dedup telemetry (obs GLOSSARY: predup_hits /
    # probe_rounds), reset at each dispatch like ``gen``
    pdh: jax.Array      # int32[]  duplicate lanes killed by the in-batch
    #                              pre-dedup this chunk
    prb: jax.Array      # int32[]  visited-table probe rounds this chunk


def shrink_indices(mask, k: int):
    """Compaction plan: ``src[j]`` is the index of the ``j+1``-th set bit
    of ``mask`` (arbitrary value for ``j >= count`` — callers mask by the
    live count). Computed as ONE inverse 1D scatter of the running
    positions — ~25x cheaper in-loop than the binary-search dual on TPU,
    where narrow 1D scatters are cheap but wide gather cascades are not."""
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    # set bits past the k-th are dropped (not collapsed onto lane k-1),
    # so every produced lane < min(count, k) is exact even on overflow
    idx = jnp.where(mask & (pos < k), pos, k)
    inv = jnp.zeros((k + 1,), jnp.int32).at[idx].set(
        jnp.arange(mask.shape[0], dtype=jnp.int32), mode="drop")
    return inv[:k]


class LruCache(dict):
    """Bounded compiled-program cache with least-recently-used eviction
    (the previous wholesale ``.clear()`` at the limit forced a full
    recompile cliff for long-lived processes alternating many model
    configs). Lock-guarded: the caches are module-global and every
    checker runs on its own background thread."""

    def __init__(self, limit: int = 64):
        super().__init__()
        import threading
        self._limit = limit
        self._order: list = []
        self._lock = threading.Lock()

    def get(self, key, default=None):
        with self._lock:
            if key in self:
                self._order.remove(key)
                self._order.append(key)
                return super().__getitem__(key)
            return default

    def _set(self, key, value):
        """Unlocked insert-with-eviction shared by the locked writers."""
        if key not in self:
            while len(self._order) >= self._limit:
                super().__delitem__(self._order.pop(0))
            self._order.append(key)
        super().__setitem__(key, value)

    def __setitem__(self, key, value):
        with self._lock:
            self._set(key, value)

    def merge_max(self, key, values):
        """Atomic elementwise-max merge (the observed-size memo): a
        separate get-max-set would let a concurrent smaller observation
        overwrite a larger one."""
        with self._lock:
            if key in self:
                old = super().__getitem__(key)
                values = tuple(max(a, b) for a, b in zip(old, values))
            self._set(key, tuple(values))


_CHUNK_CACHE = LruCache()


def model_cache_key(model):
    """Composite memoization key: the model's declared config key plus
    everything else that changes the traced program — the concrete class
    (subclasses override packed_step) and mutable flags like
    ``lossy_network_``. None disables caching."""
    mkey = model.cache_key()
    if mkey is None:
        return None
    return (type(model), mkey, getattr(model, "lossy_network_", None),
            getattr(model, "max_crashes_", None),
            getattr(model, "crashable_", None))


def build_chunk_fn(model, qcap: int, capacity: int, fmax: int, kmax: int,
                   symmetry: bool = False, sound: bool = False,
                   hcap: int = 0, n_init: int = 0, kraw: int = 0,
                   hint_eff: int = 0, ecap: int = 0,
                   fused: bool = False, fused_interpret: bool = False,
                   cc: int = 0):
    """Compile the K-level chunk runner for fixed buffer shapes.

    Returned callable: ``chunk(carry, target_remaining, grow_limit,
    h_base) -> (carry, stats)`` where ``target_remaining`` bounds ``gen``
    (INT32_MAX when unbounded), ``grow_limit`` is the log length at which
    the loop exits so the host can grow the table, and ``h_base`` anchors
    the representative window at the host's already-pulled count.
    ``kmax`` bounds valid children per iteration; exceeding it sets
    ``kovf`` and leaves the carry untouched.

    Thin frontiers (common at the start and tail of every search) run a
    small compiled step; the program SEQUENCES three ``while_loop``s —
    small, large, small — each gated on its frontier-size window, instead
    of branching per iteration: an in-loop ``lax.cond`` over the two step
    sizes copied every carried buffer per iteration (~1.4 ms at paxos
    shapes, profiler-verified round 5 — the round-3 cond finding), and
    host-chained separate programs paid the ~30 ms tunneled dispatch
    floor per launch. Sequential loops in one launch pay neither.

    With ``sound`` (``CheckerBuilder.sound_eventually()``), dedup and the
    log work on (state, pending-ebits) NODE keys (``fp64_node_device``)
    while the log's original-fp columns record the plain state
    fingerprints for replay — fixing the reference's documented
    DAG-rejoin miss (`bfs.rs:239-244`).

    Memoized on :func:`model_cache_key`: checker runs re-use the jitted
    (and already-compiled) chunk across instances of the same model config.
    """
    mkey = model_cache_key(model)
    key = (mkey, qcap, capacity, fmax, kmax, symmetry, sound, hcap,
           n_init, kraw, hint_eff, ecap, fused, fused_interpret, cc)
    if mkey is not None:
        cached = _CHUNK_CACHE.get(key)
        if cached is not None:
            return cached
    fn = _build_chunk_fn(model, qcap, capacity, fmax, kmax, symmetry,
                         sound, hcap, n_init, kraw, hint_eff, ecap,
                         fused, fused_interpret, cc)
    if mkey is not None:
        _CHUNK_CACHE[key] = fn
    return fn


def _build_chunk_fn(model, qcap: int, capacity: int, fmax: int, kmax: int,
                    symmetry: bool, sound: bool = False, hcap: int = 0,
                    n_init: int = 0, kraw: int = 0, hint_eff: int = 0,
                    ecap: int = 0, fused: bool = False,
                    fused_interpret: bool = False, cc: int = 0):
    return jax.jit(
        build_chunk_core(model, qcap, capacity, fmax, kmax, symmetry,
                         sound, hcap, n_init, kraw, hint_eff, ecap,
                         fused, fused_interpret, cc),
        # the fused+cc chunk additionally donates the cross-chunk ring
        # halves it threads through (args 1 and 2)
        donate_argnums=(0, 1, 2) if (fused and cc) else (0,))


def build_chunk_core(model, qcap: int, capacity: int, fmax: int,
                     kmax: int, symmetry: bool, sound: bool = False,
                     hcap: int = 0, n_init: int = 0, kraw: int = 0,
                     hint_eff: int = 0, ecap: int = 0,
                     fused: bool = False, fused_interpret: bool = False,
                     cc: int = 0):
    """The UN-jitted chunk program: ``chunk(carry, target_remaining,
    grow_limit, h_base) -> (carry, stats)``. ``build_chunk_fn`` wraps
    it in the solo engines' donating ``jax.jit``; the batch engine
    (``checker/batch_loop.py``) instead maps it over a LANE axis with
    ``jax.vmap`` — one compiled program advancing many small same-shape
    jobs, each lane carrying its own queue/table/log slices."""
    if fused:
        # support matrix (ops/fused.py supports()): the engines route
        # sound / host-property / hint configs to the staged build
        assert not sound and not hcap and not hint_eff and not ecap, \
            "fused chunk build outside its support matrix"
    else:
        assert not cc, "cc dedup ring is a fused-path structure"
    n_actions = model.max_actions
    width = model.packed_width
    properties = model.properties()
    prop_count = len(properties)
    eventually_idx = eventually_indices(properties)
    # host-evaluated properties are discovered between chunks (post-hoc),
    # never by the in-loop registers — their placeholder bits must not
    # stop (or worse, stall) the device loop
    host_idx = frozenset(getattr(model, "host_property_indices", ()))
    device_prop_idx = [i for i in range(prop_count) if i not in host_idx]
    fa = fmax * n_actions
    kmax = min(kmax, fa)
    # two-stage candidate compaction: raw-valid lanes compact to the
    # kraw buffer (where hashing and in-batch dedup run); dedup
    # SURVIVORS compact again to the narrower kmax buffer for the table
    # probe, candidate assembly, and appends. Duplicate-heavy models
    # (2pc: >80% duplicate lanes) keep their narrow probe while the
    # hash/dedup still runs far below the fa width. kraw == kmax (the
    # sound-mode default — node-key dedup happens in the table) makes
    # stage two a trace-time no-op.
    #
    # With ``hint_eff`` (models declaring ``branching_hint``: a per-ROW
    # bound on valid children), stage one is PER-ROW instead of global:
    # a tiny top_k over each row's action axis selects its <= hint_eff
    # valid slots and one gather reads them straight out of the 3-D
    # successor tensor — no fa-wide cumsum/scatter, no F*A flat reshape
    # (a tile relayout), and kraw is the static fmax*hint_eff. A row
    # exceeding hint_eff aborts the iteration before any mutation
    # (rmax rides the stats; the host rebuilds with a larger hint).
    if hint_eff and hint_eff >= n_actions:
        hint_eff = 0  # degenerate: the full action axis, use global path
    if hint_eff:
        kraw = fmax * hint_eff
    else:
        kraw = min(kraw, fa) if kraw else kmax
    kmax = min(kmax, kraw)
    # in-loop history-key dedup for host-evaluated properties
    hist_on = hcap > 0
    if hist_on:
        cols = getattr(model, "host_property_cols", None)
        hoff, hwidth = cols if cols is not None \
            else (0, model.packed_width)
        # a full-of-foreign probe advances one bucket per round, so the
        # scan is bounded by the bucket count; claim-loser retries add a
        # small constant. Hitting the bound reports hovf (the growth
        # signal) instead of spinning out the default 4096 rounds.
        h_rounds = min(4096, hcap + 64)
    # the queue slice must cover BOTH the widest append (kmax rows; the
    # fused step appends straight from the F*A lane mask — no candidate
    # staging — so its margin is fa) and the frontier dequeue (fmax rows
    # — dynamic_slice would silently CLAMP its start near the end of the
    # queue, re-expanding consumed rows and skipping pending ones)
    qmargin = max(fa, fmax) if fused else max(kmax, fmax)

    def make_cond(lo_water, hi_water):
        def cond(state):
            # the fused+cc state threads (carry, ring_hi, ring_lo, cch)
            # ahead of the scalars; index from both ends so one cond
            # covers both layouts
            c, target_remaining, grow_limit = (state[0], state[-2],
                                               state[-1])
            avail = c.q_tail - c.q_head
            # [lo, hi] is the loop's frontier-size window: the small loop
            # (hi = fmax_small) yields once the frontier outgrows it, the
            # large loop (lo = fmax_small+1) yields once it thins; the
            # next loop in the chunk's small-large-small sequence picks
            # the frontier up, in the same launch
            go = (avail > 0) & (avail >= lo_water) & (avail <= hi_water) \
                & (c.steps > 0) \
                & ~c.ovf & ~c.xovf & ~c.kovf & ~c.hovf \
                & (c.gen < target_remaining) \
                & (c.log_n < grow_limit) \
                & (c.q_tail <= qcap - qmargin)
            if ecap:
                # the cross-edge log must keep one iteration of headroom;
                # the host grows it on exit
                go = go & (c.e_n <= ecap - qmargin)
            if device_prop_idx and not host_idx:
                # stop once every device-evaluated property has a
                # discovery — but only when no host properties remain:
                # those need the reached set to keep growing between
                # post-hoc passes
                go = go & ~c.disc_hit[jnp.array(device_prop_idx)].all()
            return go
        return cond

    def make_step(fmax_b: int, kraw_b: int, kfin_b: int):
        def step(state):
            c, target_remaining, grow_limit = state
            sl = jax.lax.dynamic_slice(
                c.q, (c.q_head, 0), (fmax_b, width + 3))
            frontier = sl[:, :width]
            ebits = sl[:, width]
            pfp = (sl[:, width + 1], sl[:, width + 2])
            take = jnp.minimum(c.q_tail - c.q_head, fmax_b)
            fvalid = jnp.arange(fmax_b, dtype=jnp.int32) < take

            # the shared check_block analog (ops/expand.py); the frontier
            # fingerprints come from the queue cache, not a re-hash, and
            # child fingerprints are deferred to the narrow buffer below
            exp = expand_frontier(model, frontier, fvalid, ebits,
                                  eventually_idx, symmetry=symmetry,
                                  pfp=pfp, child_fp=False)
            cvalid = exp.cvalid
            gen_count = cvalid.sum(dtype=jnp.int32)
            vcount = gen_count
            if hint_eff:
                # per-row bound: abort (before any mutation) only when a
                # single row outgrows the declared branching hint
                rcnt = exp.avalid.sum(axis=1, dtype=jnp.int32)
                rmax_it = rcnt.max()
                kovf = rmax_it > hint_eff
            else:
                rmax_it = jnp.int32(0)
                kovf = vcount > kraw_b

            if sound:
                # node keys: dedup identity = (state fp, pending ebits).
                # The parent's node used its AT-ENQUEUE bits (pre-clear
                # `ebits`); witnesses and log parents use node keys so the
                # host mirror chain stays walkable
                p_whi, p_wlo = fp64_node_device(exp.phi, exp.plo, ebits)
            else:
                p_whi, p_wlo = exp.phi, exp.plo

            # sticky discovery registers (idempotent: safe even if the
            # kovf branch re-expands this frontier after a kmax rebuild)
            disc_hit, disc_hi, disc_lo = c.disc_hit, c.disc_hi, c.disc_lo
            if prop_count:
                new_hit, cand_hi, cand_lo = discovery_candidates(
                    properties, exp, fvalid, whi=p_whi, wlo=p_wlo)
                keep = disc_hit | ~new_hit
                disc_hi = jnp.where(keep, disc_hi, cand_hi)
                disc_lo = jnp.where(keep, disc_lo, cand_lo)
                disc_hit = disc_hit | new_hit

            # GATHER-EARLY, TWO-STAGE: compact the raw-valid lanes to the
            # kraw_b buffer FIRST — hashing (and canonicalization, under
            # symmetry) and the in-batch dedup run there instead of at
            # the full fa width (at paxos shapes that was ~5
            # scatter/gather passes of 131k lanes each; per-lane
            # scatter/gather latency is the iteration's cost floor on
            # this platform, NOTES.md). Dedup SURVIVORS then compact to
            # the narrower kfin_b buffer where the table probe, the
            # candidate-matrix gather, and the appends run — on
            # duplicate-heavy models (2pc: >80% duplicate lanes) the
            # probe would otherwise pay 3-4x its necessary lane width.
            #
            # Abort protocol WITHOUT lax.cond: on this platform each
            # branch of a conditional that threads the big carried
            # buffers costs a full buffer copy EVERY iteration (~25 ms at
            # engine shapes, profiler-verified), so overflow handling is
            # expressed as masks instead. kovf pre-gates the table
            # insert's valid lanes, so nothing mutates and the host can
            # re-expand the same frontier after resizing (kraw and kmax
            # are sized independently from the reported vmax/dmax). hovf
            # COMMITS the iteration (its inserted keys and rows are real)
            # and only stops the loop; the unresolved lanes' keys went
            # unlogged, which the host recovers by rescanning this
            # chunk's queue span (TpuChecker._rescan_history). Garbage
            # rows block-written past an un-advanced tail are never
            # observed: the tail only moves on commit and the next
            # commit overwrites them.
            if hint_eff:
                # PER-ROW stage one: hint_eff rounds of argmax-and-mask
                # over each row's action axis (pure elementwise/reduce —
                # no cross-row scan, no fa-wide scatter) pick the row's
                # valid slots in action order; the slots become GLOBAL
                # flat indices for one plain 1-D gather. Parent-side
                # columns broadcast along the hint axis — no gather.
                # (A lax.top_k + 3-D take_along_axis variant measured ~2x
                # slower end-to-end on this platform.)
                avals = jnp.where(
                    exp.avalid,
                    jnp.arange(n_actions, 0, -1, dtype=jnp.int32)[None, :],
                    0)
                acols = jnp.arange(n_actions, dtype=jnp.int32)[None, :]
                cols = []
                for _s in range(hint_eff):
                    j = jnp.argmax(avals, axis=1).astype(jnp.int32)
                    cols.append(j)
                    avals = jnp.where(acols == j[:, None], 0, avals)
                j_table = jnp.stack(cols, axis=1)  # (F, hint)
                src = (jnp.arange(fmax_b, dtype=jnp.int32)[:, None]
                       * n_actions + j_table).reshape(-1)
                rows_k = exp.flat[src]
                rvalid = (jnp.arange(hint_eff, dtype=jnp.int32)[None, :]
                          < rcnt[:, None]).reshape(-1)
                par3 = jnp.broadcast_to(
                    jnp.stack([exp.ebits, p_whi, p_wlo], axis=1)[:, None, :],
                    (fmax_b, hint_eff, 3)).reshape(-1, 3)
            else:
                src = shrink_indices(cvalid, kraw_b)
                rvalid = jnp.arange(kraw_b, dtype=jnp.int32) < vcount
                rows_k = exp.flat[src]
                ridx = src // n_actions  # parent frontier row per lane
                # parent-side columns gathered in ONE 3-column pass
                par3 = jnp.stack([exp.ebits, p_whi, p_wlo], axis=1)[ridx]
            if symmetry:
                canon = jax.vmap(model.packed_representative)
                s_chi, s_clo = fp64_device(canon(rows_k))
                o_hi, o_lo = fp64_device(rows_k)
            else:
                s_chi, s_clo = fp64_device(rows_k)
                o_hi, o_lo = s_chi, s_clo
            ebits_k = par3[:, 0]
            if sound:
                # dedup identity under sound = (state fp, pending ebits)
                # node keys; the state fps stay in the candidate matrix
                # for the queue's fingerprint cache. No in-batch dedup
                # (the table resolves node-key duplicates), so stage two
                # is a no-op: kraw == kmax.
                k_chi, k_clo = fp64_node_device(s_chi, s_clo, ebits_k)
                dvalid = rvalid
            else:
                # EXACT in-batch duplicate-lane drop (ops/expand.py).
                # Load-bearing beyond dedup hygiene: WITHOUT it, same-fp
                # duplicate lanes spiral the table probe's claim-retry
                # rounds (paxos measured 23x slower)
                dvalid = pre_dedup(s_chi, s_clo, rvalid)
                k_chi, k_clo = s_chi, s_clo
            dcount = dvalid.sum(dtype=jnp.int32)
            kovf = kovf | (dcount > kfin_b)

            # ONE candidate matrix, assembled at kraw_b lanes
            # (ops/expand.assemble_candidates owns the column layout)
            cand, log_off = assemble_candidates(
                rows_k, ebits_k, s_chi, s_clo, par3[:, 1], par3[:, 2],
                o_hi, o_lo, width, symmetry, sound,
                nk_hi=k_chi if sound else None,
                nk_lo=k_clo if sound else None)

            if kfin_b < kraw_b:
                # stage two: survivors to the narrow probe buffer
                src2 = shrink_indices(dvalid, kfin_b)
                cand = cand[src2]
                k_chi = k_chi[src2]
                k_clo = k_clo[src2]
                kvalid = (jnp.arange(kfin_b, dtype=jnp.int32) < dcount) \
                    & ~kovf
            else:
                kvalid = dvalid & ~kovf

            inserted, key_hi, key_lo, t_ovf, t_rounds = table_insert(
                c.key_hi, c.key_lo, k_chi, k_clo, kvalid,
                with_rounds=True)
            t_ovf = t_ovf & ~kovf
            cnt = inserted.sum(dtype=jnp.int32)

            # the candidate matrix is gathered ONCE for the inserted lanes
            src3 = shrink_indices(inserted, kfin_b)
            n_all = cand[src3]
            n_flat = n_all[:, :width]

            if sound and ecap:
                # cross edges: dedup HITS whose child node still has
                # pending bits — with the main log's insert edges this
                # completes the node graph for the lasso sweep
                ehit = kvalid & ~inserted & (cand[:, width] != 0)
                ecnt = ehit.sum(dtype=jnp.int32)
                esrc = shrink_indices(ehit, kfin_b)
                erows = jnp.concatenate(
                    [cand[:, width + 5:width + 7],   # parent node key
                     cand[:, width + 3:width + 5]],  # child node key
                    axis=1)[esrc]
                elog = jax.lax.dynamic_update_slice(
                    c.elog, erows, (c.e_n, 0))
                e_n = c.e_n + ecnt
            else:
                elog, e_n = c.elog, c.e_n

            if hist_on:
                # dedup the fresh rows by host-property key against the
                # persistent history table; the queue index of each NEW
                # key's first row is logged for the host's post-chunk
                # pull. Garbage lanes (>= cnt) are masked. On h_ovf the
                # iteration still COMMITS (inserted keys/rows are real;
                # rolling back the big tables would cost a full copy per
                # iteration) — only the unresolved lanes' keys go
                # unlogged, and the host recovers them with a standalone
                # rescan of this chunk's queue span after growing the
                # table (TpuChecker._rescan_history).
                hhi, hlo = fp64_device(n_flat[:, hoff:hoff + hwidth])
                hval = jnp.arange(kfin_b, dtype=jnp.int32) < cnt
                h_ins, hkey_hi, hkey_lo, h_ovf = table_insert(
                    c.hkey_hi, c.hkey_lo, hhi, hlo, hval,
                    max_rounds=h_rounds)
                h_ovf = h_ovf & ~kovf
                hsrc = shrink_indices(h_ins, kfin_b)
                hcnt = h_ins.sum(dtype=jnp.int32)
                hidx = jax.lax.dynamic_update_slice(
                    c.hidx, (c.q_tail + hsrc).astype(jnp.int32),
                    (c.h_n,))
                h_n = c.h_n + hcnt
            else:
                h_ovf = jnp.bool_(False)
                hkey_hi, hkey_lo = c.hkey_hi, c.hkey_lo
                hidx, h_n = c.hidx, c.h_n

            take = jnp.where(kovf, 0, take)
            # generated counts every valid transition (host-engine
            # semantics), not the post-dedup lane count
            vgen = jnp.where(kovf, 0, gen_count)

            # the TWO block appends: queue block = (row | ebits | state
            # fp cache), log block = (dedup key | parent | original) —
            # each one contiguous column slice of the compacted matrix
            q = jax.lax.dynamic_update_slice(
                c.q, n_all[:, :width + 3], (c.q_tail, 0))
            log = jax.lax.dynamic_update_slice(
                c.log, n_all[:, log_off:log_off + c.log.shape[1]],
                (c.log_n, 0))

            return c._replace(
                q=q,
                q_head=c.q_head + take,
                q_tail=c.q_tail + cnt,
                key_hi=key_hi, key_lo=key_lo,
                log=log,
                log_n=c.log_n + cnt,
                hkey_hi=hkey_hi, hkey_lo=hkey_lo, hidx=hidx, h_n=h_n,
                elog=elog, e_n=e_n,
                gen=c.gen + vgen,
                ovf=c.ovf | t_ovf,
                disc_hit=disc_hit, disc_hi=disc_hi, disc_lo=disc_lo,
                kovf=c.kovf | kovf, hovf=c.hovf | h_ovf,
                xovf=c.xovf | exp.xovf,
                steps=c.steps - 1,
                vmax=jnp.maximum(c.vmax, vcount),
                dmax=jnp.maximum(c.dmax, dcount),
                rmax=jnp.maximum(c.rmax, rmax_it),
                # dedup telemetry (kovf iterations committed nothing)
                pdh=c.pdh + jnp.where(kovf, 0, vcount - dcount),
                prb=c.prb + jnp.where(kovf, 0, t_rounds))
        return step

    def make_fused_step(fmax_b: int):
        """The fused analog of ``make_step``: ONE Pallas kernel
        (ops/fused.py) expands, fingerprints, evaluates the property
        predicates (discovery lanes flagged in-register — only the
        per-property sticky registers leave the kernel), pre-dedups
        (against the in-batch arena AND the cross-chunk recent-key
        ring, when ``cc``) and probes the visited table — duplicate
        lanes die inside the kernel, so there is no kraw/kmax candidate
        staging (and no kovf protocol: appends gather the fresh-lane
        mask at the raw F*A width, covered by the fa queue margin).
        Everything after the kernel — the sticky discovery merge, the
        candidate-matrix assembly for the two block appends — is the
        staged code on the kernel's outputs."""
        from ..ops.fused import build_fused_block_fn

        blk = build_fused_block_fn(model, fmax_b, capacity,
                                   symmetry=symmetry, probe=True,
                                   interpret=fused_interpret,
                                   props=bool(prop_count), cc=cc)
        fa_b = fmax_b * n_actions

        def step(state):
            if cc:
                (c, rhi, rlo, cch, target_remaining,
                 grow_limit) = state
            else:
                c, target_remaining, grow_limit = state
                rhi = rlo = None
            sl = jax.lax.dynamic_slice(
                c.q, (c.q_head, 0), (fmax_b, width + 3))
            frontier = sl[:, :width]
            ebits = sl[:, width]
            phi, plo = sl[:, width + 1], sl[:, width + 2]
            take = jnp.minimum(c.q_tail - c.q_head, fmax_b)
            fvalid = jnp.arange(fmax_b, dtype=jnp.int32) < take

            out = blk(frontier, ebits, fvalid, c.key_hi, c.key_lo,
                      pfp=(phi, plo) if prop_count else None,
                      ring=(rhi, rlo) if cc else None)
            vcount = out.cvalid.sum(dtype=jnp.int32)
            dcount = out.dvalid.sum(dtype=jnp.int32)
            cnt = out.inserted.sum(dtype=jnp.int32)

            disc_hit, disc_hi, disc_lo = c.disc_hit, c.disc_hi, c.disc_lo
            if prop_count:
                # in-kernel property eval: the kernel's per-call sticky
                # registers merge into the carry with the same
                # first-hit-wins rule the staged path uses
                keep = disc_hit | ~out.disc_hit
                disc_hi = jnp.where(keep, disc_hi, out.disc_hi)
                disc_lo = jnp.where(keep, disc_lo, out.disc_lo)
                disc_hit = disc_hit | out.disc_hit

            # parent-side columns broadcast along the action axis;
            # assemble_candidates keeps the staged column layout so the
            # queue and log blocks stay the same contiguous slices
            ceb = jnp.repeat(out.ebits, n_actions)
            par_hi = jnp.repeat(phi, n_actions)
            par_lo = jnp.repeat(plo, n_actions)
            cand, log_off = assemble_candidates(
                out.flat, ceb, out.chi, out.clo, par_hi, par_lo,
                out.ohi, out.olo, width, symmetry, False)
            src3 = shrink_indices(out.inserted, fa_b)
            n_all = cand[src3]
            q = jax.lax.dynamic_update_slice(
                c.q, n_all[:, :width + 3], (c.q_tail, 0))
            log = jax.lax.dynamic_update_slice(
                c.log, n_all[:, log_off:log_off + c.log.shape[1]],
                (c.log_n, 0))
            nc = c._replace(
                q=q, q_head=c.q_head + take, q_tail=c.q_tail + cnt,
                key_hi=out.key_hi, key_lo=out.key_lo,
                log=log, log_n=c.log_n + cnt,
                gen=c.gen + vcount,
                ovf=c.ovf | out.ovf,
                disc_hit=disc_hit, disc_hi=disc_hi, disc_lo=disc_lo,
                xovf=c.xovf | out.xovf,
                steps=c.steps - 1,
                vmax=jnp.maximum(c.vmax, vcount),
                dmax=jnp.maximum(c.dmax, dcount),
                # dvalid already excludes ring hits, so the in-batch
                # share is (raw - survivors - ring hits) — keeps
                # predup_hits bit-identical to the staged counter while
                # cc_dedup_hits rides its own stats slot
                pdh=c.pdh + (vcount - dcount - out.cch),
                prb=c.prb + out.rounds)
            if cc:
                return (nc, out.ring_hi, out.ring_lo, cch + out.cch,
                        target_remaining, grow_limit)
            return nc
        return step

    # thin BFS frontiers (a few hundred pending states) are common at the
    # start and tail of every search, and for narrow models they dominate
    # the iteration count; paying the full fmax*max_actions lane width for
    # them wastes most of the machine — so the chunk sequences a small
    # step loop, the large loop, and a tail small loop (see the
    # build_chunk_fn docstring for why sequencing beats an in-loop cond)
    from ..ops.expand import small_step_sizes
    fmax_small, kmax_small, two_size = small_step_sizes(
        fmax, kmax, n_actions)
    fa_small = fmax_small * n_actions
    kraw_small = fmax_small * hint_eff if hint_eff \
        else min(fa_small, kraw)
    if fused:
        step_large = make_fused_step(fmax)
        if two_size:
            step_small = make_fused_step(fmax_small)
    else:
        step_large = make_step(fmax, kraw, kmax)
        if two_size:
            # the small step's raw bound is fa_small itself; its
            # stage-two buffer shrinks with kmax but never below what
            # dedup can survive
            step_small = make_step(fmax_small, kraw_small,
                                   min(kmax_small, kraw_small))

    full_state = bool(fused and cc)

    def make_body(step):
        if full_state:
            return step  # the fused+cc step returns the whole state
        def body(state):
            return (step(state), state[1], state[2])
        return body

    def run_loops(state):
        imax = jnp.int32(2**31 - 1)
        if two_size:
            # outer loop over the [small-loop, large-loop] pair: a
            # frontier oscillating around the knee keeps running until
            # the steps budget (or another exit condition) is spent,
            # instead of ending the chunk at the first re-crossing and
            # paying a host round trip per crossing
            small = (jnp.int32(0), jnp.int32(fmax_small))
            large = (jnp.int32(fmax_small + 1), imax)

            def outer_body(state):
                state = jax.lax.while_loop(
                    make_cond(*small), make_body(step_small), state)
                return jax.lax.while_loop(
                    make_cond(*large), make_body(step_large), state)

            return jax.lax.while_loop(
                make_cond(jnp.int32(0), imax), outer_body, state)
        return jax.lax.while_loop(
            make_cond(jnp.int32(0), imax),
            make_body(step_large), state)

    def base_stats(out):
        # ALL host-read scalars packed into ONE uint32 vector: on a
        # tunneled device every device->host transfer is a round trip
        # (profiler-measured ~10-60 ms each), and a per-leaf device_get
        # of a dozen scalars dominated the whole chunk sync. Layout
        # (tpu.py unpacks positionally — keep in sync):
        # [q_head, q_tail, log_n, gen, ovf, xovf, kovf, h_n, hovf,
        #  vmax, dmax, rmax, e_n, pdh, prb,
        #  disc_hit[P], disc_hi[P], disc_lo[P],
        #  recent queue row (W+3),
        #  then hist window (hist_on) | cc ring hits (fused+cc)]
        # the most recently enqueued state's queue row rides the sync
        # for free (the Explorer decodes it as live progress — the
        # chunk loop has no per-state visitation to sample from)
        recent = out.q[jnp.maximum(out.q_tail - 1, 0)]
        return jnp.concatenate([
            jnp.stack([out.q_head, out.q_tail, out.log_n, out.gen,
                       out.ovf.astype(jnp.int32),
                       out.xovf.astype(jnp.int32),
                       out.kovf.astype(jnp.int32),
                       out.h_n,
                       out.hovf.astype(jnp.int32),
                       out.vmax, out.dmax, out.rmax,
                       out.e_n, out.pdh, out.prb]).astype(jnp.uint32),
            out.disc_hit.astype(jnp.uint32),
            out.disc_hi, out.disc_lo, recent])

    if full_state:
        def chunk_cc(carry: ChunkCarry, ring_hi, ring_lo,
                     target_remaining, grow_limit, h_base):
            # the cross-chunk dedup ring threads OUTSIDE ChunkCarry:
            # adding carry fields would change the STAGED programs'
            # traced signatures and invalidate the persistent compile
            # cache for the whole non-fused matrix (the seed_carry
            # 5-arg caveat, CHANGES.md PR 9). cch (ring hits) is
            # chunk-local telemetry — re-zeroed per dispatch — and
            # rides the stats vector as one trailing element.
            state = run_loops((carry, ring_hi, ring_lo, jnp.int32(0),
                               target_remaining, grow_limit))
            out, rhi, rlo, cch = state[0], state[1], state[2], state[3]
            stats = jnp.concatenate([
                base_stats(out),
                jnp.reshape(cch, (1,)).astype(jnp.uint32)])
            return out, rhi, rlo, stats
        return chunk_cc

    def chunk(carry: ChunkCarry, target_remaining, grow_limit, h_base):
        # h_base anchors the representative window at the host's pulled
        # count (NOT this launch's entry h_n), covering everything the
        # whole small/large loop sequence logged
        state = run_loops((carry, target_remaining, grow_limit))
        out = state[0]
        stats = base_stats(out)
        if not hist_on:
            return out, stats
        # window over the representatives logged this chunk: rides the
        # host's per-chunk sync, so the common case (few fresh distinct
        # histories) needs NO standalone pull dispatch. Overflow beyond
        # HIST_WINDOW falls back to TpuChecker._pull_host_reps. The rows,
        # witness fps AND the scalar stats ride ONE flat vector: every
        # device->host transfer on the tunneled chip costs ~100 ms of
        # latency regardless of size, so a separate window transfer
        # doubled the per-chunk sync cost.
        sel = out.hidx[jnp.minimum(h_base + jnp.arange(HIST_WINDOW),
                                   out.hidx.shape[0] - 1)]
        rows = out.q[jnp.minimum(sel, out.q.shape[0] - 1)][:, :width]
        li = jnp.clip(sel - n_init, 0, out.log.shape[0] - 1)
        win = jnp.concatenate([rows, out.log[li][:, 0:2]], axis=1)
        return out, jnp.concatenate([stats, win.reshape(-1)])

    return chunk


#: representatives returned inline with each chunk's sync; beyond this the
#: host issues a standalone pull for the remainder (rare — distinct
#: host-property keys grow far slower than states)
HIST_WINDOW = 256


_SEED_CACHE = LruCache()


def seed_carry(model, qcap: int, capacity: int, init_rows, full_ebits,
               steps: int = 0, symmetry: bool = False, hcap: int = 0,
               init_fps=None, table_plan=None, ecap: int = 0,
               table=None):
    """Host-side construction of the initial carry (init states enqueued;
    the caller bulk-inserts their fingerprints into the table).
    ``full_ebits`` is a scalar for fresh runs or a per-row array when
    resuming from a checkpointed frontier. ``table`` (a bucket-major
    ``(key_hi, key_lo)`` pair) adopts an EXISTING visited table instead
    of allocating zeros — the spill path re-seeds a fresh epoch around
    the in-place-evicted table without ever pulling its keys to the
    host (mutually exclusive with ``table_plan``).

    The whole construction is ONE jitted dispatch (a dozen separate
    zeros/update dispatches each paid a tunneled-host round trip). The
    engine launches the first chunk with the seed still in flight: the
    round-2/3 measurement that this slowed the loop ~2.5x no longer
    reproduces with the consolidated carry (NOTES.md round 4), and the
    old pre-launch ``block_until_ready`` cost a ~100 ms tunnel round
    trip per run."""
    import numpy as np

    width = model.packed_width
    prop_count = len(model.properties())
    k = len(init_rows)
    assert table is None or table_plan is None, \
        "seed_carry: table= and table_plan= are mutually exclusive"
    kt = 0 if table_plan is None else 1 << max(
        (len(table_plan[1]) - 1).bit_length(), 0)
    adopt = table is not None
    key = (qcap, capacity, width, prop_count, symmetry, k, hcap, kt,
           ecap, adopt)
    fn = _SEED_CACHE.get(key)
    if fn is None:
        logcap = capacity

        # NOTE: the adopt=False program keeps the original 5-parameter
        # signature — threading the (unused) table halves through it
        # would change every seed program's HLO and invalidate the
        # persistent compile cache for the whole non-spill test matrix
        def _build(seed_block, t_idx, t_hi, t_lo, steps_s, khi_in,
                   klo_in):
            q = jnp.zeros((qcap, width + 3), jnp.uint32)
            if k:
                q = jax.lax.dynamic_update_slice(q, seed_block, (0, 0))
            if adopt:
                key_hi, key_lo = khi_in, klo_in
            else:
                key_hi = jnp.zeros((capacity // _BUCKET, _BUCKET),
                                   jnp.uint32)
                key_lo = jnp.zeros((capacity // _BUCKET, _BUCKET),
                                   jnp.uint32)
            if kt:
                # seed the visited table from the host placement plan —
                # part of this single program, no separate dispatch
                key_hi = key_hi.at[t_idx // _BUCKET, t_idx % _BUCKET].set(
                    t_hi, mode="drop")
                key_lo = key_lo.at[t_idx // _BUCKET, t_idx % _BUCKET].set(
                    t_lo, mode="drop")
            return ChunkCarry(
                q=q,
                q_head=jnp.int32(0), q_tail=jnp.int32(k),
                key_hi=key_hi,
                key_lo=key_lo,
                log=jnp.zeros((logcap, 6 if symmetry else 4),
                              jnp.uint32),
                log_n=jnp.int32(0),
                disc_hit=jnp.zeros((prop_count,), bool),
                disc_hi=jnp.zeros((prop_count,), jnp.uint32),
                disc_lo=jnp.zeros((prop_count,), jnp.uint32),
                gen=jnp.int32(0), ovf=jnp.bool_(False),
                xovf=jnp.bool_(False), kovf=jnp.bool_(False),
                steps=steps_s,
                hkey_hi=jnp.zeros((hcap if hcap else 1,), jnp.uint32),
                hkey_lo=jnp.zeros((hcap if hcap else 1,), jnp.uint32),
                hidx=jnp.zeros((logcap if hcap else 1,), jnp.int32),
                h_n=jnp.int32(0), hovf=jnp.bool_(False),
                elog=jnp.zeros((ecap if ecap else 1, 4), jnp.uint32),
                e_n=jnp.int32(0),
                vmax=jnp.int32(0), dmax=jnp.int32(0),
                rmax=jnp.int32(0),
                pdh=jnp.int32(0), prb=jnp.int32(0))

        if adopt:
            fn = jax.jit(_build)
        else:
            def build5(seed_block, t_idx, t_hi, t_lo, steps_s):
                return _build(seed_block, t_idx, t_hi, t_lo, steps_s,
                              None, None)

            fn = jax.jit(build5)
        _SEED_CACHE[key] = fn
    if k:
        init_arr = np.stack(init_rows).astype(np.uint32)
        eb_arr = np.broadcast_to(np.asarray(full_ebits, np.uint32),
                                 (k,)).copy()
        fps = np.asarray(init_fps if init_fps is not None
                         else [0] * k, np.uint64)
        seed_block = np.concatenate(
            [init_arr, eb_arr[:, None],
             (fps >> np.uint64(32)).astype(np.uint32)[:, None],
             fps.astype(np.uint32)[:, None]], axis=1)
    else:
        seed_block = np.zeros((0, width + 3), np.uint32)
    if kt:
        plan, seed_keys = table_plan
        arr = np.zeros((kt,), np.uint64)
        arr[:len(seed_keys)] = np.asarray(seed_keys, np.uint64)
        t_idx = np.full((kt,), capacity, np.int64)  # oob rows dropped
        t_idx[:len(plan)] = np.where(plan >= 0, plan, capacity)
        t_idx = t_idx.astype(np.int32)
        t_hi = (arr >> np.uint64(32)).astype(np.uint32)
        t_lo = arr.astype(np.uint32)
    else:
        t_idx = np.zeros((0,), np.int32)
        t_hi = t_lo = np.zeros((0,), np.uint32)
    if adopt:
        return fn(seed_block, t_idx, t_hi, t_lo, jnp.int32(steps),
                  table[0], table[1])
    return fn(seed_block, t_idx, t_hi, t_lo, jnp.int32(steps))
