"""Per-state visitation hooks.

Reference: ``CheckerVisitor``/``PathRecorder``/``StateRecorder``
(`/root/reference/src/checker/visitor.rs`). Visitors receive the full
:class:`Path` to each evaluated state; ``PathRecorder`` doubles as a validity
oracle because reconstructing an invalid path raises.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Set

from .path import Path


class CheckerVisitor:
    """Applied to every evaluated state's path. Callables also qualify."""

    def visit(self, model, path: Path) -> None:
        raise NotImplementedError


class _FnVisitor(CheckerVisitor):
    def __init__(self, fn: Callable[[Path], None]):
        self._fn = fn

    def visit(self, model, path: Path) -> None:
        self._fn(path)


def as_visitor(v) -> CheckerVisitor:
    if isinstance(v, CheckerVisitor):
        return v
    if callable(v):
        return _FnVisitor(v)
    raise TypeError(f"not a visitor: {v!r}")


class PathRecorder(CheckerVisitor):
    """Records the set of visited paths (`visitor.rs:46-67`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._paths: Set[Path] = set()

    @classmethod
    def new_with_accessor(cls):
        recorder = cls()

        def accessor() -> Set[Path]:
            with recorder._lock:
                return set(recorder._paths)
        return recorder, accessor

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self._paths.add(path)


class StateRecorder(CheckerVisitor):
    """Records evaluated states in visitation order (`visitor.rs:81-100`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._states: List = []

    @classmethod
    def new_with_accessor(cls):
        recorder = cls()

        def accessor() -> List:
            with recorder._lock:
                return list(recorder._states)
        return recorder, accessor

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self._states.append(path.last_state())
