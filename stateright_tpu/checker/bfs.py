"""Host breadth-first search engine.

Replicates the reference BFS semantics (`/root/reference/src/checker/bfs.rs`):
FIFO frontier of ``(state, fingerprint, ebits)``; a ``generated`` map of
fingerprint -> parent fingerprint used both for dedup and for trace
reconstruction by replay (`bfs.rs:314-342`); property evaluation on pop with
early exit once every property has a discovery; ``eventually`` bits flushed
as counterexamples at terminal states. The two documented soundness caveats
for ``eventually`` (ebits not part of the fingerprint, and cycle-vs-DAG-join
ambiguity — `bfs.rs:239-244`, `:249-256`) are replicated by default, so
behavior matches the reference's pinned tests;
``CheckerBuilder.sound_eventually()`` opts into node-keyed dedup that fixes
the first caveat (DAG rejoins).

Symmetry reduction is intentionally *not* applied here: as in the reference,
only the DFS engine honors it.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from ..core import Expectation
from .builder import CheckerBuilder
from .host import HostChecker


class BfsChecker(HostChecker):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        # Dedup-key -> parent dedup-key (None for init states). Keys are
        # state fingerprints; under sound_eventually() they are NODE keys
        # (state fingerprint + pending eventually-bits, ``fp64_node``),
        # with ``_node_fp`` translating back for replay.
        self._generated: Dict[int, Optional[int]] = {}
        model = self._model
        init_states = [s for s in model.init_states()
                       if model.within_boundary(s)]
        self._state_count = len(init_states)
        ebits = self._init_ebits()
        self._init_sound(builder, ebits)
        mask = self._ebits_mask(ebits)
        for s in init_states:
            self._generated.setdefault(
                self._node_key(model.fingerprint(s), mask), None)
        self._unique_state_count = len(self._generated)
        self._pending = deque(
            (s, model.fingerprint(s), ebits) for s in init_states)

    def _run(self) -> None:
        model = self._model
        properties = self._properties
        generated = self._generated
        pending = self._pending
        discoveries = self._discovery_fps
        visitor = self._visitor
        target = self._target_state_count

        trace = self._trace
        pops = 0
        cancelled = self._cancel_event.is_set
        while pending:
            if cancelled():
                break
            state, state_fp, ebits = pending.popleft()
            pops += 1
            if trace and not pops % 4096:
                trace.emit("progress", gen=self._state_count,
                           unique=self._unique_state_count,
                           pending=len(pending))
            # this node's dedup key uses the AT-ENQUEUE bits (dedup
            # happened at enqueue time, before this pop's clearing)
            state_key = self._node_key(state_fp, self._ebits_mask(ebits))
            if visitor is not None:
                visitor.visit(model, self._reconstruct_path(state_key))

            # Property evaluation (bfs.rs:192-226).
            is_awaiting_discoveries = False
            for i, prop in enumerate(properties):
                if prop.name in discoveries:
                    continue
                if prop.expectation == Expectation.ALWAYS:
                    if not prop.condition(model, state):
                        discoveries[prop.name] = state_key
                        self._note_discovery(prop.name, state_key)
                    else:
                        is_awaiting_discoveries = True
                elif prop.expectation == Expectation.SOMETIMES:
                    if prop.condition(model, state):
                        discoveries[prop.name] = state_key
                        self._note_discovery(prop.name, state_key)
                    else:
                        is_awaiting_discoveries = True
                else:  # EVENTUALLY: discoveries only surface at terminals.
                    is_awaiting_discoveries = True
                    if prop.condition(model, state):
                        ebits = ebits - {i}
            if not is_awaiting_discoveries:
                return

            # Expansion (bfs.rs:229-264).
            child_mask = self._ebits_mask(ebits)
            actions: List = []
            is_terminal = True
            model.actions(state, actions)
            for action in actions:
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                self._state_count += 1
                next_fp = model.fingerprint(next_state)
                next_key = self._node_key(next_fp, child_mask)
                if next_key in generated:
                    is_terminal = False
                    continue
                generated[next_key] = state_key
                self._unique_state_count = len(generated)
                is_terminal = False
                pending.append((next_state, next_fp, ebits))
            if is_terminal:
                for i, prop in enumerate(properties):
                    # first discovery wins (the reference's insert-once
                    # flush, `bfs.rs:265-272`): without the guard, a late
                    # terminal whose path skipped ebit-clearing (the
                    # property loop above stops evaluating discovered
                    # properties) overwrites the real witness
                    if i in ebits and prop.name not in discoveries:
                        discoveries[prop.name] = state_key
                        self._note_discovery(prop.name, state_key)
            if target is not None and self._state_count >= target:
                return

