"""Multi-core host DFS: ``spawn_dfs()`` honoring ``threads(n)``.

The reference DFS has the same worker/job-market parallelism as its BFS
(`/root/reference/src/checker/dfs.rs:28-29`, worker loop `dfs.rs:76-159`,
work sharing `dfs.rs:145-157`): threads pop stack jobs, share spare work
when peers idle, and dedup against a shared concurrent set. Python
threads serialize on the GIL, so the host-parallel analog here is
**process workers over stack jobs**:

  * the visited set is a shared-memory open-addressed table of uint64
    fingerprints (linear probing). Probes are lock-free reads; the
    store into an empty slot takes a striped lock and re-checks, so no
    claimed fingerprint is ever lost to a concurrent overwrite. Racing
    workers can still each claim the same state in *different* slots
    and both explore it — the process analog of the reference's benign
    DashSet races ("Races other threads, but that's fine",
    `dfs.rs:210,218,297`); the final unique count deduplicates the
    table contents exactly (``np.unique``). Fingerprint 0 collides
    with the empty-slot sentinel and is remapped to 1 on insert (a
    benign 1-in-2^64 merge, noted at ``_shared_insert``).
  * jobs are lists of DFS stack entries ``(state, fingerprint-path,
    ebits)``; a worker whose local stack grows splits its bottom half
    back to the job queue whenever the queue runs dry — the reference's
    proactive share step (`dfs.rs:145-157`).
  * workers receive the model once, via cloudpickle over a
    ``forkserver`` start (models hold lambdas; the forkserver never
    inherits this process's native threads, so running after an XLA
    engine initialized in-process is safe — unlike ``fork``).

Like the reference's multithreaded runs, which worker wins a discovery
and the total generated count are nondeterministic (duplicate
exploration from insert races adds to ``state_count``); full-enumeration
``unique_state_count`` matches the sequential engine exactly. Symmetry
reduction is supported with the same enqueue-original rule as the
sequential DFS; ``sound_eventually`` and visitors require ``threads(1)``.
"""

from __future__ import annotations

import queue as queue_mod
from typing import Dict, List

import numpy as np

from ..core import Expectation
from .builder import CheckerBuilder
from .host import HostChecker
from .path import Path

#: probes before declaring the shared table full
_MAX_PROBE = 1 << 14
#: expansions between share-step checks
_SHARE_PERIOD = 256
#: striped insert locks (contended only when two workers store into the
#: same stripe at the same instant — inserts happen once per unique state)
_N_STRIPES = 64


def _shared_insert(table, mask: int, fp: int, locks) -> bool:
    """Insert ``fp``; True iff this worker claimed it first.

    Probing is lock-free; the store into an empty slot takes the slot's
    striped lock and re-reads, so a claimed fingerprint can never be
    lost to a concurrent overwrite (two workers that both read a slot
    as empty would otherwise leave only the second store). Two workers
    inserting the SAME fingerprint can still both win — in different
    slots — which is benign duplicate exploration; the master dedups
    the table contents (``np.unique``) for the exact final count.

    Fingerprint 0 is indistinguishable from the empty-slot sentinel and
    is remapped to 1 (hash-table sentinel convention); a real fp-1
    state would merge with it, which is no worse than any other fp64
    collision.
    """
    if fp == 0:
        fp = 1
    i = fp & mask
    for _ in range(_MAX_PROBE):
        v = int(table[i])
        if v == fp:
            return False
        if v == 0:
            with locks[i % _N_STRIPES]:
                v = int(table[i])
                if v == 0:
                    table[i] = fp
                    return True
                if v == fp:
                    return False
            # slot claimed by a different fp while waiting: keep probing
        i = (i + 1) & mask
    raise RuntimeError(
        "shared DFS visited table is full; raise threads-DFS capacity "
        "via tpu_options(host_table_capacity=...) or bound the run with "
        "target_state_count(...)")


def _dfs_worker(payload: bytes, shm_name: str, capacity: int, jobq,
                resq, stop, counter, nworkers: int, locks) -> None:
    """Worker loop: pop a stack job, run DFS on it, share spare work."""
    import cloudpickle
    from multiprocessing import shared_memory

    model, properties, symmetry = cloudpickle.loads(payload)
    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        table = np.ndarray((capacity,), dtype=np.uint64, buffer=shm.buf)
        mask = capacity - 1
        local_disc: set = set()

        def run_job(pending: List) -> int:
            gen = 0
            ticks = 0
            while pending:
                if stop.is_set():
                    return gen
                ticks += 1
                if (ticks % _SHARE_PERIOD == 0 and len(pending) > 2
                        and jobq.qsize() < nworkers):
                    # share step (dfs.rs:145-157): give the bottom of
                    # the stack (shallowest, largest subtrees) away
                    half = pending[:len(pending) // 2]
                    del pending[:len(pending) // 2]
                    with counter.get_lock():
                        counter.value += 1
                    jobq.put(half)
                state, fingerprints, ebits = pending.pop()

                # property evaluation (dfs.rs:204-237)
                for i, prop in enumerate(properties):
                    if prop.name in local_disc:
                        continue
                    if prop.expectation == Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            local_disc.add(prop.name)
                            resq.put(("disc", prop.name,
                                      list(fingerprints)))
                    elif prop.expectation == Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            local_disc.add(prop.name)
                            resq.put(("disc", prop.name,
                                      list(fingerprints)))
                    else:  # EVENTUALLY
                        if prop.condition(model, state):
                            ebits = ebits - {i}

                # expansion (dfs.rs:239-301)
                actions: List = []
                is_terminal = True
                model.actions(state, actions)
                for action in actions:
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    gen += 1
                    is_terminal = False
                    if symmetry is not None:
                        rep_fp = model.fingerprint(symmetry(next_state))
                        next_fp = None
                    else:
                        rep_fp = next_fp = model.fingerprint(next_state)
                    if not _shared_insert(table, mask, rep_fp, locks):
                        continue
                    if next_fp is None:
                        # enqueue-original rule (dfs.rs:266-269)
                        next_fp = model.fingerprint(next_state)
                    pending.append(
                        (next_state, fingerprints + [next_fp], ebits))
                if is_terminal:
                    for i, prop in enumerate(properties):
                        if i in ebits and prop.name not in local_disc:
                            local_disc.add(prop.name)
                            resq.put(("disc", prop.name,
                                      list(fingerprints)))
            return gen

        while not stop.is_set():
            try:
                job = jobq.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            try:
                gen = run_job(job)
                resq.put(("done", gen))
            finally:
                with counter.get_lock():
                    counter.value -= 1
    except Exception as exc:  # surface worker crashes to the master
        resq.put(("error", f"{type(exc).__name__}: {exc}"))
    finally:
        shm.close()


class ParallelDfsChecker(HostChecker):
    """Job-market multi-process DFS (``threads(n)``, n > 1)."""

    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        if builder.visitor_ is not None:
            raise ValueError(
                "per-state visitors require the sequential engine; drop "
                "threads(...) or the visitor")
        if builder.sound_eventually_ and any(
                p.expectation == Expectation.EVENTUALLY
                for p in self._properties):
            raise NotImplementedError(
                "sound_eventually() is not supported by the multi-process "
                "DFS; use threads(1) spawn_dfs")
        self._workers = max(2, builder.thread_count_)
        self._capacity = int(builder.tpu_options_.get(
            "host_table_capacity", 1 << 22))
        assert self._capacity & (self._capacity - 1) == 0, \
            "host_table_capacity must be a power of two"
        self._discovery_fps: Dict[str, List[int]] = {}
        self._generated: set = set()

    def _run(self) -> None:
        import multiprocessing as mp
        from multiprocessing import shared_memory

        import cloudpickle

        model = self._model
        properties = self._properties
        symmetry = self._symmetry
        discoveries = self._discovery_fps
        target = self._target_state_count
        ctx = mp.get_context("forkserver")

        shm = shared_memory.SharedMemory(
            create=True, size=8 * self._capacity)
        procs: List = []
        try:
            table = np.ndarray((self._capacity,), dtype=np.uint64,
                               buffer=shm.buf)
            table[:] = 0
            mask = self._capacity - 1

            locks = [ctx.Lock() for _ in range(_N_STRIPES)]
            init_states = [s for s in model.init_states()
                           if model.within_boundary(s)]
            self._state_count = len(init_states)
            ebits = self._init_ebits()
            entries = []
            for s in init_states:
                fp = model.fingerprint(s)
                rep_fp = (model.fingerprint(symmetry(s))
                          if symmetry is not None else fp)
                if _shared_insert(table, mask, rep_fp, locks):
                    entries.append((s, [fp], ebits))
            self._unique_state_count = len(entries)
            if not properties or not entries:
                return

            payload = cloudpickle.dumps((model, properties, symmetry))
            jobq = ctx.Queue()
            resq = ctx.Queue()
            stop = ctx.Event()
            counter = ctx.Value("i", 0)
            # round-robin the init entries so several workers start busy
            n_jobs = min(len(entries), self._workers)
            jobs: List[List] = [entries[i::n_jobs] for i in range(n_jobs)]
            with counter.get_lock():
                counter.value = len(jobs)
            for job in jobs:
                jobq.put(job)
            for wid in range(self._workers):
                p = ctx.Process(
                    target=_dfs_worker,
                    args=(payload, shm.name, self._capacity, jobq, resq,
                          stop, counter, self._workers, locks),
                    daemon=True)
                p.start()
                procs.append(p)

            while True:
                try:
                    msg = resq.get(timeout=0.05)
                except queue_mod.Empty:
                    msg = None
                if msg is not None:
                    kind = msg[0]
                    if kind == "disc":
                        if msg[1] not in discoveries:
                            discoveries[msg[1]] = msg[2]
                            self._note_discovery(msg[1], msg[2])
                        if len(discoveries) == len(properties):
                            break
                    elif kind == "done":
                        self._state_count += msg[1]
                        self._unique_state_count = int(
                            np.count_nonzero(table))
                        self._metrics.inc("jobs")
                        if self._trace:
                            self._trace.emit(
                                "progress", gen=self._state_count,
                                unique=self._unique_state_count)
                    else:  # error
                        raise RuntimeError(
                            f"DFS worker failed: {msg[1]}")
                if target is not None and self._state_count >= target:
                    break
                with counter.get_lock():
                    done = counter.value == 0
                if done and msg is None:
                    break
            stop.set()
            # drain any last messages (discoveries already in flight)
            while True:
                try:
                    msg = resq.get(timeout=0.05)
                except queue_mod.Empty:
                    break
                if msg[0] == "disc":
                    if msg[1] not in discoveries:
                        discoveries[msg[1]] = msg[2]
                        self._note_discovery(msg[1], msg[2])
                elif msg[0] == "done":
                    self._state_count += msg[1]
                    self._metrics.inc("jobs")
            # exact unique count: racing claims can store a fingerprint
            # in two slots, so the count dedups the table contents. The
            # deduplicated set also backs generated_fingerprints().
            vals = np.unique(table[table != np.uint64(0)])
            self._unique_state_count = int(vals.size)
            self._generated = set(int(v) for v in vals)
        finally:
            try:
                stop.set()
            except Exception:
                pass
            for p in procs:
                p.join(timeout=2.0)
                if p.is_alive():
                    p.terminate()
            shm.close()
            shm.unlink()

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: Path.from_fingerprints(self._model, fps)
            for name, fps in list(self._discovery_fps.items())
        }
