"""Small-model latency: race a budgeted host BFS against the device engine.

The device engine pays fixed costs a tiny model never amortizes — the
XLA dispatch floor, ~100 ms of tunnel latency per device->host transfer,
and buffer seeding (NOTES.md) — so `increment_lock 3` (61 states) took
seconds on `spawn_tpu()` while the host enumerates it in milliseconds.
The reference's `check` subcommand semantics are simply "the spawned
checking run finishes" (`/root/reference/src/checker.rs:116-145`), so
`spawn_tpu()` now spawns BOTH engines and adopts whichever finishes
first:

  * the host racer is BUDGETED (default 1.5 s): small models finish
    well inside it; for big models it cancels itself so the only lasting
    cost is ~one host core for the first moments of a long device run;
  * the loser is cancelled cooperatively (`HostChecker.cancel()`), and a
    cancelled or errored racer is never adopted as a RESULT. A fatal
    device error (e.g. packed-capacity overflow) waits for the budgeted
    host racer: a complete host result wins (the check IS answered);
    the device error surfaces only when the host cannot finish in
    budget — deterministic up to the budget. Runs that must exercise
    the device guards pin ``tpu_options(race=False)`` (or a ``mode``);
  * both engines satisfy the same `Checker` contract, and for full
    enumerations their unique counts/fingerprint sets agree exactly (the
    host BFS is the differential oracle for the device engine), so the
    adopted winner is observationally equivalent. Early-exit
    generated-counts are engine-specific, as with the reference's
    multithreaded runs.

Racing is skipped (pure device engine) whenever the run needs a
device-only or engine-specific feature: a visitor, symmetry reduction,
`sound_eventually`, checkpoint resume/resumable, an explicit
`tpu_options(mode=...)`, or `tpu_options(race=False)` (the Explorer
disables it to introspect the device checker). A mesh run races only
on explicit `race=True` — its device lane is the sharded engine, and
the resilience order is ladder-first: a transient device death
degrades the mesh (D -> D/2 -> single chip, `checker/resilience.py`)
INSIDE the device engine; the un-budgeted host-BFS failover below only
fires once the ladder itself is exhausted.
"""

from __future__ import annotations

import atexit
import threading
import time

from .builder import Checker, CheckerBuilder

#: worker THREADS of cancelled losers (threads only — retaining the
#: checker objects would pin their visited sets/frontiers/device logs
#: for process lifetime); a loser may still be draining a device chunk,
#: and XLA teardown racing a live dispatch aborts the process
#: (observed: "FATAL: exception not rethrown" on exit)
_LOSER_THREADS: list = []


@atexit.register
def _drain_losers() -> None:
    for thread in _LOSER_THREADS:
        if thread is not None and thread.is_alive():
            thread.join(timeout=60.0)
            if thread.is_alive():
                # a silent give-up here left a wedged XLA teardown
                # (e.g. a transfer blocked on a dead tunnel) completely
                # invisible; name the thread so the hang is diagnosable
                import sys
                print(
                    f"stateright_tpu: raced loser thread "
                    f"{thread.name!r} is still alive after a 60s join "
                    "at interpreter exit — XLA teardown appears wedged "
                    "(often a device transfer blocked on a dead "
                    "tunnel); the process may abort instead of exiting "
                    "cleanly", file=sys.stderr)


def _retire(checker) -> None:
    checker.cancel()
    _LOSER_THREADS.append(getattr(checker, "_thread", None))


def race_eligible(builder: CheckerBuilder) -> bool:
    opts = builder.tpu_options_
    # a mesh run only races on explicit race=True: small models never
    # pick a mesh, so the default keeps sharded runs un-raced — but an
    # opted-in raced mesh gets the full degradation ladder (the engine
    # re-shards onto surviving chips) BEFORE the host-BFS failover rung
    return (opts.get("race", True)
            and ("mesh" not in opts or opts.get("race") is True)
            and "mode" not in opts
            and not opts.get("resumable")
            and builder.visitor_ is None
            and builder.symmetry_fn_ is None
            and not builder.sound_eventually_
            and builder.resume_path_ is None)


class RacingChecker(Checker):
    """Adopts the first engine (host BFS vs device) to finish."""

    #: host racer budget: small models finish in milliseconds; anything
    #: that outlives this is device territory. Overridable per run via
    #: ``tpu_options(race_budget=seconds)`` — a model the host would
    #: finish at ~2 s should not get its racer cancelled at the line.
    HOST_BUDGET_S = 1.5

    def __init__(self, builder: CheckerBuilder):
        from .bfs import BfsChecker
        from .tpu import TpuChecker

        self._model = builder.model
        self._builder = builder  # kept for the engine-failover fallback
        budget = builder.tpu_options_.get("race_budget")
        if budget is not None:
            self.HOST_BUDGET_S = float(budget)
        if "mesh" in builder.tpu_options_:
            # explicit race=True on a mesh run (race_eligible): the
            # device lane is the sharded engine, whose degradation
            # ladder runs BEFORE the failover rung below ever applies
            from ..parallel.engine import ShardedTpuChecker
            self._tpu = ShardedTpuChecker(builder)
        else:
            self._tpu = TpuChecker(builder)
        try:
            self._host = BfsChecker(builder)
        except Exception:
            # a model that can't run on the host engine races nothing
            self._host = None
        self._winner = None
        self._failover = False
        self._decided = threading.Event()
        self._decider: threading.Thread | None = None
        self._decider_lock = threading.Lock()

    # ------------------------------------------------------------------
    def _start_background(self) -> None:
        """Start both engines plus the decider thread (non-blocking, so
        ``report()``'s periodic progress lines keep working)."""
        with self._decider_lock:
            if self._decider is None:
                self._tpu._start_background()
                if self._host is not None:
                    self._host._start_background()
                self._decider = threading.Thread(target=self._decide_loop,
                                                 daemon=True)
                self._decider.start()

    def _decide_loop(self) -> None:
        host, tpu = self._host, self._tpu
        tpu_failed = False
        fallback = False  # host is the un-budgeted failover engine
        t0 = time.monotonic()
        while True:
            if host is not None and host._done:
                if host._error is None and not host.cancelled():
                    self._winner = host
                    _retire(tpu)
                    break
                host = None  # disqualified; the device run decides
            if tpu._done and not tpu_failed:
                if tpu._error is None:
                    self._winner = tpu
                    if host is not None:
                        _retire(host)
                    break
                # device run failed (e.g. packed capacity overflow): the
                # budgeted host racer may still deliver a complete,
                # correct result — wait for it; the error surfaces only
                # if the host cannot (deterministic up to the budget)
                tpu_failed = True
            if host is None and tpu._done:
                # engine failover: a TRANSIENT device failure (a dead
                # tunnel, exhausted retries) on a raced run falls back
                # to an UN-budgeted host BFS continuing the check
                # rather than surfacing the backend's error — the
                # check still gets answered, just at host speed.
                # Capacity/programming errors surface as before: the
                # host would either hit the same model bug or silently
                # mask it.
                host = None if fallback else self._spawn_fallback(tpu)
                if host is None:
                    self._winner = tpu  # surfaces the device error
                    break
                fallback = True
                continue
            if (host is not None and not fallback
                    and time.monotonic() - t0 > self.HOST_BUDGET_S):
                _retire(host)
                host = None
            time.sleep(0.002)
        self._decided.set()
        # drop the loser references AFTER publishing the decision, so
        # concurrent progress readers never see a half-decided state;
        # retaining the losers would pin their visited sets / frontiers /
        # device log buffers for the result object's lifetime
        if self._winner is not self._tpu:
            self._tpu = None
        if self._winner is not self._host:
            self._host = None

    def _spawn_fallback(self, tpu):
        """Start the un-budgeted host BFS after a transient device
        failure (``tpu_options(failover=False)`` opts out); returns the
        running checker, or ``None`` when failover does not apply.
        This is the LAST resilience rung: the device engine's own
        degradation ladder (retry -> re-shard onto surviving chips ->
        single chip) has already run inside ``tpu`` by the time its
        error surfaces here."""
        from .resilience import FaultKind, blamed_device, classify_error

        err = tpu._error
        if (err is None
                or not self._builder.tpu_options_.get("failover", True)
                or classify_error(err) is not FaultKind.TRANSIENT):
            return None
        from .bfs import BfsChecker

        try:
            host = BfsChecker(self._builder)
        except Exception:
            return None
        self._failover = True
        if tpu._trace:
            tpu._trace.emit("failover", to="host-bfs",
                            error=f"{type(err).__name__}: {err}",
                            device=blamed_device(err))
        host._start_background()
        return host

    def _decide(self):
        if self._winner is None:
            self._start_background()
            self._decided.wait()
        return self._winner

    # --- Checker interface (decides, then delegates) -------------------
    def join(self) -> "Checker":
        self._decide().join()
        return self

    def is_done(self) -> bool:
        return self._decided.is_set() and self._winner.is_done()

    def model(self):
        return self._model

    def state_count(self) -> int:
        # live progress before a winner exists: the device run's counts
        # (the host racer either wins within its budget or is cancelled)
        if self._decided.is_set():
            return self._winner.state_count()
        tpu = self._tpu
        return tpu.state_count() if tpu is not None else 0

    def unique_state_count(self) -> int:
        if self._decided.is_set():
            return self._winner.unique_state_count()
        tpu = self._tpu
        return tpu.unique_state_count() if tpu is not None else 0

    def profile(self):
        """The WINNING engine's metrics snapshot (keys documented in
        ``stateright_tpu.obs.GLOSSARY``), tagged with which engine won:
        ``engine`` is ``"host"`` for the budgeted host racer,
        ``"device"`` for the device engine. A host win used to report
        ``{}``; now both outcomes carry the winner's real phase
        timers/counters. An engine failover (transient device failure
        adopted by the un-budgeted host fallback) adds
        ``failovers=1``."""
        from .bfs import BfsChecker

        winner = self._decide()
        prof = winner.profile()
        prof["engine"] = ("host" if isinstance(winner, BfsChecker)
                          else "device")
        if self._failover:
            prof["failovers"] = prof.get("failovers", 0) + 1
        return prof

    def discoveries(self):
        return self._decide().discoveries()

    def generated_fingerprints(self):
        return self._decide().generated_fingerprints()

    def error(self):
        return self._decide().error()

    def save(self, path) -> None:
        # tpu_options(resumable=True) disables racing, so a raced run
        # never has a checkpointable frontier regardless of which engine
        # won — surface the same guidance the device engine gives
        raise RuntimeError(
            "save() needs the pending frontier: run with "
            "tpu_options(resumable=True) on the device engine")

    def __getattr__(self, name):
        # engine-specific surface: the winner's (losers are freed on
        # decision), else the not-yet-decided device checker's
        winner = self.__dict__.get("_winner")
        if winner is not None:
            return getattr(winner, name)
        tpu = self.__dict__.get("_tpu")
        if tpu is not None:
            return getattr(tpu, name)
        raise AttributeError(name)
