"""Resilience layer for the device checking engines.

Round 5's primary bench artifact was empty because the TPU backend died
mid-run (``UNAVAILABLE: TPU backend setup/compile error``) *after* the
initial probe succeeded, and nothing between the chunk loop and
``bench.py`` could survive it. Long device runs on tunneled/preemptible
chips fail in ways a single-process host search never does, and each
way deserves a different response:

* **transient backend faults** (``UNAVAILABLE``, ``DEADLINE_EXCEEDED``,
  tunnel/connection resets, a watchdog-expired chunk sync) — the chip or
  its tunnel hiccupped; the productive response is bounded
  retry-with-backoff: re-seed the device buffers from the host-side
  authoritative state (:class:`HostShadow`) and resume;
* **capacity/model errors** (``RESOURCE_EXHAUSTED``, the engines' own
  table/probe/packed-capacity overflows) — retrying reproduces them;
  the user must raise a bound;
* **programming errors** — everything else; surface immediately.

The engines wire this module around chunk dispatch
(``TpuChecker._run_device``, ``ShardedTpuChecker._run``):
``tpu_options(retries=N, backoff=s)`` bounds the retry loop,
``tpu_options(chunk_deadline=s)`` turns a hung device sync into a
classified fault via :func:`call_with_deadline`, and
``tpu_options(autosave=path, autosave_interval=chunks)`` checkpoints
the shadow periodically (and on exhausted retries) through the same
atomic tmp+``os.replace`` write as ``Checker.save``
(:func:`atomic_savez`). Every retry/failover/autosave/watchdog event
flows through the obs layer (``retries``/``failovers``/``autosaves``
metric keys, matching trace events).

Past the retry budget, a sharded run does not die: the **degradation
ladder** (:class:`DegradePolicy`, ``tpu_options(degrade=True,
min_mesh=1)``) halves the mesh onto the surviving power-of-two device
subset — excluding the chip :func:`blamed_device` names, and jumping
the rest of the budget when :class:`FaultAttributor` pins consecutive
faults on one chip — re-routes the shadow's pending frontier by
``owner_of(fp, D/2)``, and resumes; the final rung hands the shadow to
the single-chip device loop. Host BFS (a raced run's failover) and the
checkpoint-and-raise ending are only reached below ``min_mesh``.

:class:`HostShadow` is the piece that makes retry *possible*: with
resilience enabled the host keeps an authoritative copy of everything
needed to rebuild the device state — the (fingerprint -> parent)
mirror, the pending frontier rows (with their at-enqueue ebits and
cached fingerprints), and under ``sound_eventually`` the insert/cross
edge records the post-exhaustion lasso sweep reads. Maintenance costs
one small device gather per chunk (the ``shadow`` phase timer), which
also forfeits most of the double-buffered pipeline's overlap — the
documented price of a run that can outlive its backend.
"""

from __future__ import annotations

import enum
import hashlib
import os
import random
import re
import tempfile
import threading
from typing import Dict, List, Optional

import numpy as np


def _combine64(hi, lo) -> np.ndarray:
    return (np.asarray(hi).astype(np.uint64) << np.uint64(32)) \
        | np.asarray(lo).astype(np.uint64)


# ----------------------------------------------------------------------
# error taxonomy
# ----------------------------------------------------------------------
class FaultKind(enum.Enum):
    """What a runtime error means for the run (README § Resilience)."""

    TRANSIENT = "transient"      # backend/tunnel hiccup: retry
    CAPACITY = "capacity"        # a bound is too small: raise it, rerun
    CORRUPTION = "corruption"    # a chip returned wrong results: quarantine
    PROGRAMMING = "programming"  # a bug: surface immediately


#: lowercase substrings marking a transient backend/tunnel fault. The
#: PJRT status codes (UNAVAILABLE/DEADLINE_EXCEEDED/ABORTED) cover the
#: round-5 failure mode; the connection phrases cover a dropped tunnel
#: surfacing as a raw socket error.
TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted:",
    "connection reset",
    "connection refused",
    "connection aborted",
    "connection closed",
    "broken pipe",
    "socket closed",
    "tunnel",
    "heartbeat",
)

#: lowercase substrings marking a capacity/model error — retrying
#: reproduces these; the fix is a bigger bound (tpu_options(capacity=),
#: hcap=, net_capacity, ...) — or, for the table/allocation subset
#: (SPILLABLE_MARKERS), a spill of the visited set into the host tier.
CAPACITY_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "hash table overflow",
    "probe overflow",
    "capacity overflow",
    "table overflow",
)

#: the capacity subset a visited-set spill can actually relieve: table
#: and allocation pressure. "capacity overflow" is deliberately absent —
#: that is the PACKED-STATE encoding bound (net_capacity and friends;
#: `checker/tpu.py` ``_XOVF_MESSAGE``), which no amount of host-tiering
#: fixes, so it stays terminal.
SPILLABLE_MARKERS = tuple(m for m in CAPACITY_MARKERS
                          if m != "capacity overflow")


class ChunkDeadlineError(RuntimeError):
    """A chunk sync outran ``tpu_options(chunk_deadline=s)`` — a hung
    dispatch reclassified as a transient fault instead of an eternal
    hang (the watchdog; classified TRANSIENT by construction)."""


class CorruptionError(RuntimeError):
    """A chunk audit caught a device lying: the fingerprints it reported
    do not match a deterministic re-execution of the same frontier slice
    (host oracle or a different chip). Silent data corruption never
    *raises* on its own — this error is synthesized by the auditor
    (:class:`AuditPolicy`, ``tpu_options(audit=...)``) so the fault can
    route through the ordinary classification/attribution machinery.
    ``device_index`` names the lying chip (mesh position) for
    :func:`blamed_device`; the message deliberately matches no
    TRANSIENT/CAPACITY marker so :func:`classify_error` reports
    CORRUPTION by type, never by substring accident."""

    def __init__(self, msg: str, device_index: int = 0,
                 mismatches: int = 0):
        super().__init__(msg)
        self.device_index = int(device_index)
        self.mismatches = int(mismatches)


class CandidateOverflowError(RuntimeError):
    """A wedged ``kovf`` protocol: the candidate-buffer resize made no
    progress (the fused/sharded pre-mutation abort would rebuild the
    identical program and abort forever). The message carries a
    :data:`CAPACITY_MARKERS` phrase so :func:`classify_error` reports
    CAPACITY, and the retry envelope recovers by growing the k-buffer
    to its bound and re-seeding, instead of surfacing to the user."""

    def __init__(self, msg: str, vmax: int = 0, dmax: int = 0,
                 bmax: int = 0):
        super().__init__(msg)
        self.vmax, self.dmax, self.bmax = vmax, dmax, bmax


def classify_error(exc: BaseException) -> FaultKind:
    """Classify a runtime error, walking the ``__cause__`` chain so a
    wrapped fault (e.g. the exhausted-retries RuntimeError raised
    ``from`` the original backend error) keeps its classification."""
    seen: set = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if isinstance(e, CorruptionError):
            return FaultKind.CORRUPTION
        if isinstance(e, (ChunkDeadlineError, ConnectionError,
                          TimeoutError)):
            return FaultKind.TRANSIENT
        msg = f"{type(e).__name__}: {e}".lower()
        if any(m in msg for m in TRANSIENT_MARKERS):
            return FaultKind.TRANSIENT
        if any(m in msg for m in CAPACITY_MARKERS):
            return FaultKind.CAPACITY
        e = e.__cause__ if e.__cause__ is not None else e.__context__
    return FaultKind.PROGRAMMING


def find_candidate_overflow(
        exc: BaseException) -> "Optional[CandidateOverflowError]":
    """The :class:`CandidateOverflowError` in ``exc``'s cause chain, if
    any — the retry envelope's capacity branch recovers from one by
    growing the k-buffer instead of evicting table ranges."""
    seen: set = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if isinstance(e, CandidateOverflowError):
            return e
        e = e.__cause__ if e.__cause__ is not None else e.__context__
    return None


def spill_eligible(exc: BaseException) -> bool:
    """Whether a capacity-classified fault is one a visited-set spill
    (HBM -> host tier) can relieve: table/allocation pressure
    (:data:`SPILLABLE_MARKERS`) or a wedged candidate-buffer protocol
    (:class:`CandidateOverflowError`). Packed-state encoding overflows
    (``xovf``) are capacity faults too, but tiering cannot fix a model
    bound — they stay terminal. Walks the cause chain like
    :func:`classify_error`."""
    seen: set = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if isinstance(e, CandidateOverflowError):
            return True
        msg = f"{type(e).__name__}: {e}".lower()
        if "packed-state capacity overflow" in msg:
            return False
        if any(m in msg for m in SPILLABLE_MARKERS):
            return True
        e = e.__cause__ if e.__cause__ is not None else e.__context__
    return False


# ----------------------------------------------------------------------
# retry policy
# ----------------------------------------------------------------------
class RetryPolicy:
    """Bounded retry with jittered exponential backoff.

    ``retries`` is the number of recoveries allowed per consecutive
    fault burst (the attempt counter resets after any successful chunk
    sync, so a long run that hiccups every few minutes keeps going;
    ``retries`` bounds how long the engine beats on a *dead* backend
    before degrading). ``backoff`` is the first delay in seconds; each
    further consecutive attempt doubles it (capped) with +/-25% jitter
    so a fleet of runs sharing one recovering backend does not
    stampede it. ``seed`` (``tpu_options(retry_seed=...)``) pins the
    jitter to a private ``random.Random`` stream so fault-injection
    tests are deterministic across ``PYTHONHASHSEED`` and reruns; the
    default draws from the global RNG (fleet-level decorrelation).
    """

    __slots__ = ("retries", "backoff", "cap", "jitter", "_rng")

    def __init__(self, retries: int = 0, backoff: float = 1.0,
                 cap: float = 30.0, jitter: float = 0.25,
                 seed: Optional[int] = None):
        if retries < 0:
            raise ValueError("tpu_options(retries=...) must be >= 0")
        if backoff < 0:
            raise ValueError("tpu_options(backoff=...) must be >= 0")
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self._rng = random if seed is None else random.Random(seed)

    @classmethod
    def from_options(cls, opts: dict) -> "RetryPolicy":
        seed = opts.get("retry_seed")
        return cls(retries=int(opts.get("retries", 0)),
                   backoff=float(opts.get("backoff", 1.0)),
                   seed=None if seed is None else int(seed))

    @property
    def enabled(self) -> bool:
        return self.retries > 0

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based), jittered."""
        if self.backoff <= 0:
            return 0.0
        base = min(self.backoff * (2.0 ** (attempt - 1)), self.cap)
        return base * (1.0 + self.jitter * (2.0 * self._rng.random() - 1.0))


# ----------------------------------------------------------------------
# per-device fault attribution + the mesh degradation ladder
# ----------------------------------------------------------------------
#: message patterns naming the chip a backend error came from. PJRT
#: status strings usually carry one ("device 3", "TPU_2 heartbeat
#: lost", ...); the injected test faults use the same phrasing.
_DEVICE_PATTERNS = tuple(re.compile(p) for p in (
    r"\bdevice[ _#:]+(\d+)",
    r"\btpu[_ :](\d+)\b",
    r"\bchip[ _#:]+(\d+)",
    r"\bshard[ _#:]+(\d+)",
))


def blamed_device(exc: BaseException) -> Optional[int]:
    """The device index a fault names, or ``None`` when the error is
    not attributable to one chip. Walks the cause chain like
    :func:`classify_error`; an explicit integer ``device_index``
    attribute on any link wins over message parsing."""
    seen: set = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        idx = getattr(e, "device_index", None)
        if isinstance(idx, int) and idx >= 0:
            return idx
        msg = f"{type(e).__name__}: {e}".lower()
        for pat in _DEVICE_PATTERNS:
            m = pat.search(msg)
            if m:
                return int(m.group(1))
        e = e.__cause__ if e.__cause__ is not None else e.__context__
    return None


class FaultAttributor:
    """Consecutive per-device fault attribution across a run.

    ``note(device)`` records one classified transient fault; it returns
    ``True`` when the same chip has been blamed ``blame_after`` times
    in a row — a repeat offender the ladder drops *without* burning the
    rest of the retry budget on it (re-seeding a mesh whose one bad
    chip raises every attempt is pure waste). A successful chunk sync
    or a taken rung calls :meth:`clear` (the streak is consecutive,
    like the retry budget); lifetime per-device totals survive for
    postmortems."""

    __slots__ = ("blame_after", "totals", "_last", "_streak")

    def __init__(self, blame_after: int = 2):
        self.blame_after = max(1, int(blame_after))
        self.totals: Dict[int, int] = {}
        self._last: Optional[int] = None
        self._streak = 0

    def note(self, device: Optional[int]) -> bool:
        if device is None:
            self._last, self._streak = None, 0
            return False
        self.totals[device] = self.totals.get(device, 0) + 1
        if device == self._last:
            self._streak += 1
        else:
            self._last, self._streak = device, 1
        return self._streak >= self.blame_after

    def clear(self) -> None:
        self._last, self._streak = None, 0


def match_device(devices, ref) -> Optional[int]:
    """Resolve one device reference onto a mesh position, or ``None``.

    Shared by ``degrade_step`` and ``promote_step`` (parallel/
    engine.py) so the two directions of the elastic ladder cannot
    drift: a real PJRT fault names the GLOBAL device id; an injected
    one may name the mesh position; a promote grant hands whole
    ``jax.Device`` objects. Matching order — object identity, then the
    global ``.id``, then the bare position fallback."""
    if ref is None:
        return None
    devs = list(devices)
    if not isinstance(ref, int):
        for i, dv in enumerate(devs):
            if dv is ref or dv == ref:
                return i
        ref = getattr(ref, "id", None)
        if not isinstance(ref, int):
            return None
    ids = [getattr(dv, "id", None) for dv in devs]
    if ref in ids:
        return ids.index(ref)
    if 0 <= ref < len(devs):
        return ref
    return None


def select_survivors(devices, new_d: int, *, blamed_pos=None,
                     labels=None) -> list:
    """The width-``new_d`` survivor subset for one ladder rung.

    On a multi-host mesh (``labels`` has more than one distinct value)
    a blamed position takes its whole HOST out first — DCN partitions
    and host deaths fault every chip behind that NIC — so the halved
    mesh stays host-aligned; on one host only the blamed chip is
    dropped. The kept prefix preserves host-major order, which is what
    keeps the ``owner_of(fp, D/2)`` re-route identical to a cross-mesh
    checkpoint resume."""
    devs = list(devices)
    if blamed_pos is not None:
        if labels is not None and len(set(labels)) > 1:
            bad = labels[blamed_pos]
            devs = [dv for dv, h in zip(devs, labels) if h != bad]
        else:
            devs.pop(blamed_pos)
    return devs[:new_d]


def resolve_grant(universe, refs, exclude=()) -> list:
    """Map a promote grant (``jax.Device`` objects, global ids, or
    positions into ``universe``) onto concrete devices, dropping
    duplicates, unresolvable refs, and anything in ``exclude`` (the
    devices the mesh already holds)."""
    universe = list(universe)
    out: list = []
    taken = {id(dv) for dv in exclude}
    for ref in refs:
        pos = match_device(universe, ref)
        if pos is None:
            continue
        dv = universe[pos]
        if id(dv) in taken:
            continue
        taken.add(id(dv))
        out.append(dv)
    return out


class DegradePolicy:
    """The mesh degradation ladder (README § Resilience).

    When the sharded engine exhausts its :class:`RetryPolicy` on a
    transient fault — or :class:`FaultAttributor` pins repeated faults
    on one chip — it re-routes the shadow's pending frontier by
    ``owner_of(fp, D/2)`` onto the surviving power-of-two device
    subset (excluding the blamed chip when known), rebuilds the
    sharded carry, recompiles for the smaller mesh, and resumes:
    D -> D/2 -> ... -> ``min_mesh``. The final single-chip rung runs
    the plain device loop (``TpuChecker._run_device``) seeded from the
    shadow handoff. Only below ``min_mesh`` does the run take the old
    endings (checkpoint-and-raise, or a raced run's host-BFS
    failover). ``tpu_options(degrade=False)`` opts out; ``min_mesh``
    must be a power of two >= 1."""

    __slots__ = ("enabled", "min_mesh", "blame_after")

    def __init__(self, enabled: bool = True, min_mesh: int = 1,
                 blame_after: int = 2):
        min_mesh = int(min_mesh)
        if min_mesh < 1 or (min_mesh & (min_mesh - 1)):
            raise ValueError(
                "tpu_options(min_mesh=...) must be a power of two >= 1 "
                "(the mesh halves rung by rung)")
        self.enabled = bool(enabled)
        self.min_mesh = min_mesh
        self.blame_after = max(1, int(blame_after))

    @classmethod
    def from_options(cls, opts: dict) -> "DegradePolicy":
        return cls(enabled=bool(opts.get("degrade", True)),
                   min_mesh=int(opts.get("min_mesh", 1)),
                   blame_after=int(opts.get("blame_after", 2)))


class SpillPolicy:
    """Visited-set tiering HBM -> host RAM (README § Memory tiering).

    The device table growth protocol quadruples capacity until the
    state space fits; ``tpu_options(max_capacity=N)`` caps that at the
    HBM budget. Once growth would exceed the cap — or an allocation
    raises a spill-eligible capacity fault inside the retry envelope —
    the engines drain the pipeline, evict the coldest
    fingerprint-prefix ranges from the device table into the host tier
    (:class:`HostShadow` already holds every key; eviction just shrinks
    the device-resident hot set), re-seed and resume. Rediscoveries of
    evicted keys are filtered against the host tier during the
    pipeline's host-side process stage, so a capped run enumerates the
    same fingerprint set as an uncapped one.

    ``spill`` (default True) gates eligibility; ``spill_frac`` is the
    fraction of resident keys each spill targets for eviction;
    ``max_spills`` bounds CONSECUTIVE fault-driven spills (reset by any
    successful chunk sync) before the run takes the capacity-terminal
    ending (checkpoint + flight dump + actionable raise)."""

    __slots__ = ("enabled", "max_capacity", "frac", "max_spills")

    def __init__(self, enabled: bool = True,
                 max_capacity: Optional[int] = None, frac: float = 0.5,
                 max_spills: int = 8):
        if max_capacity is not None:
            max_capacity = int(max_capacity)
            if max_capacity < 4 or (max_capacity & (max_capacity - 1)):
                raise ValueError(
                    "tpu_options(max_capacity=...) must be a power of "
                    "two >= 4 (the table quadruples up to it)")
        frac = float(frac)
        if not 0.0 < frac <= 1.0:
            raise ValueError(
                "tpu_options(spill_frac=...) must be in (0, 1]")
        self.enabled = bool(enabled)
        self.max_capacity = max_capacity
        self.frac = frac
        self.max_spills = max(1, int(max_spills))

    @classmethod
    def from_options(cls, opts: dict) -> "SpillPolicy":
        return cls(enabled=bool(opts.get("spill", True)),
                   max_capacity=opts.get("max_capacity"),
                   frac=float(opts.get("spill_frac", 0.5)),
                   max_spills=int(opts.get("max_spills", 8)))

    def can_grow(self, capacity: int) -> bool:
        """Whether quadrupling ``capacity`` stays inside the budget."""
        return self.max_capacity is None \
            or capacity * 4 <= self.max_capacity


# ----------------------------------------------------------------------
# silent-corruption audit (README § Silent corruption defense)
# ----------------------------------------------------------------------
class AuditPolicy:
    """Sampled redundant re-execution of chunk results.

    Every robustness layer above defends against faults that *raise*;
    a chip that silently returns wrong fingerprints completes "green"
    with states unexplored ("Cores that don't count", HotOS'21).
    ``tpu_options(audit=N)`` re-executes every Nth chunk's frontier
    slice — the fingerprints of the freshly appended queue rows — on a
    *different* device (host oracle on single-chip) and compares them
    word-for-word against what the chip claimed; ``audit=frac`` with a
    float in (0, 1] samples that fraction of chunks deterministically;
    ``audit=True`` means every chunk; ``audit=False`` (the default) is
    the unaudited pre-existing engine, bit for bit.

    A mismatch becomes a :class:`CorruptionError` blaming the lying
    chip, the shadow rolls back to the last audited boundary
    (:meth:`HostShadow.audit_mark` / :meth:`HostShadow.rollback_to_mark`
    — corrupt folds since the boundary are undone, so the final digest
    matches an uncorrupted oracle run), and the fault routes down the
    existing ladder: quarantine + degrade on a mesh, re-seed + replay
    on a single chip.

    Sampling caveat (documented, inherent): a chip that lies ONCE
    between two sampled audits goes uncaught — the audit verifies the
    sampled chunk's own rows. A *persistently* lying chip is caught at
    the next sampled chunk and every fold since the last clean audit is
    rolled back. ``audit=1`` closes the gap entirely.
    """

    __slots__ = ("every",)

    def __init__(self, every: int = 0):
        every = int(every)
        if every < 0:
            raise ValueError("tpu_options(audit=...) must be >= 0")
        self.every = every

    @classmethod
    def from_options(cls, opts: dict) -> "AuditPolicy":
        raw = opts.get("audit", False)
        if raw is False or raw is None:
            return cls(0)
        if raw is True:
            return cls(1)
        if isinstance(raw, float):
            if not 0.0 < raw <= 1.0:
                raise ValueError(
                    "tpu_options(audit=...) as a float is a sampling "
                    "fraction and must be in (0, 1]")
            return cls(max(1, round(1.0 / raw)))
        return cls(int(raw))

    @property
    def enabled(self) -> bool:
        return self.every > 0

    def should_audit(self, ordinal: int) -> bool:
        """Whether chunk ``ordinal`` (0-based) is a sampled audit point
        (deterministic — every run audits the same chunks)."""
        return self.every > 0 and ordinal % self.every == 0


_AUDIT_JIT = None


def oracle_fps(rows: np.ndarray, device=None) -> np.ndarray:
    """Independently re-execute the fingerprint of each packed state
    row. With ``device`` the computation runs on THAT chip (the
    cross-device redundant-execution path: a different device re-hashes
    the rows the audited chip produced); without, the host oracle
    (`fingerprint.fp64_rows`, the native C reference) answers. Both are
    bit-identical to the device kernel by the differential-test
    contract, so any disagreement with the claimed fingerprints is the
    audited chip lying, not an oracle artifact."""
    rows = np.ascontiguousarray(rows, np.uint32)
    if device is None:
        from ..fingerprint import fp64_rows
        return np.asarray(fp64_rows(rows), np.uint64)
    global _AUDIT_JIT
    if _AUDIT_JIT is None:
        import jax

        from ..ops.hash_kernel import fp64_device
        _AUDIT_JIT = jax.jit(fp64_device)
    import jax
    hi, lo = _AUDIT_JIT(jax.device_put(rows, device))
    return _combine64(np.asarray(hi), np.asarray(lo))


def audit_chunk_rows(q_new: np.ndarray, log_new: np.ndarray,
                     width: int, *, sound: bool = False,
                     device=None) -> int:
    """Audit one chunk's fresh appends for shard ``s``: re-execute the
    frontier slice's fingerprints (on ``device`` when given, else the
    host oracle) and compare against the two places the chip claimed
    them — the queue rows' fingerprint columns and the insert log's
    child-key columns. Returns the number of mismatching rows (0 =
    clean). Uses only host-resident arrays the shadow fold already
    gathered — auditing adds no extra device pulls."""
    n = len(q_new)
    if n == 0:
        return 0
    q_new = np.asarray(q_new, np.uint32)
    log_new = np.asarray(log_new, np.uint32)
    claimed = _combine64(q_new[:, width + 1], q_new[:, width + 2])
    expect = oracle_fps(q_new[:, :width], device=device)
    bad = claimed != expect
    logged = _combine64(log_new[:, 0], log_new[:, 1])
    if sound:
        # the insert log keys on (state, pending-ebits) NODE identity;
        # re-derive it from the re-executed state fp + at-enqueue ebits
        node_rows = np.stack(
            [expect.astype(np.uint32),
             (expect >> np.uint64(32)).astype(np.uint32),
             q_new[:, width]], axis=1)
        bad |= logged != oracle_fps(node_rows, device=device)
    else:
        bad |= logged != expect
    return int(np.count_nonzero(bad))


# ----------------------------------------------------------------------
# watchdog
# ----------------------------------------------------------------------
def call_with_deadline(fn, deadline: float, what: str = "device sync"):
    """Run ``fn()`` on a watchdog thread; raise :class:`ChunkDeadlineError`
    if it has not returned within ``deadline`` seconds.

    The abandoned call cannot be cancelled (there is no portable way to
    interrupt a blocked PJRT transfer) — the daemon thread is left to
    finish or die with the process; the RUN, however, gets a classified
    transient fault instead of hanging forever."""
    if not deadline or deadline <= 0:
        return fn()
    box: list = []

    def run():
        try:
            box.append(("ok", fn()))
        except BaseException as exc:  # delivered to the caller below
            box.append(("err", exc))

    t = threading.Thread(target=run, daemon=True,
                         name="stateright-tpu-watchdog")
    t.start()
    t.join(deadline)
    if not box:
        raise ChunkDeadlineError(
            f"{what} exceeded tpu_options(chunk_deadline={deadline}) — "
            "treating the hung dispatch as a transient backend fault")
    tag, val = box[0]
    if tag == "err":
        raise val
    return val


# ----------------------------------------------------------------------
# host-side authoritative state
# ----------------------------------------------------------------------
def pack_qrows(rows, ebits, fps, width: int) -> np.ndarray:
    """Host-side queue-row packing: ``[packed row | ebits | fp hi | fp
    lo]`` — the exact layout ``seed_carry``/``seed_sharded_carry`` put
    on device, so the shadow's seed rows match the device's bit for
    bit."""
    k = len(rows)
    out = np.zeros((k, width + 3), np.uint32)
    if not k:
        return out
    out[:, :width] = np.stack([np.asarray(r, np.uint32) for r in rows])
    out[:, width] = np.broadcast_to(np.asarray(ebits, np.uint32), (k,))
    fp_arr = np.asarray([int(f) for f in fps], np.uint64)
    out[:, width + 1] = (fp_arr >> np.uint64(32)).astype(np.uint32)
    out[:, width + 2] = fp_arr.astype(np.uint32)
    return out


#: fingerprint-prefix granularity of the host tier: eviction ranges are
#: buckets of the dedup key's TOP 8 bits. Top bits compose with
#: ``owner_of(fp, D)`` (also top-bit) routing: with D <= 256 every
#: prefix bucket lies entirely inside one shard, so per-shard eviction
#: ranges are owner-consistent by construction and survive mesh
#: halving (adjacent shards merge, adjacent prefix sets merge).
SPILL_PREFIX_BITS = 8


def fp_prefix(fps) -> np.ndarray:
    """The host-tier prefix bucket of each 64-bit dedup key."""
    return (np.asarray(fps, np.uint64)
            >> np.uint64(64 - SPILL_PREFIX_BITS)).astype(np.int64)


_GATHER_JIT = None


def gather_rows(mat, idx: np.ndarray) -> np.ndarray:
    """Pull ``mat[idx]`` to the host through one process-wide jitted
    gather (indices padded to power-of-two buckets so the shape set —
    and thus the retrace count — stays logarithmic)."""
    global _GATHER_JIT
    n = len(idx)
    if n == 0:
        return np.zeros((0,) + tuple(mat.shape[1:]), np.uint32)
    if _GATHER_JIT is None:
        import jax
        import jax.numpy as jnp

        def g(m, i):
            return m[jnp.minimum(i, m.shape[0] - 1)]

        _GATHER_JIT = jax.jit(g)
    bucket = max(16, 1 << (n - 1).bit_length())
    padded = np.zeros((bucket,), np.int32)
    padded[:n] = np.asarray(idx, np.int32)
    return np.asarray(_GATHER_JIT(mat, padded))[:n]


class HostShadow:
    """The host-side authoritative copy of a device run's search state.

    Maintained per chunk while resilience is enabled
    (``tpu_options(retries=..., autosave=...)``); everything a recovery
    or an autosave needs lives here, so a dead backend can never take
    the run's progress with it:

    * the (dedup key -> parent key) mirror is updated incrementally
      (the engine's ``_generated``/``_orig_of`` dicts are shared by
      reference, so path reconstruction and checkpointing see a
      complete mirror without the end-of-run device log pull);
    * the current epoch's queue rows (packed row + at-enqueue ebits +
      cached fingerprint), from which :meth:`pending` rebuilds the
      frontier after a fault — an *epoch* is one device incarnation;
      re-seeding starts a new one from the pending rows;
    * per-shard insert records (log rows + at-enqueue ebits) and cross
      edges, from which the ``sound_eventually`` lasso sweep rebuilds
      the node graph without touching the device.

    Layout invariants leaned on: both engines' queues and logs are
    append-only and append in lockstep (queue row ``n_init_s + i`` is
    log row ``i`` of its shard), and growth passes preserve every
    shard-relative position — so per-chunk gathers of the new suffixes
    reconstruct the device state exactly.

    With :class:`SpillPolicy` tiering active the shadow additionally IS
    the host tier: it tracks which fingerprint-prefix ranges have been
    evicted from the device table (``evicted_prefixes`` — top
    :data:`SPILL_PREFIX_BITS` bits of the dedup key, so ranges compose
    with ``owner_of``'s top-bit shard routing and survive
    :meth:`reshard` down the degradation ladder), a per-prefix
    last-touch clock that :meth:`spill_plan` uses to pick the COLDEST
    ranges, and :meth:`probe_host` — the batched membership check the
    engines run over each chunk's device-"fresh" keys so rediscoveries
    of evicted keys are filtered (and never corrupt the parent mirror)
    before their successors are counted.
    """

    def __init__(self, shards: int, width: int, generated: Dict,
                 orig_of: Dict, translate: bool, sound: bool):
        self.shards = shards
        self.width = width
        self._generated = generated
        self._orig_of = orig_of
        self._translate = translate
        self._sound = sound
        # --- memory tiering (SpillPolicy) -----------------------------
        #: device-evicted fingerprint-prefix buckets (monotone: a prefix
        #: stays evicted once spilled — re-promotion would need the
        #: device table to re-absorb keys the budget just rejected)
        self.evicted_prefixes: set = set()
        #: keys resident ONLY in the host tier at the last spill
        self.host_tier_keys = 0
        #: cumulative rediscoveries filtered against the host tier
        self.host_probe_hits = 0
        self._heat = np.zeros((1 << SPILL_PREFIX_BITS,), np.int64)
        self._clock = 0
        self._roots: List[int] = []   # first-epoch dedup keys (lasso)
        self._first_epoch = True
        # --- silent-corruption defense (AuditPolicy) ------------------
        #: running chunk-digest head: sha256 folded over each chunk's
        #: reported child keys in fold order — the provenance anchor the
        #: artifact integrity chain binds checkpoints/results to
        self.chain_head = hashlib.sha256(b"stateright-tpu").hexdigest()
        #: set by the engines when ``tpu_options(audit=...)`` is on;
        #: gates the mark/rollback bookkeeping so the unaudited default
        #: path stays zero-cost
        self.audit_enabled = False
        self._mark: Optional[tuple] = None
        self._mark_keys: List[int] = []
        # cumulative across epochs (the lasso sweep's inputs)
        self._inserts: List[List[tuple]] = [[] for _ in range(shards)]
        self._edges: List[List[np.ndarray]] = [[] for _ in range(shards)]
        # current-epoch queue state
        self._epoch_q: List[List[np.ndarray]] = [[] for _ in range(shards)]
        self._heads = [0] * shards
        self._tails = [0] * shards
        self.log_n = [0] * shards  # epoch-local committed log counts
        self.e_n = [0] * shards    # epoch-local committed edge counts

    # ------------------------------------------------------------------
    def seed_epoch(self, per_shard_rows: List[np.ndarray]) -> None:
        """Start a device epoch: ``per_shard_rows[s]`` are shard ``s``'s
        seed queue rows (``pack_qrows`` layout) in device queue order."""
        assert len(per_shard_rows) == self.shards
        self._epoch_q = [[np.asarray(r, np.uint32)] if len(r) else []
                         for r in per_shard_rows]
        self._heads = [0] * self.shards
        self._tails = [len(r) for r in per_shard_rows]
        self.log_n = [0] * self.shards
        self.e_n = [0] * self.shards
        if self._first_epoch:
            self._first_epoch = False
            if self._sound:
                from ..fingerprint import fp64_node
            for r in per_shard_rows:
                for j in range(len(r)):
                    fp = int(_combine64(r[j, self.width + 1],
                                        r[j, self.width + 2]))
                    self._roots.append(
                        fp64_node(fp, int(r[j, self.width]))
                        if self._sound else fp)
        if self.audit_enabled:
            self.audit_mark()

    def note_chunk(self, s: int, q_new: np.ndarray, log_new: np.ndarray,
                   elog_new: Optional[np.ndarray], q_head: int) -> int:
        """Fold one chunk's per-shard appends in (queue rows and log
        rows are the lockstep suffixes; counts must match). Returns the
        number of device-"fresh" keys the host tier recognized as
        rediscoveries (0 while no ranges are evicted) — those keys'
        mirror entries are left untouched, so a rediscovery can never
        rewrite a parent chain into a cycle."""
        n = len(log_new)
        assert len(q_new) == n, (len(q_new), n)
        hits = 0
        if n:
            q_new = np.asarray(q_new, np.uint32)
            log_new = np.asarray(log_new, np.uint32)
            self._epoch_q[s].append(q_new)
            self._tails[s] += n
            self.log_n[s] += n
            self._inserts[s].append((log_new, q_new[:, self.width]))
            child = _combine64(log_new[:, 0], log_new[:, 1])
            parent = _combine64(log_new[:, 2], log_new[:, 3])
            self.chain_head = hashlib.sha256(
                self.chain_head.encode() + child.tobytes()).hexdigest()
            # per-prefix last-touch clock: newly inserted children mark
            # their ranges hot, and so do the parents being expanded —
            # the ranges dedup is currently hitting are the ones NOT to
            # evict
            self._clock += 1
            self._heat[np.unique(np.concatenate(
                (fp_prefix(child), fp_prefix(parent))))] = self._clock
            pairs = zip(child.tolist(), parent.tolist())
            if self.evicted_prefixes:
                # host-tier re-probe: with eviction active a device-
                # "fresh" key may be a rediscovery (its range was
                # evicted, or bucket compaction opened an earlier slot);
                # only genuinely fresh keys enter the mirror
                g = self._generated
                fresh = [(c, p) for c, p in pairs if c not in g]
                hits = n - len(fresh)
                self.host_probe_hits += hits
                self.host_tier_keys = max(0, self.host_tier_keys - hits)
                pairs = fresh
                if self._mark is not None:
                    self._mark_keys.extend(c for c, _p in fresh)
                g.update(pairs)
            else:
                if self._mark is not None:
                    pairs = list(pairs)
                    self._mark_keys.extend(c for c, _p in pairs)
                self._generated.update(pairs)
            if self._translate:
                orig = _combine64(log_new[:, 4], log_new[:, 5])
                if self.evicted_prefixes:
                    keep = {c for c, _p in pairs}
                    self._orig_of.update(
                        (c, o) for c, o in zip(child.tolist(),
                                               orig.tolist())
                        if c in keep)
                else:
                    self._orig_of.update(zip(child.tolist(),
                                             orig.tolist()))
        if elog_new is not None and len(elog_new):
            self._edges[s].append(np.asarray(elog_new, np.uint32))
            self.e_n[s] += len(elog_new)
        self._heads[s] = int(q_head)
        return hits

    # --- silent-corruption defense (AuditPolicy) ----------------------
    def audit_mark(self) -> None:
        """Pin the current fold position as the last audited boundary.
        Called after every PASSED audit (and at each epoch seed), so
        :meth:`rollback_to_mark` can undo everything a lying chip
        folded in since the last point the oracle vouched for."""
        self._mark = (list(self._heads), list(self._tails),
                      list(self.log_n), list(self.e_n),
                      [len(p) for p in self._inserts],
                      [len(p) for p in self._edges],
                      self.chain_head, self.host_probe_hits,
                      self.host_tier_keys)
        self._mark_keys = []

    def rollback_to_mark(self) -> int:
        """Undo every fold since :meth:`audit_mark`: mirror entries,
        queue appends, insert/edge records, head positions and the
        chain head all return to the audited boundary, so the replay
        re-expands the same frontier on trustworthy silicon and the
        final digest matches an uncorrupted run. Returns the number of
        mirror keys undone (0 when no mark is pinned)."""
        if self._mark is None:
            return 0
        (heads, tails, log_n, e_n, ins_len, edg_len,
         chain, probe_hits, tier_keys) = self._mark
        for k in self._mark_keys:
            self._generated.pop(k, None)
            self._orig_of.pop(k, None)
        undone = len(self._mark_keys)
        self._mark_keys = []
        for s in range(self.shards):
            rows = self._epoch_rows(s)
            self._epoch_q[s] = [rows[:tails[s]]] if tails[s] else []
            del self._inserts[s][ins_len[s]:]
            del self._edges[s][edg_len[s]:]
        self._heads = list(heads)
        self._tails = list(tails)
        self.log_n = list(log_n)
        self.e_n = list(e_n)
        self.chain_head = chain
        self.host_probe_hits = probe_hits
        self.host_tier_keys = tier_keys
        return undone

    # --- memory tiering (SpillPolicy) ---------------------------------
    @property
    def spill_active(self) -> bool:
        return bool(self.evicted_prefixes)

    def is_evicted(self, key: int) -> bool:
        return (int(key) >> (64 - SPILL_PREFIX_BITS)) \
            in self.evicted_prefixes

    def hot_keys(self) -> List[int]:
        """The device-resident hot set: every mirrored dedup key whose
        prefix range has not been evicted — what a post-fault re-seed
        (or a degradation rung) re-inserts into the device table."""
        if not self.evicted_prefixes:
            return list(self._generated.keys())
        shift = 64 - SPILL_PREFIX_BITS
        ev = self.evicted_prefixes
        return [k for k in self._generated if (k >> shift) not in ev]

    def probe_host(self, fps) -> np.ndarray:
        """Batched host-tier membership: ``mask[i]`` is True when
        ``fps[i]`` is already in the authoritative mirror (a duplicate
        the device table could no longer see)."""
        g = self._generated
        return np.fromiter((int(f) in g for f in np.asarray(fps)),
                           bool, len(fps))

    def spill_plan(self, hot_budget: int):
        """Pick the coldest not-yet-evicted prefix ranges until the
        projected device-resident key count fits ``hot_budget``.

        Returns ``(new_prefixes, hot_count, evicted_now)`` — the ranges
        to evict now (possibly empty when everything over budget is
        already evicted), the resulting hot-set size, and the number of
        mirrored keys those new ranges move to the host tier — or
        ``None`` when no plan can shrink the hot set below the budget
        (host tier exhausted in the only sense that matters: eviction
        cannot make more room)."""
        keys = np.fromiter((int(k) for k in self._generated), np.uint64,
                           len(self._generated))
        counts = np.bincount(fp_prefix(keys),
                             minlength=1 << SPILL_PREFIX_BITS)
        resident = [p for p in range(1 << SPILL_PREFIX_BITS)
                    if counts[p] and p not in self.evicted_prefixes]
        hot = int(sum(counts[p] for p in resident))
        new: List[int] = []
        evicted_now = 0
        # coldest first: oldest last-touch clock, prefix as tiebreak
        for p in sorted(resident, key=lambda p: (self._heat[p], p)):
            if hot <= hot_budget:
                break
            new.append(p)
            hot -= int(counts[p])
            evicted_now += int(counts[p])
        if hot > hot_budget:
            return None
        self.evicted_prefixes.update(new)
        self.host_tier_keys = int(
            sum(int(counts[p]) for p in self.evicted_prefixes
                if p < len(counts)))
        return new, hot, evicted_now

    def reshard(self, shards: int) -> None:
        """Re-partition for a new mesh width (the degradation ladder).

        The live pending frontier is preserved — concatenated into the
        first slot so :meth:`pending` keeps answering until the caller
        re-routes it and starts the next epoch with :meth:`seed_epoch`.
        The cumulative insert/edge records just re-bucket (the lasso
        sweep merges across shards anyway); roots and the shared
        mirror dicts are untouched."""
        live = [self._epoch_rows(s)[self._heads[s]:self._tails[s]]
                for s in range(self.shards)]
        live_rows = (np.concatenate(live) if live
                     else np.zeros((0, self.width + 3), np.uint32))
        old_inserts, old_edges = self._inserts, self._edges
        self.shards = shards
        self._inserts = [[] for _ in range(shards)]
        self._edges = [[] for _ in range(shards)]
        for s, parts in enumerate(old_inserts):
            self._inserts[s % shards].extend(parts)
        for s, parts in enumerate(old_edges):
            self._edges[s % shards].extend(parts)
        self._epoch_q = [[] for _ in range(shards)]
        self._heads = [0] * shards
        self._tails = [0] * shards
        if len(live_rows):
            self._epoch_q[0] = [live_rows]
            self._tails[0] = len(live_rows)
        self.log_n = [0] * shards
        self.e_n = [0] * shards

    # ------------------------------------------------------------------
    def _epoch_rows(self, s: int) -> np.ndarray:
        parts = self._epoch_q[s]
        if not parts:
            return np.zeros((0, self.width + 3), np.uint32)
        if len(parts) > 1:
            self._epoch_q[s] = parts = [np.concatenate(parts)]
        return parts[0]

    def pending(self):
        """The live frontier — ``(rows, ebits, fps)`` concatenated in
        shard order — from which a recovery (or an autosave checkpoint)
        re-seeds a fresh device incarnation."""
        rows_l, eb_l, fp_l = [], [], []
        for s in range(self.shards):
            allq = self._epoch_rows(s)
            live = allq[self._heads[s]:self._tails[s]]
            rows_l.append(live[:, :self.width])
            eb_l.append(live[:, self.width])
            fp_l.append(_combine64(live[:, self.width + 1],
                                   live[:, self.width + 2]))
        return (np.concatenate(rows_l) if rows_l
                else np.zeros((0, self.width), np.uint32),
                np.concatenate(eb_l) if eb_l
                else np.zeros((0,), np.uint32),
                np.concatenate(fp_l) if fp_l
                else np.zeros((0,), np.uint64))

    def root_keys(self) -> List[int]:
        """First-epoch seed dedup keys (the lasso sweep's roots)."""
        return list(self._roots)

    def insert_block(self, s: int):
        """Shard ``s``'s cumulative insert records as ``(log_rows,
        ebits)`` arrays (the lasso sweep's ``add_log_block`` inputs)."""
        parts = self._inserts[s]
        if not parts:
            return None
        return (np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]))

    def edge_block(self, s: int) -> np.ndarray:
        parts = self._edges[s]
        if not parts:
            return np.zeros((0, 4), np.uint32)
        return np.concatenate(parts)


# ----------------------------------------------------------------------
# artifact integrity chain (checkpoints, autosaves, result.json)
# ----------------------------------------------------------------------
#: the previous autosave generation's suffix: `<path>` is always the
#: NEWEST loadable checkpoint (g0 — what `resume_from(path)` reads and
#: every pre-existing test pins), `<path>.g1` the one before it. A
#: corrupt or truncated `<path>` rolls back one generation on resume.
AUTOSAVE_PREV_SUFFIX = ".g1"


def payload_digest(arrays: Dict[str, np.ndarray]) -> str:
    """Deterministic sha256 over a checkpoint payload: sorted array
    names with dtype, shape and raw bytes — what the integrity chain
    signs, independent of npz compression details."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def chain_integrity(payload_sha: str, chain_head: str) -> str:
    """The integrity field an artifact carries: its payload sha256
    chained to the run's chunk-digest head at write time, so a
    tampered/corrupt payload AND a payload transplanted from a
    different run history both fail verification."""
    return hashlib.sha256(
        (payload_sha + ":" + chain_head).encode()).hexdigest()


# ----------------------------------------------------------------------
# crash-safe checkpoint write (shared by save() and autosave)
# ----------------------------------------------------------------------
def atomic_savez(path, **arrays) -> None:
    """``np.savez_compressed`` into a temp file in the target directory,
    fsync, then ``os.replace`` into place — an interrupted write
    (SIGKILL, full disk, a dying host) can never leave a truncated file
    where a good checkpoint stood. The file object (not a path) keeps
    numpy from appending its own ``.npz`` suffix."""
    path = os.fspath(path)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
