"""The TPU-native checking engine: ``CheckerBuilder.spawn_tpu()``.

Re-design of the reference's BFS hot loop (`/root/reference/src/checker/bfs.rs:165-274`)
for the XLA compilation model. Instead of threads popping one state at a time
from a shared deque with DashMap dedup, the frontier is a device-resident
batch of packed states and one jitted *level step* fuses everything the
reference does per state:

  * property evaluation (always/sometimes masks + eventually-bit clearing)
    via the model's vmapped ``packed_properties`` — fused into the step, no
    host round-trip per state;
  * expansion via vmapped ``packed_step`` (the action axis is the
    nondeterminism axis; disabled actions, no-op transitions and boundary
    violations are mask bits, mirroring ``next_state -> None`` pruning);
  * fingerprinting via the device hash kernel (`ops/hash_kernel.py`);
  * visited-set dedup via batched parallel insert into an HBM-resident
    open-addressed table (`ops/hashtable.py`).

The host orchestrates: it pulls per-level masks/fingerprints (small), keeps
the (fingerprint -> parent-fingerprint) mirror used for trace reconstruction
by replay (the TLC technique, `bfs.rs:314-342`), records discoveries, and
builds the next frontier by index-gather on device — packed states never
round-trip to the host.

Semantic differences vs the host engines (both documented and benign):
  * work granularity is a frontier segment, not a single state, so
    ``state_count``/``unique_state_count`` may exceed the host engines'
    values on early-exit runs (the reference's own multithreaded runs are
    similarly nondeterministic); full-enumeration unique counts match
    exactly;
  * which duplicate within a batch wins a slot (and thus which parent a
    state records) is unspecified — the reference tolerates the same benign
    DashMap race (`bfs.rs:198,206,268`).

The ``eventually`` semantics replicate the reference's documented caveats
(`bfs.rs:239-256`): ebits ride per-frontier-row (bit i = property i not yet
satisfied on this path), are not part of the fingerprint, and joins/cycles
are not treated as terminal.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..core import Expectation
from .builder import CheckerBuilder
from .host import HostChecker
from .path import Path

_MIN_BUCKET = 16


def _next_pow2(n: int) -> int:
    return 1 << max((n - 1).bit_length(), 0)


def _bucket(n: int) -> int:
    return max(_MIN_BUCKET, _next_pow2(n))


class TpuChecker(HostChecker):
    """Level-synchronous device BFS over a packed model."""

    def __init__(self, builder: CheckerBuilder):
        model = builder.model
        for attr in ("packed_width", "max_actions", "encode", "packed_step",
                     "packed_properties"):
            if not hasattr(model, attr):
                raise TypeError(
                    f"spawn_tpu() requires a PackedModel (missing {attr!r}); "
                    "see stateright_tpu.models.packed.PackedModel. Host-only "
                    "models can use spawn_bfs()/spawn_dfs().")
        super().__init__(builder)
        opts = builder.tpu_options_
        self._capacity = int(opts.get("capacity", 1 << 20))
        assert self._capacity & (self._capacity - 1) == 0, \
            "capacity must be a power of two"
        self._max_segment = int(opts.get("max_segment", 1 << 15))
        self._grow_at = float(opts.get("grow_at", 0.55))
        # fingerprint -> parent fingerprint mirror (host side; the
        # checkpointable search record, also used for path reconstruction).
        self._generated: Dict[int, Optional[int]] = {}
        if builder.symmetry_fn_ is not None:
            raise NotImplementedError(
                "symmetry reduction on the TPU engine requires a packed "
                "canonicalization; use spawn_dfs() for symmetry or provide "
                "packed_representative (planned).")

    # ------------------------------------------------------------------
    def _run(self) -> None:
        import jax
        import jax.numpy as jnp

        from ..ops.hash_kernel import fp64_device
        from ..ops.hashtable import make_table, table_insert

        model = self._model
        properties = self._properties
        prop_count = len(properties)
        width = model.packed_width
        n_actions = model.max_actions
        eventually_idx = [i for i, p in enumerate(properties)
                         if p.expectation == Expectation.EVENTUALLY]
        full_ebits = np.uint32(sum(1 << i for i in eventually_idx))
        generated = self._generated
        discoveries = self._discovery_fps
        target = self._target_state_count
        visitor = self._visitor

        # --- jitted level step -----------------------------------------
        def level_fn(frontier, fvalid, ebits, key_hi, key_lo):
            pbits = jax.vmap(model.packed_properties)(frontier)  # [F, P]
            if eventually_idx:
                sat_bits = jnp.zeros(
                    (frontier.shape[0],), dtype=jnp.uint32)
                for i in eventually_idx:
                    sat_bits = sat_bits | jnp.where(
                        pbits[:, i], jnp.uint32(1 << i), jnp.uint32(0))
                ebits = ebits & ~sat_bits
            succ, avalid = jax.vmap(model.packed_step)(frontier)
            avalid = avalid & fvalid[:, None]
            flat = succ.reshape((-1, width))
            fhi, flo = fp64_device(flat)
            phi, plo = fp64_device(frontier)
            inserted, key_hi, key_lo, overflow = table_insert(
                key_hi, key_lo, fhi, flo, avalid.reshape(-1))
            terminal = fvalid & ~avalid.any(axis=1)
            gen_count = avalid.sum(dtype=jnp.int32)
            return (key_hi, key_lo, flat, inserted, fhi, flo, phi, plo,
                    pbits, ebits, terminal, gen_count, overflow)

        level_fn = jax.jit(level_fn)

        def gather_fn(flat, ebits_new, idx):
            return flat[idx], ebits_new[idx // n_actions]

        gather_fn = jax.jit(gather_fn)

        insert_fn = jax.jit(table_insert)

        # --- init -------------------------------------------------------
        init_states = [s for s in model.init_states()
                       if model.within_boundary(s)]
        self._state_count = len(init_states)
        init_rows: List[np.ndarray] = []
        for s in init_states:
            fp = model.fingerprint(s)
            if fp not in generated:
                generated[fp] = None
                init_rows.append(model.encode(s))
        self._unique_state_count = len(generated)

        key_hi, key_lo = make_table(self._capacity)
        key_hi, key_lo = self._bulk_insert(
            insert_fn, key_hi, key_lo, list(generated.keys()))

        segments: deque = deque()
        for start in range(0, len(init_rows), self._max_segment):
            chunk = init_rows[start:start + self._max_segment]
            fcount = len(chunk)
            bucket = _bucket(fcount)
            rows = np.zeros((bucket, width), dtype=np.uint32)
            rows[:fcount] = np.stack(chunk)
            fvalid = np.arange(bucket) < fcount
            ebits = np.full((bucket,), full_ebits, dtype=np.uint32)
            segments.append((jnp.asarray(rows), jnp.asarray(fvalid),
                             jnp.asarray(ebits)))

        # --- search loop ------------------------------------------------
        while segments:
            if len(discoveries) == prop_count:
                return
            frontier, fvalid, ebits = segments.popleft()
            (key_hi, key_lo, flat, inserted_d, fhi_d, flo_d, phi_d, plo_d,
             pbits_d, ebits_d, terminal_d, gen_count_d, overflow_d) = \
                level_fn(frontier, fvalid, ebits, key_hi, key_lo)
            (inserted, fhi, flo, phi, plo, pbits, ebits_np, terminal,
             gen_count, overflow, fvalid_np) = jax.device_get(
                (inserted_d, fhi_d, flo_d, phi_d, plo_d, pbits_d, ebits_d,
                 terminal_d, gen_count_d, overflow_d, fvalid))
            if overflow:
                raise RuntimeError(
                    "device hash table overflow (capacity "
                    f"{self._capacity}); raise via "
                    "checker_builder.tpu_options(capacity=...)")

            self._state_count += int(gen_count)
            frontier_fps = (phi.astype(np.uint64) << np.uint64(32)) \
                | plo.astype(np.uint64)
            child_fps = (fhi.astype(np.uint64) << np.uint64(32)) \
                | flo.astype(np.uint64)

            if visitor is not None:
                for k in np.nonzero(fvalid_np)[0]:
                    visitor.visit(
                        model, self._reconstruct_path(int(frontier_fps[k])))

            # discoveries: always/sometimes on the evaluated frontier rows
            for i, prop in enumerate(properties):
                if prop.name in discoveries:
                    continue
                if prop.expectation == Expectation.ALWAYS:
                    mask = fvalid_np & ~pbits[:, i]
                elif prop.expectation == Expectation.SOMETIMES:
                    mask = fvalid_np & pbits[:, i]
                else:
                    continue
                hits = np.nonzero(mask)[0]
                if hits.size:
                    discoveries[prop.name] = int(frontier_fps[hits[0]])
            # eventually: flushed at terminal rows with bits remaining
            if eventually_idx:
                term_hits = np.nonzero(
                    fvalid_np & terminal & (ebits_np != 0))[0]
                for k in term_hits:
                    bits = int(ebits_np[k])
                    for i in eventually_idx:
                        if bits & (1 << i) and \
                                properties[i].name not in discoveries:
                            discoveries[properties[i].name] = \
                                int(frontier_fps[k])

            # mirror the newly inserted (fingerprint, parent) pairs
            new_idx = np.nonzero(inserted)[0]
            for k in new_idx:
                generated[int(child_fps[k])] = \
                    int(frontier_fps[k // n_actions])
            self._unique_state_count = len(generated)

            if len(discoveries) == prop_count:
                return
            if target is not None and self._state_count >= target:
                return

            # grow the table before it saturates
            if len(generated) > self._grow_at * self._capacity:
                self._capacity *= 4
                key_hi, key_lo = make_table(self._capacity)
                key_hi, key_lo = self._bulk_insert(
                    insert_fn, key_hi, key_lo, list(generated.keys()))

            # next frontier segments: device gather of winner rows
            for start in range(0, len(new_idx), self._max_segment):
                group = new_idx[start:start + self._max_segment]
                bucket = _bucket(len(group))
                idx = np.zeros((bucket,), dtype=np.int32)
                idx[:len(group)] = group
                new_fvalid = np.arange(bucket) < len(group)
                rows, eb = gather_fn(flat, ebits_d, jnp.asarray(idx))
                segments.append((rows, jnp.asarray(new_fvalid), eb))

    # ------------------------------------------------------------------
    def _bulk_insert(self, insert_fn, key_hi, key_lo, fps: List[int]):
        """(Re)insert known fingerprints, e.g. at init or after growth."""
        import jax.numpy as jnp
        chunk_size = 1 << 16
        for start in range(0, len(fps), chunk_size):
            chunk = fps[start:start + chunk_size]
            n = _bucket(len(chunk))
            arr = np.zeros((n,), dtype=np.uint64)
            arr[:len(chunk)] = np.asarray(chunk, dtype=np.uint64)
            valid = np.arange(n) < len(chunk)
            _, key_hi, key_lo, overflow = insert_fn(
                key_hi, key_lo,
                jnp.asarray((arr >> np.uint64(32)).astype(np.uint32)),
                jnp.asarray(arr.astype(np.uint32)),
                jnp.asarray(valid))
            if bool(overflow):
                raise RuntimeError(
                    "device hash table overflow during bulk insert")
        return key_hi, key_lo

    def _reconstruct_path(self, fp: int) -> Path:
        fingerprints: deque = deque()
        next_fp = fp
        while next_fp in self._generated:
            parent = self._generated[next_fp]
            fingerprints.appendleft(next_fp)
            if parent is None:
                break
            next_fp = parent
        return Path.from_fingerprints(self._model, fingerprints)

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: self._reconstruct_path(fp)
            for name, fp in list(self._discovery_fps.items())
        }
