"""The TPU-native checking engine: ``CheckerBuilder.spawn_tpu()``.

Re-design of the reference's BFS hot loop (`/root/reference/src/checker/bfs.rs:165-274`)
for the XLA compilation model. Instead of threads popping one state at a time
from a shared deque with DashMap dedup, the frontier is a device-resident
batch of packed states and one jitted *level step* fuses everything the
reference does per state:

  * property evaluation (always/sometimes masks + eventually-bit clearing)
    via the model's vmapped ``packed_properties`` — fused into the step, no
    host round-trip per state;
  * expansion via vmapped ``packed_step`` (the action axis is the
    nondeterminism axis; disabled actions, no-op transitions and boundary
    violations are mask bits, mirroring ``next_state -> None`` pruning);
  * fingerprinting via the device hash kernel (`ops/hash_kernel.py`);
  * visited-set dedup via batched parallel insert into an HBM-resident
    open-addressed table (`ops/hashtable.py`);
  * **compaction**: newly inserted children are scatter-compacted into a
    dense buffer that directly becomes the next frontier — packed states
    never round-trip to the host, and the host pulls only 16 bytes per new
    state (its fingerprint and its parent's) plus a handful of scalars.
    Discovery selection (which frontier row violated/satisfied each
    property) is likewise reduced on device to one fingerprint per property.

The host orchestrates: it keeps the (fingerprint -> parent-fingerprint)
mirror used for trace reconstruction by replay (the TLC technique,
`bfs.rs:314-342`), records discoveries, and slices frontier segments out of
the device-resident compact buffers.

Semantic differences vs the host engines (both documented and benign):
  * work granularity is a frontier segment, not a single state, so
    ``state_count``/``unique_state_count`` may exceed the host engines'
    values on early-exit runs (the reference's own multithreaded runs are
    similarly nondeterministic); full-enumeration unique counts match
    exactly;
  * which duplicate within a batch wins a slot (and thus which parent a
    state records) is unspecified — the reference tolerates the same benign
    DashMap race (`bfs.rs:198,206,268`).

The ``eventually`` semantics replicate the reference's documented caveats
(`bfs.rs:239-256`): ebits ride per-frontier-row (bit i = property i not yet
satisfied on this path), are not part of the fingerprint, and joins/cycles
are not treated as terminal.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..core import Expectation
from .builder import CheckerBuilder
from .host import HostChecker
from .path import Path

_MIN_BUCKET = 16

_XOVF_MESSAGE = (
    "packed-state capacity overflow: a successor state could not be "
    "encoded (e.g. more distinct in-flight envelopes than net_capacity "
    "slots). Raise the model's capacity bounds — continuing would "
    "silently under-explore the state graph.")


def _next_pow2(n: int) -> int:
    return 1 << max((n - 1).bit_length(), 0)


def model_tag(model) -> str:
    """Checkpoint identity check, shared by the solo engines and the
    batch loop's per-lane pause checkpoints: a checkpoint only makes
    sense for the same model config (same packed layout, same
    transitions) AND the same fingerprint algorithm — resuming
    old-scheme fingerprints would silently fail to dedup against newly
    computed ones."""
    from ..fingerprint import FP_VERSION

    return (f"{type(model).__module__}.{type(model).__qualname__}"
            f"|{model.cache_key()!r}|w={model.packed_width}"
            f"|fpv={FP_VERSION}")


def _bucket(n: int) -> int:
    return max(_MIN_BUCKET, _next_pow2(n))


def _combine64(hi, lo) -> np.ndarray:
    """Host-side (hi, lo) uint32 pair -> uint64 fingerprint array."""
    return (np.asarray(hi).astype(np.uint64) << np.uint64(32)) \
        | np.asarray(lo).astype(np.uint64)


def _compact(mask, *columns):
    """Scatter-compact ``columns`` rows where ``mask`` holds to the front.

    Returns (count, *compacted) with compacted columns the same shape as the
    inputs; rows past ``count`` are zero.
    """
    import jax.numpy as jnp

    n = mask.shape[0]
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    idx = jnp.where(mask, pos, n)
    out = tuple(jnp.zeros_like(c).at[idx].set(c, mode="drop")
                for c in columns)
    return (mask.sum(dtype=jnp.int32),) + out


from .device_loop import LruCache as _LruCache

_LEVEL_CACHE = _LruCache()
#: observed per-iteration maxima by engine config — (vmax raw, dmax
#: post-dedup), keyed like the chunk-program cache. Later runs of the
#: same model config start with candidate buffers sized to what the
#: config actually branches (+17%), instead of the static defaults
#: (2pc's fa/2 default is ~65% wider than its observed raw maximum —
#: every dedup/compaction pass scales with that width). An unlucky
#: shallow first run that under-observes costs at most one kovf
#: abort-and-rebuild, the same protocol that covers undersized defaults.
_SIZE_MEMO = _LruCache(limit=256)
_INSERT_JIT = None


def _size_fit(observed: int) -> int:
    """Quantize an observed maximum (+1/6 margin) to 1/8-power-of-two
    buckets: run-to-run drift in the maxima (batch boundaries move) must
    not move the compiled shapes, or every run would recompile."""
    want = observed + observed // 6
    step = max(256, 1 << (max(want.bit_length(), 9) - 3))
    return -(-want // step) * step


def candidate_sizes(model, fmax: int, sound: bool, opts: dict,
                    size_key) -> "tuple":
    """The kraw/kmax candidate-buffer sizing shared by the single-chip
    and sharded engines: static defaults (ops/expand.py), tightened by
    the observed-size memo — which only tightens the DEFAULTS (a
    user-tuned kraw/kmax is an explicit instruction and must not be
    clamped by what a possibly-shallow earlier run happened to
    observe)."""
    from ..ops.expand import kfinal_default, kmax_default
    fa = fmax * model.max_actions
    kraw = kmax_default(model, fmax, sound)
    kmax = kfinal_default(model, fmax, sound)
    if "kraw" not in opts and "kmax" not in opts and size_key is not None:
        seen = _SIZE_MEMO.get(size_key)
        if seen is not None:
            kraw = min(kraw, max(1 << 12, _size_fit(seen[0])))
            kmax = min(kmax, max(1 << 12, _size_fit(seen[1])))
    kraw = min(int(opts.get("kraw", kraw)), fa)
    kmax = min(int(opts.get("kmax", kmax)), kraw)
    return kraw, kmax


def _insert_jit():
    """Process-wide jitted ``table_insert`` (shapes retrace within one
    wrapper; a fresh ``jax.jit`` per run would recompile every time)."""
    global _INSERT_JIT
    if _INSERT_JIT is None:
        import jax

        from ..ops.hashtable import table_insert
        _INSERT_JIT = jax.jit(table_insert)
    return _INSERT_JIT


_EVICT_JIT = None


def _evict_jit():
    """Process-wide jitted ``table_evict_prefix`` (the spill path's
    in-place range eviction; shapes retrace within one wrapper)."""
    global _EVICT_JIT
    if _EVICT_JIT is None:
        import jax

        from ..ops.hashtable import table_evict_prefix
        _EVICT_JIT = jax.jit(table_evict_prefix)
    return _EVICT_JIT


def build_level_fn(model, symmetry: bool = False):
    """Build the jitted single-chip BFS level step for a packed model.

    One launch fuses everything the reference does per state in
    ``check_block`` (`bfs.rs:165-274`) — the shared expansion core
    (`ops/expand.py`) plus visited-set insert and child compaction. Outputs
    are device-resident; everything the host must inspect is either a
    scalar or a compacted array whose prefix length is one of those
    scalars. Memoized on ``model.cache_key()``.
    """
    from .device_loop import model_cache_key

    mkey = model_cache_key(model)
    if mkey is not None:
        mkey = (mkey, symmetry)
        cached = _LEVEL_CACHE.get(mkey)
        if cached is not None:
            return cached
    fn = _build_level_fn(model, symmetry)
    if mkey is not None:
        _LEVEL_CACHE[mkey] = fn
    return fn


def _build_level_fn(model, symmetry: bool):
    import jax
    import jax.numpy as jnp

    from ..ops.expand import (discovery_candidates, eventually_indices,
                              expand_frontier)
    from ..ops.hashtable import table_insert

    properties = model.properties()
    n_actions = model.max_actions
    eventually_idx = eventually_indices(properties)

    def level_fn(frontier, fvalid, ebits, key_hi, key_lo):
        exp = expand_frontier(model, frontier, fvalid, ebits,
                              eventually_idx, symmetry=symmetry)
        inserted, key_hi, key_lo, overflow = table_insert(
            key_hi, key_lo, exp.chi, exp.clo, exp.cvalid)

        # compact the new states: this dense prefix IS the next frontier
        par_hi = jnp.repeat(exp.phi, n_actions)
        par_lo = jnp.repeat(exp.plo, n_actions)
        ceb = jnp.repeat(exp.ebits, n_actions)
        (count, comp_rows, comp_chi, comp_clo, comp_phi, comp_plo,
         comp_eb, comp_ohi, comp_olo) = _compact(
            inserted, exp.flat, exp.chi, exp.clo, par_hi, par_lo, ceb,
            exp.ohi, exp.olo)

        disc_hit, disc_hi, disc_lo = discovery_candidates(
            properties, exp, fvalid)
        gen_count = exp.cvalid.sum(dtype=jnp.int32)
        return (key_hi, key_lo, comp_rows, comp_chi, comp_clo, comp_phi,
                comp_plo, comp_eb, count, disc_hit, disc_hi, disc_lo,
                gen_count, overflow, exp.phi, exp.plo, exp.xovf,
                comp_ohi, comp_olo)

    return jax.jit(level_fn)


_LEVEL_HELPERS = None


def _level_helpers():
    """Process-wide jitted helpers for the per-level engine (shapes retrace
    within each wrapper)."""
    global _LEVEL_HELPERS
    if _LEVEL_HELPERS is None:
        import jax
        import jax.numpy as jnp

        def slice_fn(rows, ebs, start, size):
            # clipped gather: out-of-range rows are garbage but always land
            # in the fvalid-masked tail, so no state is shifted or dropped
            idx = jnp.minimum(start + jnp.arange(size),
                              rows.shape[0] - 1)
            return rows[idx], ebs[idx]

        def take_fn(chi, clo, phi, plo, size):
            return chi[:size], clo[:size], phi[:size], plo[:size]

        def take2_fn(a, b, size):
            return a[:size], b[:size]

        def take_rows_fn(rows, size):
            return rows[:size]

        _LEVEL_HELPERS = (jax.jit(slice_fn, static_argnums=(3,)),
                          jax.jit(take_fn, static_argnums=(4,)),
                          jax.jit(take_rows_fn, static_argnums=(1,)),
                          jax.jit(take2_fn, static_argnums=(2,)))
    return _LEVEL_HELPERS


def auto_fmax(model, shards: int = 1) -> int:
    """Default expansion width: ~12.5M child lane-words per iteration
    (divided across shards) — empirically the knee of the lane-cost curve
    across model shapes (paxos at 8192 rows measures ~8% faster per
    unique state than at 10922; narrow high-action models like 2pc keep
    the 12288-row cap). VERY wide rows (packed actor
    states, width >= 256) have a much lower knee (~6M lane-words —
    ABD-ordered measured best near fmax=1024 at width 331, round 4): the
    dense successor materialization is bandwidth-bound there, not
    op-latency-bound. Shared by the single-chip and sharded engines so
    the knee is tuned in one place. The floor (1024 rows on a single
    chip, divided across shards down to 256) keeps enough frontier rows
    per iteration to amortize the fixed per-iteration cost."""
    target = (3 << 21) if model.packed_width >= 256 else (3 << 22)
    return max(max(256, (1 << 10) // shards), min(
        3 << 12,
        target // (model.max_actions * model.packed_width * shards)))


def _enable_compile_cache() -> None:
    """Point JAX's persistent compilation cache somewhere sane (unless the
    user already configured one). Engine shapes recur across processes —
    without this every checker run repays ~10-30s of XLA compiles."""
    import os

    import jax

    if jax.config.jax_compilation_cache_dir:
        return
    path = os.environ.get(
        "STATERIGHT_TPU_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "stateright_tpu",
                     "xla"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except OSError:
        pass  # unwritable cache dir: compile uncached


class TpuChecker(HostChecker):
    """Level-synchronous device BFS over a packed model."""

    def __init__(self, builder: CheckerBuilder):
        model = builder.model
        for attr in ("packed_width", "max_actions", "encode", "packed_step",
                     "packed_properties"):
            if not hasattr(model, attr):
                raise TypeError(
                    f"spawn_tpu() requires a PackedModel (missing {attr!r}); "
                    "see stateright_tpu.models.packed.PackedModel. Host-only "
                    "models can use spawn_bfs()/spawn_dfs().")
        super().__init__(builder)
        opts = builder.tpu_options_
        self._tpu_options = opts
        self._capacity = int(opts.get("capacity", 1 << 20))
        assert self._capacity & (self._capacity - 1) == 0, \
            "capacity must be a power of two"
        self._max_segment = int(opts.get("max_segment", 1 << 15))
        self._grow_at = float(opts.get("grow_at", 0.55))
        # fused Pallas expand→fingerprint→dedup kernel (ops/fused.py):
        # 'auto' tries the Pallas build on TPU backends and falls back
        # to the staged path on any failure (classified + traced —
        # never a hard error); True forces it (interpret mode off TPU,
        # how the CPU parity suite pins it); False forces staged
        self._fused_mode = opts.get("fused", "auto")
        if self._fused_mode not in (True, False, "auto"):
            raise ValueError(
                f"unknown tpu_options fused {self._fused_mode!r}; "
                "expected True, False, or 'auto'")
        # cross-chunk in-kernel dedup tier (ops/fused.py): a small
        # device-resident recent-key ring probed before the main table,
        # killing the re-expanded duplicates in-batch dedup cannot see
        # (2pc7's ~9x gen/uniq). True = default capacity, an int = ring
        # slots (power of two), False = off. Rides the fused path only.
        cc_opt = opts.get("cc_dedup", True)
        if cc_opt is True:
            from ..ops.fused import CC_DEFAULT
            self._cc_cap = CC_DEFAULT
        elif cc_opt is False:
            self._cc_cap = 0
        else:
            self._cc_cap = int(cc_opt)
            if self._cc_cap & (self._cc_cap - 1) or self._cc_cap < 4:
                raise ValueError(
                    f"tpu_options(cc_dedup={cc_opt!r}) must be True, "
                    "False, or a power-of-two slot count >= 4 (the "
                    "ring is direct-mapped by the fingerprint hash)")
        #: why a fused='auto' run stayed staged (None when it fused or
        #: was never eligible to) — surfaced by report()'s metrics line
        #: next to the fused_unsupported gauge
        self._fused_unsupported_reason = None
        # host-evaluated properties (e.g. the linearizability search):
        # declared by the model, evaluated per level on newly inserted
        # states, memoized by model.host_property_key(row)
        self._host_props = [
            (i, self._properties[i])
            for i in getattr(model, "host_property_indices", ())]
        # packed fast-path evaluators, resolved ONCE into _host_props
        # order. The canonical form is a dict keyed by PROPERTY NAME —
        # a renamed/reordered subclass property binds the right lambda
        # or fails loudly, where the legacy positional list could
        # silently bind the wrong one (it survives only with the
        # length guard)
        self._host_fns = self._resolve_host_fns(
            getattr(model, "host_property_fns", None))
        # --- resilience knobs (checker/resilience.py) ------------------
        from .resilience import (AuditPolicy, DegradePolicy, RetryPolicy,
                                 SpillPolicy)
        self._retry_policy = RetryPolicy.from_options(opts)
        self._degrade_policy = DegradePolicy.from_options(opts)
        # silent-corruption audit (README § Silent corruption defense):
        # sampled chunks re-execute their frontier fingerprints on a
        # different device (host oracle on single-chip) and a mismatch
        # quarantines the lying chip. Off by default — the unaudited
        # path is the pre-existing engine bit for bit.
        self._audit_policy = AuditPolicy.from_options(opts)
        # injected lying-chip hook (tests/bench): (ordinal, shards) ->
        # the mesh position whose reported fingerprints get one bit
        # flipped this chunk, or None — the corruption analog of
        # fault_hook
        self._corrupt_hook = opts.get("corrupt_hook")
        #: mesh positions the auditor caught lying this run — the
        #: scheduler maps them onto the lease's devices and withholds
        #: them from future grants (service/scheduler.py)
        self._quarantined: set = set()
        #: the shadow's running chunk-digest head at the last fold —
        #: what checkpoint/result artifacts chain their integrity to
        self._shadow_chain_head = None
        # memory tiering (README § Memory tiering): growth past the HBM
        # budget — or a spill-eligible capacity fault in the retry
        # envelope — evicts cold fingerprint-prefix ranges to the host
        # tier instead of dying
        self._spill_policy = SpillPolicy.from_options(opts)
        if self._spill_policy.max_capacity is not None \
                and self._spill_policy.max_capacity < self._capacity:
            raise ValueError(
                f"tpu_options(max_capacity={self._spill_policy.max_capacity}) "
                f"is below capacity={self._capacity}; the budget caps "
                "GROWTH, so it must be >= the starting capacity")
        self._fault_hook = opts.get("fault_hook")
        # legacy hooks take (chunk); two-parameter hooks also receive
        # the current mesh width, so an injected "permanent" device
        # fault can stop firing once the ladder drops the dead chip
        self._fault_hook_arity = 1
        if self._fault_hook is not None:
            import inspect
            try:
                self._fault_hook_arity = len(
                    inspect.signature(self._fault_hook).parameters)
            except (TypeError, ValueError):
                pass
        #: mesh width the fault hooks/watchdog report (the sharded
        #: engine maintains it down the ladder; 1 on the plain loop)
        self._fault_shards = 1
        # degraded-mesh handoff (parallel/engine.py ladder -> the
        # single-chip rung): pending frontier + discoveries, and the
        # run-spanning shadow _make_shadow re-adopts
        self._handoff = None
        self._handoff_shadow = None
        self._handoff_device = None
        self._chunk_deadline = opts.get("chunk_deadline")
        if self._chunk_deadline is not None \
                and float(self._chunk_deadline) <= 0:
            raise ValueError(
                "tpu_options(chunk_deadline=...) must be positive "
                "seconds (omit it to disable the watchdog)")
        self._autosave_path = opts.get("autosave")
        self._autosave_every = int(opts.get("autosave_interval", 32))
        # host-evaluated EVENTUALLY properties run on the per-level
        # engine: the device never clears their ebits (the packed
        # placeholder bit must be False); the host evaluates each new
        # state's condition (memoized by host_property_key) and corrects
        # its ebits before it is enqueued, so terminal flushes report
        # faithful counterexamples
        self._host_ev = [(i, p) for i, p in self._host_props
                         if p.expectation == Expectation.EVENTUALLY]
        self._host_prop_cache: Dict[bytes, List[bool]] = {}
        # sound-eventually mode: dedup on (state, pending-ebits) NODE keys
        # (`fingerprint.fp64_node`), fixing the reference's documented
        # DAG-rejoin miss (`bfs.rs:239-244`)
        self._sound = builder.sound_eventually_ and any(
            p.expectation == Expectation.EVENTUALLY
            for p in self._properties)
        if self._sound:
            if any(i > 31 for i, p in enumerate(self._properties)
                   if p.expectation == Expectation.EVENTUALLY):
                raise NotImplementedError(
                    "sound_eventually() supports eventually-property "
                    "indices 0..31")
            if self._host_props:
                raise NotImplementedError(
                    "sound_eventually() with host-evaluated properties "
                    "is not supported on the TPU engine")
            if self._spill_policy.max_capacity is not None:
                raise NotImplementedError(
                    "tpu_options(max_capacity=...) memory tiering is "
                    "not supported with sound_eventually(): rediscovered"
                    " node keys would re-enter the cross-edge records "
                    "the lasso sweep treats as a faithful node graph. "
                    "Raise tpu_options(capacity=...) instead.")
        # host-property history dedup (device engine): the history-key
        # table rides IN the chunk carry (device_loop.ChunkCarry.hkey_*);
        # hcap is its capacity, grown on occupancy pressure or hovf.
        # (The 'hmax' option is read by the sharded engine only.)
        self._posthoc_cap = int(opts.get("hcap", 1 << 16))
        if self._posthoc_cap & (self._posthoc_cap - 1) \
                or self._posthoc_cap < 4:
            raise ValueError(
                "tpu_options(hcap=...) must be a power of two >= 4 "
                "(the open-addressing probe ring masks by bucket count)")
        self._h_pulled = 0  # representatives already host-evaluated
        self._hscan_tail = 0  # queue rows known fully history-deduped
        # phase timers/counters ride the shared obs registry
        # (HostChecker._metrics); keys are the obs.GLOSSARY canon
        # device-resident search record, pulled lazily by _ensure_mirror
        self._mirror_carry = None
        # most recently enqueued queue row (rides each chunk sync) —
        # the Explorer's live-progress sample for the device engine
        self._recent_row = None
        # last sync's (device_s, xfer_s) split, set by
        # _materialize_stats (None when the pull never completed)
        self._pull_timing = None
        self._resume_path = builder.resume_path_
        self._resume_frontier = None
        self._base_fps: List[int] = []
        _enable_compile_cache()
        # fingerprint -> parent fingerprint mirror (host side; the
        # checkpointable search record, also used for path reconstruction).
        self._generated: Dict[int, Optional[int]] = {}
        # under symmetry: canonical fp -> the ORIGINAL explored state's fp,
        # so witness paths replay through concrete states
        self._orig_of: Dict[int, int] = {}
        self._symmetry_fn = builder.symmetry_fn_
        self._symmetry = builder.symmetry_fn_ is not None
        if self._symmetry:
            if not hasattr(model, "packed_representative"):
                raise NotImplementedError(
                    "symmetry reduction on the TPU engine requires the "
                    "model to implement packed_representative (the device "
                    "canonicalization); use spawn_dfs() otherwise")
            if self._audit_policy.enabled:
                raise NotImplementedError(
                    "tpu_options(audit=...) is not supported with "
                    "symmetry reduction: the queue rows are ORIGINAL "
                    "states while their cached fingerprints are the "
                    "canonical representatives', so the oracle cannot "
                    "re-execute them independently. Audit unreduced "
                    "runs, or rely on the artifact integrity chain.")

    # _timed/profile() come from HostChecker: ONE metrics registry per
    # run, keys documented once in stateright_tpu.obs.GLOSSARY (the
    # overlap timers dispatch/sync_stall/host_overlap included).

    def _resolve_host_fns(self, fns) -> "Optional[list]":
        """Normalize ``model.host_property_fns`` into ``_host_props``
        order: a dict binds by property name (unknown/missing names
        fail loudly); a legacy sequence binds positionally behind the
        length guard."""
        if fns is None:
            return None
        if isinstance(fns, dict):
            names = [p.name for _i, p in self._host_props]
            unknown = sorted(set(fns) - set(names))
            missing = [n for n in names if n not in fns]
            if unknown or missing:
                raise ValueError(
                    "host_property_fns keys must match the model's "
                    "host-evaluated property names exactly "
                    f"(host_property_indices -> {names}); "
                    f"unknown={unknown}, missing={missing}. A subclass "
                    "that renames or reorders properties must keep the "
                    "packed fast-path evaluators in lockstep (or drop "
                    "host_property_fns to fall back to decode())")
            return [fns[n] for n in names]
        if len(fns) != len(self._host_props):
            raise ValueError(
                f"model declares {len(self._host_props)} host-evaluated "
                f"properties (host_property_indices) but {len(fns)} "
                "host_property_fns; a subclass that changes properties "
                "must keep the packed fast-path evaluators in lockstep "
                "(or drop host_property_fns to fall back to decode())")
        return list(fns)

    # --- fused-kernel selection (ops/fused.py) -------------------------
    def _fused_resolve(self, *, sharded: bool, fmax: int,
                       capacity: int, probe_lanes: int = 0) -> "tuple":
        """Resolve ``tpu_options(fused=...)`` into ``(on, interpret)``.

        ``'auto'``: configurations outside the support matrix stay
        staged — announced by a one-time ``fused_unsupported`` trace
        event naming the reason plus the ``fused_unsupported`` gauge
        (so profile()/report() say WHY a run didn't fuse, instead of
        quietly downgrading); on a TPU backend the build is attempted
        via ``ops.fused.verify_build`` (and, sharded, the owner-side
        probe kernel via ``verify_probe_build``, timed under the
        ``probe_kernel_s`` metric; both memoized) and ANY failure is
        classified through the resilience taxonomy, counted
        (``fused_fallbacks``) and traced (``fused_fallback`` event) —
        never a hard error. Off-TPU, 'auto' resolves to staged without
        an attempt (the interpreter would be slower than compiled XLA);
        ``tpu_options(fused_attempt=True)`` forces the attempt with the
        interpreter — the knob the forced-fallback tests use.
        ``True`` forces the fused build: unsupported configurations
        raise, and build failures surface.
        """
        mode = self._fused_mode
        if mode is False:
            return False, False
        from ..ops import fused as fused_mod

        hint = 0 if sharded else int(self._tpu_options.get("hint", 0))
        reason = fused_mod.supports(
            self._model, sound=self._sound,
            host_props=bool(self._host_props), hint=hint)
        if reason is not None:
            if mode is True:
                raise ValueError(
                    f"tpu_options(fused=True) is unsupported for this "
                    f"configuration: {reason}")
            # satellite: say WHY the run stayed staged, once per run
            if self._fused_unsupported_reason is None:
                self._fused_unsupported_reason = reason
                self._metrics.set("fused_unsupported", 1)
                if self._trace:
                    self._trace.emit("fused_unsupported", reason=reason)
            return False, False
        import jax
        interpret = jax.default_backend() != "tpu"
        if mode is True:
            return True, interpret
        if interpret and not self._tpu_options.get("fused_attempt"):
            return False, False
        try:
            fused_mod.verify_build(self._model, fmax, capacity,
                                   symmetry=self._symmetry,
                                   probe=not sharded,
                                   interpret=interpret,
                                   props=bool(self._properties),
                                   cc=self._cc_cap)
            if sharded and probe_lanes:
                # the pipeline's second kernel: its verify/compile wall
                # time is the probe_kernel_s obs key (kernel_bench
                # reports the per-dispatch timings)
                with self._metrics.timed("probe_kernel_s"):
                    fused_mod.verify_probe_build(
                        probe_lanes, capacity, interpret=interpret)
        except Exception as exc:
            from .resilience import classify_error
            cause = classify_error(exc).value
            self._metrics.inc("fused_fallbacks")
            if self._trace:
                self._trace.emit(
                    "fused_fallback", cause=cause,
                    error=f"{type(exc).__name__}: {exc}")
            return False, False
        return True, interpret

    # --- resilience plumbing (checker/resilience.py) -------------------
    def _make_shadow(self, shards: int):
        """The host-side authoritative state, maintained per chunk when
        retry or autosave is on (``None`` otherwise — zero cost). A
        degraded-mesh handoff re-adopts the run-spanning shadow (its
        cumulative insert/edge records feed the sound-mode lasso sweep
        across every epoch and rung) instead of starting a fresh one.
        An HBM budget (``max_capacity``) also turns the shadow on — the
        host tier IS the shadow, so tiering cannot run without it; so
        does the chunk auditor (``audit=``), whose rollback boundary
        and replay frontier live in the shadow."""
        if not (self._retry_policy.enabled
                or self._autosave_path is not None
                or self._audit_policy.enabled
                or (self._spill_policy.enabled
                    and self._spill_policy.max_capacity is not None)):
            return None
        adopted = self._handoff_shadow
        if adopted is not None:
            self._handoff_shadow = None
            adopted.reshard(shards)
            adopted.audit_enabled = self._audit_policy.enabled
            return adopted
        from .resilience import HostShadow
        shadow = HostShadow(shards, self._model.packed_width,
                            self._generated, self._orig_of,
                            translate=self._symmetry or self._sound,
                            sound=self._sound)
        shadow.audit_enabled = self._audit_policy.enabled
        return shadow

    def _materialize_stats(self, stats_d, ordinal: int,
                           t_disp: "Optional[float]" = None) -> np.ndarray:
        """Pull one chunk's stats vector through the resilience hooks:
        the injected fault hook fires first (the tests' transient-fault
        injection point), then the optional watchdog deadline bounds
        the device round trip (a hang becomes a classified fault).

        Device-time attribution: the host-side ``sync_stall`` timer
        conflated device compute with the tunnel transfer. The pull now
        splits the interval at the stats future's readiness —
        dispatch→ready is the ``device_s`` estimate (the chunk program
        executing; an upper bound under pipelining, where host work
        overlaps it), ready→materialized is ``xfer_s`` (the transfer).
        Stored in ``_pull_timing`` for the caller's metrics/trace."""
        import jax

        hook = self._fault_hook
        shards = int(self._fault_shards)
        self._pull_timing = None
        self._pull_stamps = None

        def pull():
            if hook is not None:
                if self._fault_hook_arity >= 2:
                    hook(ordinal, shards)
                else:
                    hook(ordinal)
            t0 = time.perf_counter()
            try:
                stats_d.block_until_ready()
            except AttributeError:
                pass  # already host-side (host fallbacks, tests)
            t1 = time.perf_counter()
            out = np.asarray(jax.device_get(stats_d))
            t2 = time.perf_counter()
            base = t_disp if t_disp is not None else t0
            self._pull_timing = (max(t1 - base, 0.0), max(t2 - t1, 0.0))
            # absolute stamps for the span profiler: the device span
            # runs dispatch->ready, the xfer span ready->materialized
            self._pull_stamps = (t1, t2)
            return out

        deadline = self._chunk_deadline
        if not deadline:
            return pull()
        from .resilience import ChunkDeadlineError, call_with_deadline
        try:
            return call_with_deadline(pull, float(deadline),
                                      what=f"chunk {ordinal} sync")
        except ChunkDeadlineError:
            if self._trace:
                # the hung transfer cannot name its chip; the mesh
                # width at least scopes the postmortem
                self._trace.emit("watchdog", deadline=float(deadline),
                                 chunk=ordinal, shards=shards)
            # a hung sync is exactly the crash the flight recorder
            # exists for: land the postmortem before the retry envelope
            # decides what happens next
            self._flight_dump("watchdog")
            raise

    def _checkpoint_save(self, path, rows, ebits, ffps,
                         discoveries: Dict[str, object]) -> None:
        """Write a ``resume_from``-loadable checkpoint (the complete
        mirror + the given pending frontier) through the crash-safe
        atomic write. Shared by ``save()`` and the autosave path. The
        metadata carries the artifact integrity chain: a sha256 over
        the payload arrays chained to the run's chunk-digest head,
        which ``_load_checkpoint`` verifies before seeding anything."""
        import json

        from .resilience import (atomic_savez, chain_integrity,
                                 payload_digest)

        child = np.fromiter(self._generated.keys(), np.uint64,
                            len(self._generated))
        parent = np.fromiter(
            (p if p is not None else 0
             for p in self._generated.values()),
            np.uint64, len(self._generated))
        okeys = np.fromiter(self._orig_of.keys(), np.uint64,
                            len(self._orig_of))
        ovals = np.fromiter(self._orig_of.values(), np.uint64,
                            len(self._orig_of))
        arrays = dict(child=child, parent=parent,
                      rows=np.asarray(rows, np.uint32),
                      ebits=np.asarray(ebits, np.uint32),
                      ffps=np.asarray(ffps, np.uint64),
                      okeys=okeys, ovals=ovals,
                      state_count=np.int64(self._state_count))
        chain_head = self._shadow_chain_head or ""
        meta = json.dumps({
            "model": self._model_tag(),
            "discoveries": {n: ([int(f) for f in fp]
                                if isinstance(fp, (list, tuple))
                                else int(fp))
                            for n, fp in discoveries.items()},
            "symmetry": bool(self._symmetry),
            "sound": bool(self._sound),
            "chain_head": chain_head,
            "integrity": chain_integrity(payload_digest(arrays),
                                         chain_head),
        })
        atomic_savez(path, meta=np.asarray(meta), **arrays)

    def _write_autosave(self, shadow,
                        discoveries: Dict[str, object]) -> None:
        """Checkpoint the shadow (periodic, and on exhausted retries):
        purely host-side, so it works even with a dead backend.

        Generation rotation: the previous checkpoint survives as
        ``<path>.g1`` before the new one lands at ``<path>`` (always
        the newest loadable generation), so a corrupt or truncated
        newest file rolls the resume back ONE generation instead of
        losing the run (``_load_checkpoint``)."""
        from .resilience import AUTOSAVE_PREV_SUFFIX
        path = os.fspath(self._autosave_path)
        if os.path.exists(path):
            os.replace(path, path + AUTOSAVE_PREV_SUFFIX)
        rows, ebits, fps = shadow.pending()
        self._shadow_chain_head = shadow.chain_head
        self._checkpoint_save(self._autosave_path, rows, ebits, fps,
                              discoveries)
        self._metrics.inc("autosaves")
        if self._trace:
            self._trace.emit("autosave",
                             path=path,
                             unique=len(self._generated))

    def _resilience_degrade(self, exc: BaseException, shadow,
                            discoveries: Dict[str, object]) -> None:
        """Retries exhausted below the ladder's ``min_mesh`` (or with
        ``degrade=False``): land an artifact instead of just dying —
        write the autosave checkpoint (when configured) and raise ONE
        actionable error naming the resume command."""
        # exhausted retries are a flight-recorder trigger in their own
        # right: the ring at this point holds the whole retry burst
        self._flight_dump("retries_exhausted")
        if self._autosave_path is not None:
            self._write_autosave(shadow, discoveries)
            path = os.fspath(self._autosave_path)
            raise RuntimeError(
                "transient device fault persisted after "
                f"{self._retry_policy.retries} retries "
                f"({type(exc).__name__}: {exc}); progress checkpointed "
                f"to {path!r} — resume with "
                f"model.checker().resume_from({path!r}).spawn_tpu() "
                "once the backend recovers") from exc
        raise RuntimeError(
            "transient device fault persisted after "
            f"{self._retry_policy.retries} retries "
            f"({type(exc).__name__}: {exc}); set "
            "tpu_options(autosave=path) to checkpoint progress on "
            "exhausted retries") from exc

    def _capacity_terminal(self, exc: BaseException, shadow,
                           discoveries: Dict[str, object]) -> None:
        """Capacity-class termination — spill disabled, ineligible, or
        the host tier exhausted too: land the postmortem artifacts a
        watchdog/retry exhaustion already gets (flight-recorder dump,
        and an autosave checkpoint when configured) before raising ONE
        actionable error naming both outs (a bigger bound, or resume)."""
        self._flight_dump("capacity")
        detail = f"{type(exc).__name__}: {exc}"
        if self._autosave_path is not None and shadow is not None:
            self._write_autosave(shadow, discoveries)
            path = os.fspath(self._autosave_path)
            raise RuntimeError(
                f"capacity exhausted and not recoverable by spill "
                f"({detail}); progress checkpointed to {path!r} — raise "
                "tpu_options(capacity=...) (or max_capacity=...) and "
                f"resume with model.checker().resume_from({path!r})"
                ".spawn_tpu()") from exc
        raise RuntimeError(
            f"capacity exhausted and not recoverable by spill "
            f"({detail}); raise tpu_options(capacity=...) (or "
            "max_capacity=...), or set tpu_options(autosave=path) to "
            "checkpoint progress at this point next time") from exc

    def _shadow_lasso_sweep(self, shadow, full_mask: int,
                            discoveries: Dict[str, object]) -> None:
        """The sound-mode SCC sweep rebuilt from the shadow's insert and
        cross-edge records instead of the device logs — after a
        mid-run recovery the device logs only cover the last epoch,
        while the shadow spans the whole run."""
        from .lasso import add_log_block, add_seed_nodes, lasso_sweep

        node_fp: Dict[int, int] = {}
        node_parent: Dict[int, tuple] = {}
        node_mask: Dict[int, int] = {}
        node_edges: Dict[int, list] = {}
        add_seed_nodes(node_fp, node_parent, node_mask,
                       shadow.root_keys(), self._orig_of, full_mask)
        empty_edges = np.zeros((0, 4), np.uint32)
        for s in range(shadow.shards):
            block = shadow.insert_block(s)
            edges = shadow.edge_block(s)
            if block is None and not len(edges):
                continue
            log_rows, eb_rows = block if block is not None else (
                np.zeros((0, 6), np.uint32), np.zeros((0,), np.uint32))
            add_log_block(node_fp, node_parent, node_mask, node_edges,
                          log_rows, eb_rows,
                          edges if len(edges) else empty_edges)
        lasso_sweep(self._properties, discoveries, node_edges,
                    node_mask, node_parent, node_fp)
        if self._trace:
            self._trace.emit(
                "lasso", nodes=len(node_mask),
                edges=sum(len(v) for v in node_edges.values()))

    # --- pausable runs (the step-driver/job-service boundary) ----------
    def request_pause(self, path=None) -> None:
        """Pause the device run at the next chunk boundary: the chunk
        loop drains its pipeline and writes a ``resume_from``-loadable
        checkpoint (complete mirror + pending frontier) to ``path``
        (default: the ``tpu_options(autosave=...)`` destination — which
        ``tpu_options(artifact_dir=...)`` always provides) before
        exiting; ``paused()`` then reports True and the checkpoint
        resumes on ANY mesh width (the scheduler's preemption-to-a-
        smaller-subset primitive). The per-level engine mode has no
        checkpointable loop and stops without a checkpoint."""
        if path is not None:
            self._pause_path = os.fspath(path)
        if self.pause_path() is None:
            raise ValueError(
                "request_pause() needs a checkpoint destination: pass "
                "request_pause(path=...) or configure "
                "tpu_options(autosave=...) / tpu_options(artifact_dir"
                "=...)")
        self._pause_event.set()

    def request_promote(self, devices) -> None:
        """Widen a sharded run D -> 2D at the next chunk boundary: the
        chunk loop drains its pipeline, extends the mesh with (up to D
        of) the granted ``devices``, re-routes the shadow's mirror and
        pending frontier by ``owner_of(fp, 2D)`` with preload-aware
        growth limits recomputed at the new width, recompiles, and
        resumes — the exact mirror of one degradation-ladder rung, so
        a job that degraded around a transient fault can climb back up
        once the blamed chip is released healthy. Requires the host
        shadow (``retries``/``autosave``/``max_capacity``); runs
        without one — and non-sharded engines — quietly decline, and
        a grant that cannot double the mesh (too few distinct new
        devices, or 2D past the shard limit) is dropped at the
        boundary rather than raising mid-run."""
        grant = list(devices)
        if not grant:
            raise ValueError(
                "request_promote() needs at least one device to widen "
                "onto (pass the freed jax.Device objects, their global "
                "ids, or jax.devices() positions)")
        self._promote_request = grant
        self._promote_event.set()

    def promote_pending(self) -> bool:
        """True while a ``request_promote`` grant awaits its
        chunk-boundary decision (the flex controller steps the driver
        until this clears, then reads the ``promotes`` counter to
        learn whether the engine took or declined the grant)."""
        return self._promote_event.is_set()

    # ------------------------------------------------------------------
    def _run(self) -> None:
        for _ in self._run_steps():
            pass

    def _run_steps(self):
        mode = str(self._tpu_options.get("mode", "auto"))
        if mode not in ("auto", "device", "level"):
            raise ValueError(
                f"unknown tpu_options mode {mode!r}; expected 'auto', "
                "'device', or 'level'")
        # a CheckerVisitor rides the DEVICE engine since round 5: the
        # append-only log is the insertion-ordered visitation record, so
        # visits replay from the mirror after the run (one path
        # reconstruction per unique state, like the per-level engine's
        # in-loop visits). The per-level engine still visits in-loop when
        # selected for other reasons (host eventually properties).
        # host-evaluated properties run on either engine: the per-level
        # engine evaluates them on each level's new states; the device
        # engine evaluates them via the in-carry history dedup. Host
        # EVENTUALLY properties need their per-row ebits corrected before
        # each state is enqueued, which only the per-level orchestration
        # provides.
        if self._host_ev:
            if mode == "device":
                raise NotImplementedError(
                    "host-evaluated eventually properties need the "
                    "per-level engine; drop tpu_options(mode='device')")
            mode = "level"
        if self._resume_path is not None and mode == "level":
            raise NotImplementedError(
                "resume_from() requires the device engine; drop "
                "tpu_options(mode='level')")
        if self._sound and mode == "level":
            raise NotImplementedError(
                "sound_eventually() requires the device engine; drop "
                "tpu_options(mode='level')")
        if mode in ("auto", "device"):
            yield from self._drive_device()
            if self._visitor is not None and not self._paused:
                with self._timed("visit"):
                    self._visit_reached()
        else:
            self._run_levels()

    def _write_pause_checkpoint(self, rows, ebits, ffps,
                                discoveries: Dict[str, object]) -> None:
        """Land the pause checkpoint (complete mirror + the pending
        frontier the caller gathered) and mark the run paused. Shared
        by the single-chip and sharded chunk loops."""
        path = self.pause_path()
        with self._timed("pause"):
            self._checkpoint_save(path, rows, ebits, ffps, discoveries)
        self._paused = True
        self._metrics.inc("pauses")
        if self._trace:
            self._trace.emit("pause", path=os.fspath(path),
                             unique=len(self._generated))

    def _seed_inits(self) -> "List[np.ndarray]":
        """Filter/fingerprint/encode the initial states into the mirror and
        return their packed rows (both engine modes seed identically)."""
        model = self._model
        init_states = [s for s in model.init_states()
                       if model.within_boundary(s)]
        self._state_count = len(init_states)
        validate = getattr(model, "validate_device_state", None)
        if self._symmetry:
            # the host symmetry_fn and the device packed_representative
            # must agree bit-for-bit, or dedup silently corrupts; check
            # the init states up front (the builder API accepts any fn)
            for s in init_states[:4]:
                host = model.encode(self._symmetry_fn(s))
                dev = np.asarray(model.packed_representative(
                    model.encode(s)))
                if not np.array_equal(host, dev):
                    raise ValueError(
                        "symmetry_fn disagrees with the model's "
                        "packed_representative on an init state: host "
                        f"canonical {host.tolist()} vs device "
                        f"{dev.tolist()}. The device engines require the "
                        "two canonicalizations to be bit-identical.")
        init_rows: List[np.ndarray] = []
        full_mask = 0
        if self._sound:
            from ..fingerprint import fp64_node
            from ..ops.expand import eventually_indices
            full_mask = sum(1 << i
                            for i in eventually_indices(self._properties))
        self._seed_cache_fps: List[int] = []
        for s in init_states:
            if validate is not None:
                validate(s)
            fp = self._canon_fp(s)
            key = fp64_node(fp, full_mask) if self._sound else fp
            if key not in self._generated:
                self._generated[key] = None
                if self._symmetry or self._sound:
                    # replay translation: node/canonical key -> the
                    # ORIGINAL explored state's fingerprint
                    self._orig_of[key] = model.fingerprint(s)
                init_rows.append(model.encode(s))
                # the queue fingerprint cache wants the CANONICAL state
                # fp (node keys are re-derived from it + the row ebits)
                self._seed_cache_fps.append(fp)
        self._unique_state_count = len(self._generated)
        return init_rows

    # ------------------------------------------------------------------
    def _run_device(self) -> None:
        """Blocking form of :meth:`_drive_device` (the degradation
        ladder's single-chip handoff rung still calls it directly)."""
        for _ in self._drive_device():
            pass

    def _drive_device(self):
        """Device-resident search: the whole multi-level loop is one XLA
        ``while_loop`` (see `device_loop.py`); the host syncs once per
        K-level chunk and pulls the (child fp, parent fp) log at the end.

        A GENERATOR since round 10: each ``yield`` is one chunk-loop
        quantum (a processed chunk or a handled intervention), so the
        run can be driven step-by-step by the job service's
        ``StepDriver`` (start → step(budget) → … → finish) instead of
        only as a blocking call; a pending ``request_pause()`` drains
        the pipeline, writes the resume_from-loadable pause checkpoint
        and exits the loop cleanly."""
        import jax
        import jax.numpy as jnp

        from .device_loop import build_chunk_fn, seed_carry
        from ..ops.hashtable import table_insert

        model = self._model
        properties = self._properties
        prop_count = len(properties)
        from ..ops.expand import eventually_indices
        full_ebits = np.uint32(sum(1 << i
                                   for i in eventually_indices(properties)))
        generated = self._generated
        # discoveries are buffered locally and published only after the
        # mirror is finalized: publishing early flips is_done() (all
        # properties discovered) while reconstruction data is still
        # device-resident, racing report()/assert_* with an empty mirror
        discoveries: Dict[str, int] = {}
        host_prop_idx = {i for i, _p in self._host_props}
        target = self._target_state_count
        opts = self._tpu_options
        fmax = int(opts.get("fmax", auto_fmax(model)))
        fa = fmax * model.max_actions
        # two-stage candidate-buffer widths (ops/expand.py): kraw holds
        # the raw-valid lanes (hash + in-batch dedup width), kmax the
        # dedup survivors (probe/append width) — every gather/probe in
        # the loop body scales with one of them, so models that know
        # their branching (max valid children per state) shrink both via
        # ``branching_hint``; an iteration that spikes past either
        # triggers the cheap kovf resize
        from .device_loop import model_cache_key

        size_key = model_cache_key(model)
        if size_key is not None:
            size_key = (size_key, fmax, self._sound, self._symmetry)
        kraw, kmax = candidate_sizes(model, fmax, self._sound, opts,
                                     size_key)
        # OPT-IN per-row stage-one compaction (device_loop.py): kraw
        # becomes the static fmax*hint; a row outgrowing it triggers the
        # same kovf rebuild protocol. Off by default: ``branching_hint``
        # is a batch-average heuristic, not a per-row bound (paxos
        # declares 4 but rows reach 10 — measured via profile()['rmax']),
        # so the global cross-row compaction usually packs tighter. Only
        # worth trying on models whose TRUE per-row branching is small
        # and uniform.
        hint_eff = int(opts.get("hint", 0))
        if hint_eff < 0 or hint_eff >= model.max_actions:
            # mirror the device-side degenerate fallback
            # (device_loop.py): the host must agree it is running the
            # global path, or the kovf resize logic would never grow
            # kraw and the chunk loop would rebuild forever
            hint_eff = 0
        k_steps = int(opts.get("chunk_steps", 64))
        insert_fn = _insert_jit()

        # --- seed -------------------------------------------------------
        self._fault_shards = 1
        handoff = self._handoff
        if self._resume_path is not None:
            init_rows, seed_ebits, seed_fps = self._load_checkpoint(
                discoveries)
        elif handoff is not None:
            # degraded-mesh handoff (the ladder's single-chip rung):
            # the shadow's pending frontier becomes the seed; the
            # mirrored reached set is already in self._generated, and
            # the prior rungs' discoveries carry over
            self._handoff = None
            init_rows, seed_ebits, seed_fps, prior = handoff
            discoveries.update(prior)
        else:
            init_rows = self._seed_inits()
            seed_ebits = full_ebits
            seed_fps = list(generated.keys())
        n_init = len(init_rows)
        self._hscan_tail = n_init
        base_unique = len(generated)
        # everything known at seed time must be re-inserted on growth (the
        # device log only records states found since)
        self._base_fps = list(generated.keys())
        if self._host_props and self._resume_path is None:
            # seed rows never enter the in-loop history log (only fresh
            # inserts do); evaluate them host-side once, like the
            # reference evaluates properties on every popped state. A
            # resumed frontier needs no pass: every pre-checkpoint state
            # was already evaluated and its discoveries ride the
            # checkpoint metadata.
            self._eval_host_props_block(
                [np.asarray(row) for row in init_rows], seed_fps,
                discoveries)
        if prop_count == 0:
            # nothing to search for: mirror the reference's immediate stop
            # once discoveries (vacuously) cover all properties
            # (bfs.rs:121-128)
            return

        # --- resilience plumbing (checker/resilience.py), created
        # BEFORE the seed: with memory tiering the shadow decides which
        # keys are device-resident at all (a degraded-mesh handoff may
        # arrive with ranges already evicted down the ladder)
        from .resilience import (SPILL_PREFIX_BITS, CorruptionError,
                                 FaultKind, audit_chunk_rows,
                                 blamed_device, classify_error,
                                 find_candidate_overflow, gather_rows,
                                 pack_qrows, spill_eligible)

        policy = self._retry_policy
        audit_pol = self._audit_policy
        corrupt_hook = self._corrupt_hook
        spill_pol = self._spill_policy
        spill_on = spill_pol.enabled and not self._sound
        shadow = self._make_shadow(1)

        # one while_loop iteration inserts at most kmax new states (and at
        # most fa once kmax has grown to its bound); capacity must keep
        # that headroom below the growth exit. ``preload`` is the table
        # occupancy seeded before the first chunk (just the inits on a
        # fresh run, the WHOLE mirrored reached set on a resume or a
        # post-fault re-seed — minus the host tier once ranges have been
        # evicted) — the growth trigger compares the epoch-local device
        # log count against the limit, so the limit must leave room for
        # the preloaded keys
        headroom = fa
        seed_keys = (shadow.hot_keys() if shadow is not None
                     else list(generated.keys()))
        preload = len(seed_keys)
        while self._grow_at * self._capacity <= headroom + preload \
                and spill_pol.can_grow(self._capacity):
            self._capacity *= 4
        if self._grow_at * self._capacity <= headroom + preload:
            # the preloaded set alone exceeds the HBM budget (a resumed
            # mirror, or a handoff after heavy spilling): evict at seed
            plan = (shadow.spill_plan(
                int(self._grow_at * self._capacity) - headroom - 1)
                if spill_on and shadow is not None else None)
            if plan is None:
                self._capacity_terminal(RuntimeError(
                    f"device hash table budget (max_capacity="
                    f"{spill_pol.max_capacity}) cannot hold the seeded "
                    f"reached set ({preload} keys) with spill "
                    "unavailable"), shadow, discoveries)
            seed_keys = shadow.hot_keys()
            preload = len(seed_keys)
            self._metrics.inc("spills")
            if plan[2]:
                self._metrics.inc("evicted_keys", plan[2])
            self._metrics.set("host_tier_keys", shadow.host_tier_keys)
            if self._trace:
                self._trace.emit("evict", prefixes=len(plan[0]),
                                 keys=plan[2])
                self._trace.emit("spill", capacity=self._capacity,
                                 hot=preload, reason="seed",
                                 host_tier_keys=shadow.host_tier_keys)

        # growth re-inserts the seed-time keys the device log lacks: the
        # HOT set only — re-promoting evicted ranges would undo a spill
        self._base_fps = seed_keys

        # append-only queue: must hold every state enqueued before the next
        # growth point (n_init + grow_limit) plus one iteration of appends
        qcap = self._device_qcap(n_init, headroom)
        hcap = self._posthoc_cap if self._host_props else 0
        # sound mode logs cross edges (dedup hits with pending bits) for
        # the post-exhaustion lasso sweep; grows independently on demand
        ecap = self._capacity if self._sound else 0
        with self._timed("seed"):
            # the block before the first chunk launch is deliberate:
            # launching the chunk (which donates the carry) while the
            # seed/insert programs are still in flight was measured to
            # slow the whole chunk loop ~2.5x on the tunneled device
            # the queue's cached fingerprints are canonical STATE fps
            # (sound mode dedups on node keys but re-derives them from
            # these); on resume (and on a degraded-mesh handoff) the
            # frontier rows carry their own recomputed fps
            cache_fps = (self._seed_cache_fps
                         if self._resume_path is None and handoff is None
                         else seed_fps)
            # the table is empty, so small seeds (the fresh-run case) are
            # placed by a host plan scattered INSIDE the seed program —
            # zero extra dispatches (a standalone table_insert dispatch,
            # a data-dependent while_loop program, costs ~0.2 s on a
            # tunneled device even for a handful of keys). Large seeds
            # (checkpoint resume mirrors the whole reached set) keep the
            # chunked device insert: the host plan's per-fingerprint
            # Python loop would be the slow path there. seed_keys is
            # the device-resident HOT set (== the whole mirror until
            # ranges have been evicted to the host tier).
            table_plan = None
            if len(seed_keys) <= (1 << 15):
                from ..ops.hashtable import plan_insert_host
                plan = plan_insert_host(seed_keys, self._capacity)
                table_plan = (plan, seed_keys)
            carry = seed_carry(
                model, qcap, self._capacity, init_rows, seed_ebits,
                symmetry=self._symmetry or self._sound, hcap=hcap,
                init_fps=cache_fps, table_plan=table_plan, ecap=ecap)
            if table_plan is None:
                key_hi, key_lo, seed_ovf = self._bulk_insert_async(
                    insert_fn, carry.key_hi, carry.key_lo, seed_keys)
                carry = carry._replace(key_hi=key_hi, key_lo=key_lo)
            else:
                seed_ovf = None  # plan_insert_host raises on overflow
            # No readiness wait: a block_until_ready here costs one
            # tunnel round trip (~100 ms, re-measured round 4). The
            # round-2/3 finding that launching the chunk over an
            # in-flight seed slowed the loop ~2.5x no longer reproduces
            # with the consolidated carry (q/log matrices, 2-D table);
            # PJRT orders the dependent programs itself.
        # fused Pallas kernel selection (ops/fused.py): resolved ONCE
        # per run — 'auto' probes the build and falls back classified
        fused_on, fused_interp = self._fused_resolve(
            sharded=False, fmax=fmax, capacity=self._capacity)
        self._metrics.set("fused", 1 if fused_on else 0)
        # cross-chunk dedup ring (fused path only): the ring halves
        # thread OUTSIDE the carry — adding ChunkCarry fields would
        # change every STAGED program's traced signature and invalidate
        # the persistent compile cache (the seed_carry 5-arg caveat).
        # cc_ring[0] holds the live (hi, lo) device pair between
        # dispatches; None = re-zeroed lazily (fresh run, post-fault
        # re-seed, spill epoch), which is always sound — the ring is a
        # cache whose misses only cost a table probe.
        cc_cap = self._cc_cap if fused_on else 0
        cc_ring = [None]
        if cc_cap:
            self._metrics.set("cc_dedup_capacity", cc_cap)

        def mk_chunk(reason: str = "initial"):
            # every rebuild implies an XLA retrace (unless the shapes
            # hit the compile cache) — count it and leave a trace event
            self._metrics.inc("compiles")
            if self._trace:
                self._trace.emit("compile", reason=reason)
            fn = build_chunk_fn(model, qcap, self._capacity, fmax,
                                kmax, symmetry=self._symmetry,
                                sound=self._sound, hcap=hcap,
                                n_init=n_init, kraw=kraw,
                                hint_eff=hint_eff, ecap=ecap,
                                fused=fused_on,
                                fused_interpret=fused_interp,
                                cc=cc_cap)
            if not cc_cap:
                return fn

            def chunk_with_ring(carry_, remaining_, grow_, h_base_):
                if cc_ring[0] is None:
                    cc_ring[0] = (jnp.zeros((cc_cap,), jnp.uint32),
                                  jnp.zeros((cc_cap,), jnp.uint32))
                carry2, rhi, rlo, stats_d = fn(
                    carry_, cc_ring[0][0], cc_ring[0][1], remaining_,
                    grow_, h_base_)
                cc_ring[0] = (rhi, rlo)
                return carry2, stats_d

            return chunk_with_ring

        chunk_fn = mk_chunk()
        pipeline = bool(opts.get("pipeline", True))

        # with retry, autosave or tiering on, the host keeps the
        # authoritative shadow (mirror + pending frontier + sound-mode
        # edge records + the host tier), updated per chunk; a transient
        # backend fault re-seeds a fresh device incarnation from it and
        # resumes, and a capacity fault spills before re-seeding
        if shadow is not None:
            shadow.seed_epoch([pack_qrows(init_rows, seed_ebits,
                                          cache_fps,
                                          model.packed_width)])

        # --- chunk loop -------------------------------------------------
        # Double-buffered pipeline (``tpu_options(pipeline=False)`` forces
        # the synchronous path): chunk N+1 is launched on the carry — a
        # device future, donated straight back in — BEFORE chunk N's stats
        # are materialized, so the host work (stats decode, batched
        # host-property evaluation, discovery bookkeeping) hides under
        # the accelerator instead of serializing with it. Speculation is
        # safe because every host-intervention condition (kovf / hovf /
        # ovf / xovf, the growth limits, an empty queue, device-property
        # completion) also gates the device loop's own cond
        # (device_loop.make_cond), so a chunk launched past one of them
        # runs zero iterations and replaying its stats is idempotent.
        # The one sanctioned divergence: an exit only the HOST can see (a
        # host-property discovery, a reached generation target) lands one
        # chunk late, so generated/unique counts may include one extra
        # chunk of real exploration — the same overshoot the chunk
        # granularity already implies (module docstring); discoveries and
        # witness paths are unaffected (sticky registers; the window
        # evaluation order is anchored per chunk).
        from .device_loop import HIST_WINDOW

        inflight: deque = deque()
        # latest unpacked per-chunk scalars, read by the post-loop phases
        cur = {"q_size": 0, "q_tail": 0, "log_n": 0, "e_n": 0}
        hgrow_pend = {"on": False, "hovf": False, "h_n": 0}
        kovf_pend = [0, 0, 0]  # observed vmax/dmax/rmax of kovf chunks

        def want_reps_now() -> bool:
            return bool(self._host_props) and any(
                p.name not in discoveries for _i, p in self._host_props)

        def dispatch() -> None:
            nonlocal carry, chunk_fn, hcap
            if hcap and not want_reps_now():
                # every host property has its discovery: the in-loop
                # history dedup is dead work now (and, saturated, would
                # stall the loop via hovf) — rebuild without it
                hcap = 0
                chunk_fn = mk_chunk("hdrop")
            # the growth limit bounds the EPOCH-LOCAL device log; the
            # preloaded table keys (inits / resumed mirror / post-fault
            # re-seed) are subtracted so total occupancy still trips
            # growth at ~grow_at
            grow_limit = np.int32(min(
                self._grow_at * self._capacity,
                self._capacity - headroom) - preload)
            remaining = np.int32(
                min(max(target - self._state_count, 0), 2**31 - 1)
                if target is not None else 2**31 - 1)
            carry = carry._replace(gen=jnp.int32(0),
                                   steps=jnp.int32(k_steps),
                                   vmax=jnp.int32(0),
                                   pdh=jnp.int32(0), prb=jnp.int32(0))
            t_d0 = time.perf_counter()
            with self._timed("dispatch"):
                carry, stats_d = chunk_fn(carry, remaining, grow_limit,
                                          np.int32(self._h_pulled))
            t_disp = time.perf_counter()
            self._metrics.inc("chunks")
            if fused_on:
                self._metrics.inc("fused_chunks")
            ordinal = int(self._metrics.get("chunks"))
            self._spans.record("dispatch", t_d0, t_disp, chunk=ordinal)
            inflight.append((ordinal, stats_d, self._h_pulled,
                             int(grow_limit), hcap, t_disp))

        def process(ordinal: int, stats_d, h_base: int, grow_limit: int,
                    hcap_d: int, t_disp: float) -> set:
            """Consume one chunk's stats vector; returns the host
            actions it demands (handled once the pipeline is drained)."""
            nonlocal seed_ovf, fault_attempt, spill_attempt, \
                corruption_attempt
            with self._timed("sync_stall"):
                # ONE transfer for everything the host reads per chunk
                # (scalars + the representative window when host props
                # are on): each transfer costs ~100 ms of tunnel latency
                # — routed through the fault hook + watchdog deadline
                stats = self._materialize_stats(stats_d, ordinal,
                                                t_disp=t_disp)
            # device-time attribution from the completed pull
            timing = self._pull_timing
            if timing is not None:
                self._metrics.add_time("device_s", timing[0])
                self._metrics.add_time("xfer_s", timing[1])
            # span twins: device (dispatch->ready) and xfer (ready->
            # materialized) as INTERVALS — under pipelining the device
            # span overlaps the PREVIOUS chunk's host span, which is
            # exactly what the attribution sweep needs to see
            stamps = getattr(self, "_pull_stamps", None)
            if stamps is not None:
                self._spans.record("device", t_disp, stamps[0],
                                   chunk=ordinal)
                self._spans.record("xfer", stamps[0], stamps[1],
                                   chunk=ordinal)
            # a successful sync proves the backend is alive: the retry
            # budget bounds CONSECUTIVE faults, not lifetime hiccups
            # (and the spill budget CONSECUTIVE unproductive spills)
            fault_attempt = 0
            spill_attempt = 0
            t0 = time.perf_counter()
            acts: set = set()
            (q_head, q_tail, log_n, gen, ovf, xovf, kovf, h_n, hovf,
             vmax, dmax, rmax, e_n, pdh, prb) = (
                int(stats[0]), int(stats[1]), int(stats[2]),
                int(stats[3]), bool(stats[4]), bool(stats[5]),
                bool(stats[6]), int(stats[7]), bool(stats[8]),
                int(stats[9]), int(stats[10]), int(stats[11]),
                int(stats[12]), int(stats[13]), int(stats[14]))
            disc_hit = stats[15:15 + prop_count].astype(bool)
            disc_hi = stats[15 + prop_count:15 + 2 * prop_count]
            disc_lo = stats[15 + 2 * prop_count:15 + 3 * prop_count]
            tail0 = 15 + 3 * prop_count
            width3 = model.packed_width + 3
            if q_tail > 0:
                # most recently enqueued state (live Explorer progress)
                self._recent_row = stats[tail0:tail0 + width3].copy()
            # cross-chunk dedup ring hits ride one trailing stats
            # element on the fused+cc path (chunk-local, like gen)
            cch = int(stats[tail0 + width3]) if cc_cap else 0
            if shadow is not None:
                # fold this chunk's appends into the host shadow (the
                # queue/log suffixes are append-only, so gathering them
                # from the LIVE carry — possibly a later in-flight
                # chunk's future — reads exactly the committed rows)
                with self._spans.span("host_probe", chunk=ordinal), \
                        self._timed("shadow"):
                    prev = shadow.log_n[0]
                    q_new = gather_rows(carry.q, np.arange(
                        n_init + prev, n_init + log_n, dtype=np.int32))
                    log_new = gather_rows(carry.log, np.arange(
                        prev, log_n, dtype=np.int32))
                    e_new = None
                    if ecap:
                        e_new = gather_rows(carry.elog, np.arange(
                            shadow.e_n[0], e_n, dtype=np.int32))
                    if corrupt_hook is not None and len(q_new) \
                            and corrupt_hook(ordinal, 1) == 0:
                        # injected lying chip (tests/bench): flip one
                        # bit in the fingerprints the device reported —
                        # consistently in the queue's fp column and the
                        # insert log's child key, like a chip whose
                        # hash unit miscomputed
                        q_new = q_new.copy()
                        log_new = log_new.copy()
                        q_new[:, model.packed_width + 1] ^= np.uint32(1)
                        log_new[:, 0] ^= np.uint32(1)
                    audited = audit_pol.should_audit(ordinal)
                    if audited:
                        self._metrics.inc("audits")
                        bad = audit_chunk_rows(
                            q_new, log_new, model.packed_width,
                            sound=self._sound)
                        if self._trace:
                            self._trace.emit("audit", chunk=ordinal,
                                             rows=int(len(q_new)),
                                             mismatches=bad, device=0)
                        if bad:
                            self._metrics.inc("audit_mismatches")
                            raise CorruptionError(
                                f"chunk {ordinal} audit: {bad} of "
                                f"{len(q_new)} frontier fingerprints "
                                "disagree with the host oracle's "
                                "re-execution — the chip is returning "
                                "wrong results",
                                device_index=0, mismatches=bad)
                    hits = shadow.note_chunk(0, q_new, log_new, e_new,
                                             q_head)
                    if audited:
                        # the oracle vouched for everything up to and
                        # including this fold: pin the replay boundary
                        # (and only a PASSED audit clears the
                        # consecutive-corruption counter — a lying chip
                        # syncs just fine)
                        shadow.audit_mark()
                        corruption_attempt = 0
                    self._shadow_chain_head = shadow.chain_head
                    if hits:
                        # host-tier re-probe: device-"fresh" keys the
                        # mirror already held (rediscoveries of evicted
                        # ranges); excluded from the unique counts
                        self._metrics.inc("host_probe_hits", hits)
                        self._metrics.set("host_tier_keys",
                                          shadow.host_tier_keys)
                if (self._autosave_path is not None
                        and self._autosave_every > 0
                        and ordinal % self._autosave_every == 0):
                    self._write_autosave(shadow, discoveries)
            new = log_n - cur["log_n"]  # this chunk's fresh inserts
            cur.update(q_size=q_tail - q_head, q_tail=q_tail,
                       log_n=log_n, e_n=e_n)
            # observed branching (raw / post-dedup), for tuning
            # model.branching_hint and the kraw/kmax buffer sizes
            metrics = self._metrics
            metrics.observe_max("vmax", vmax)
            metrics.observe_max("dmax", dmax)
            metrics.observe_max("rmax", rmax)
            # dedup telemetry: chunk-local counters (reset at dispatch,
            # so a zero-iteration speculative chunk contributes 0)
            if pdh:
                metrics.inc("predup_hits", pdh)
            if prb:
                metrics.inc("probe_rounds", prb)
            if cch:
                metrics.inc("cc_dedup_hits", cch)
            if size_key is not None:
                _SIZE_MEMO.merge_max(size_key, (vmax, dmax))
            self._state_count += gen
            # with the shadow on, len(generated) is authoritative — and
            # past a spill it is the ONLY correct count (the device log
            # includes host-filtered rediscoveries)
            self._unique_state_count = (len(generated)
                                        if shadow is not None
                                        else base_unique + log_n)
            trace = self._trace
            if trace:
                trace.emit(
                    "chunk", chunk=ordinal,
                    gen=gen, unique=self._unique_state_count,
                    q_size=q_tail - q_head, new=new,
                    # dedup hit-rate: generated children this chunk
                    # that were already in the visited table
                    dedup_hit=(round(1.0 - new / gen, 4) if gen else 0.0),
                    # hash-table load factor (growth trips near grow_at)
                    load=round(log_n / self._capacity, 4),
                    vmax=vmax, dmax=dmax,
                    # cross-chunk ring hits this chunk (fused+cc only;
                    # trace_report's fused summary totals them)
                    cc_hits=(cch if cc_cap else None),
                    # dispatch->ready / ready->materialized split (see
                    # _materialize_stats: device compute vs transfer)
                    device_s=(round(timing[0], 6) if timing else None),
                    xfer_s=(round(timing[1], 6) if timing else None))
            disc_fps = _combine64(disc_hi, disc_lo)
            for i, prop in enumerate(properties):
                if i in host_prop_idx:
                    continue  # host-evaluated: device bits are placeholders
                if disc_hit[i] and prop.name not in discoveries:
                    discoveries[prop.name] = int(disc_fps[i])
                    self._note_discovery(prop.name, int(disc_fps[i]))
            if seed_ovf is not None:
                if bool(jax.device_get(seed_ovf)):
                    raise RuntimeError(
                        "device hash table overflow while seeding; raise "
                        "tpu_options(capacity=...)")
                seed_ovf = None
            if xovf:
                raise RuntimeError(_XOVF_MESSAGE)
            if ovf:
                raise RuntimeError(
                    "device hash table probe overflow below the growth "
                    f"limit (capacity {self._capacity}); raise via "
                    "checker_builder.tpu_options(capacity=...)")
            if hcap_d and want_reps_now():
                # host properties are evaluated on the distinct-history
                # representatives the chunk loop logged (memoized per
                # key), so a shallow host counterexample still exits
                # early instead of waiting for full exhaustion. The
                # inline window is anchored at the chunk's DISPATCH-time
                # pulled count (h_base); under pipelining the rows
                # consumed since then are skipped by offset, so each
                # representative is evaluated exactly once and in the
                # same order as the synchronous path.
                fresh = h_n - self._h_pulled
                if fresh > 0:
                    with self._spans.span("props", chunk=ordinal), \
                            self._timed("posthoc"):
                        win = stats[tail0 + width3:].reshape(
                            (HIST_WINDOW, -1))
                        offset = self._h_pulled - h_base
                        take = max(0, min(fresh, HIST_WINDOW - offset))
                        if take:
                            wfp = _combine64(
                                win[offset:offset + take, -2],
                                win[offset:offset + take, -1])
                            self._eval_host_props_block(
                                win[offset:offset + take, :-2], wfp,
                                discoveries)
                            self._h_pulled += take
                        if fresh > take:
                            # more fresh keys than the inline window:
                            # pull the remainder standalone
                            self._pull_host_reps(carry, h_n, n_init,
                                                 discoveries)
                if hovf or h_n >= self._grow_at * hcap_d:
                    acts.add("hgrow")
                    hgrow_pend.update(
                        on=True, hovf=hgrow_pend["hovf"] or hovf,
                        h_n=max(hgrow_pend["h_n"], h_n))
                else:
                    self._hscan_tail = q_tail
            t_host_end = time.perf_counter()
            self._metrics.add_time("host_overlap", t_host_end - t0)
            # the umbrella host span (stats decode + shadow fold +
            # inline props): overlapped when chunk N+1 is in flight,
            # the pipeline bubble when nothing is
            self._spans.record("host", t0, t_host_end, chunk=ordinal)
            if kovf:
                # resize data for the drained handler; skip the exit
                # checks exactly like the synchronous retry `continue`
                kovf_pend[0] = max(kovf_pend[0], vmax)
                kovf_pend[1] = max(kovf_pend[1], dmax)
                kovf_pend[2] = max(kovf_pend[2], rmax)
                acts.add("kovf")
                return acts
            if (q_tail - q_head == 0
                    or len(discoveries) == prop_count
                    or (target is not None
                        and self._state_count >= target)
                    or self._cancel_event.is_set()
                    or self._pause_event.is_set()):
                acts.add("done")
            elif ecap and e_n >= ecap - max(kmax, fmax):
                acts.add("egrow")
            elif log_n >= grow_limit or q_tail > qcap - headroom:
                acts.add("grow")
            return acts

        def handle_hgrow() -> None:
            # grow the history-key table: proactively at the same
            # occupancy threshold as the fingerprint table (a near-full
            # open table crawls through thousands of probe rounds per
            # insert), or reactively on hovf. Re-seed from the logged
            # representatives; after an hovf the overflowing iteration
            # still committed, so rescan its queue span for the keys
            # that went unlogged (growing further if even the bigger
            # table overflows on that span). Runs only with the
            # pipeline drained: the reseed is sized by h_n, and an
            # in-flight chunk could log representatives past it.
            nonlocal carry, hcap, chunk_fn
            h_n = hgrow_pend["h_n"]
            q_tail = cur["q_tail"]
            with self._timed("hgrow"):
                while True:
                    new_hcap = self._posthoc_cap
                    while new_hcap * self._grow_at <= h_n:
                        new_hcap *= 4
                    if new_hcap == self._posthoc_cap:
                        new_hcap *= 4  # hovf w/o occupancy
                    hcap = self._posthoc_cap = new_hcap
                    carry = self._regrow_history_table(carry, h_n, hcap)
                    if not hgrow_pend["hovf"]:
                        break
                    carry, rescan_ovf = self._rescan_history(
                        carry, self._hscan_tail, q_tail, qcap, n_init,
                        discoveries)
                    if not rescan_ovf:
                        break
            self._hscan_tail = q_tail
            self._metrics.inc("hgrows")
            if self._trace:
                self._trace.emit("hgrow", hcap=hcap,
                                 hovf=hgrow_pend["hovf"], h_n=h_n)
            hgrow_pend.update(on=False, hovf=False, h_n=0)
            chunk_fn = mk_chunk("hgrow")

        def handle_kovf() -> None:
            # a batch overflowed one of the candidate buffers; nothing
            # was committed — resize the overflowed stage(s) to the
            # observed branching (at least doubling) and resume. rmax =
            # per-row max (sizes hint_eff), vmax = raw-valid max (sizes
            # kraw), dmax = post-dedup max (sizes kmax).
            nonlocal carry, chunk_fn, kraw, kmax, hint_eff
            vmax, dmax, rmax = kovf_pend
            before = (kraw, kmax, hint_eff)
            grew = False
            if hint_eff and rmax > hint_eff:
                hint_eff = max(hint_eff + 1, rmax + rmax // 4)
                if hint_eff >= model.max_actions:
                    hint_eff = 0  # degenerate: fall back to global
                grew = True
            if not hint_eff and vmax > kraw:
                kraw = min(max(kraw * 2,
                               -(-(vmax + vmax // 4) // 256) * 256),
                           fa)
                grew = True
            if dmax > kmax or not grew:
                kmax = min(max(kmax * 2,
                               -(-(dmax + dmax // 4) // 256) * 256),
                           kraw if not hint_eff
                           else fmax * hint_eff)
            kmax = min(kmax, kraw if not hint_eff
                       else fmax * hint_eff)
            if (kraw, kmax, hint_eff) == before:
                # wedged: rebuilding the identical program would abort
                # forever — reclassify as a capacity fault; the retry
                # envelope recovers with a k-buffer grown to its bound
                # (a pre-mutation abort lost no data)
                from .resilience import CandidateOverflowError
                raise CandidateOverflowError(
                    "candidate-buffer capacity overflow (kovf) wedged "
                    f"at kraw={kraw} kmax={kmax} hint={hint_eff} "
                    f"(observed vmax={vmax} dmax={dmax} rmax={rmax})",
                    vmax=vmax, dmax=dmax)
            self._metrics.inc("kovfs")
            if self._trace:
                self._trace.emit("kovf", kraw=kraw, kmax=kmax,
                                 vmax=kovf_pend[0], dmax=kovf_pend[1],
                                 rmax=kovf_pend[2])
            kovf_pend[:] = [0, 0, 0]
            chunk_fn = mk_chunk("kovf")
            carry = carry._replace(kovf=jnp.bool_(False))

        def handle_egrow() -> None:
            # cross-edge log full: quadruple it and resume
            nonlocal carry, chunk_fn, ecap
            with self._timed("grow"):
                new_elog = jnp.zeros((ecap * 4, 4), jnp.uint32)
                new_elog = jax.lax.dynamic_update_slice(
                    new_elog, carry.elog, (0, 0))
                ecap *= 4
                carry = carry._replace(elog=new_elog)
            if self._trace:
                self._trace.emit("egrow", ecap=ecap)
            chunk_fn = mk_chunk("egrow")

        def handle_grow() -> None:
            nonlocal carry, chunk_fn, qcap
            with self._timed("grow"):
                carry, qcap = self._grow_device(carry, qcap, n_init,
                                                headroom, insert_fn)
            self._metrics.inc("grows")
            if self._trace:
                self._trace.emit("grow", capacity=self._capacity,
                                 qcap=qcap)
            chunk_fn = mk_chunk("grow")

        spill_warned = [False]

        def warn_spill_eventually() -> None:
            # unsound EVENTUALLY + spill: a rediscovered duplicate
            # re-enqueues with its rediscovery path's pending bits, so
            # eventually verdicts may differ from an uncapped run — the
            # same path-dependence the unsound engine already documents,
            # but worth a one-time flag. sound_eventually() rejects
            # tiering up front instead.
            if spill_warned[0] or self._sound:
                return
            if any(p.expectation == Expectation.EVENTUALLY
                   for p in properties):
                import warnings
                warnings.warn(
                    "memory tiering with (unsound) eventually "
                    "properties: rediscovered duplicates re-enter the "
                    "frontier with rediscovery-path pending bits, so "
                    "eventually verdicts may differ from an uncapped "
                    "run (safety properties and fingerprint sets are "
                    "unaffected)", RuntimeWarning, stacklevel=2)
            spill_warned[0] = True

        def handle_spill(reason: str = "budget") -> None:
            # the memory wall, survived: growth would exceed the HBM
            # budget, so drain (the caller already did), evict the
            # coldest fingerprint-prefix ranges from the device table
            # IN PLACE (ops/hashtable.py table_evict_prefix — the host
            # tier already holds every key), and re-seed a fresh epoch
            # around the evicted table: the queue/log reset bounds the
            # epoch buffers, and the growth limit's preload term drops
            # by the evicted occupancy, making room to keep checking.
            nonlocal carry, chunk_fn, qcap, hcap, n_init, base_unique, \
                preload
            if int(min(self._grow_at * self._capacity,
                       self._capacity - headroom)) <= 0:
                # even an empty table cannot fit one iteration's
                # headroom under this budget: spilling again would spin
                # forever at zero progress
                self._capacity_terminal(RuntimeError(
                    f"device table budget (capacity {self._capacity}) "
                    f"cannot fit one iteration's headroom ({headroom}) "
                    "— raise tpu_options(max_capacity=...) or shrink "
                    "fmax/kmax"), shadow, discoveries)
            occupancy = preload + cur["log_n"]
            hot_budget = max(0, min(
                int((1.0 - spill_pol.frac) * occupancy),
                int(self._grow_at * self._capacity) - headroom - 1))
            plan = shadow.spill_plan(hot_budget)
            if plan is None:
                self._capacity_terminal(RuntimeError(
                    "host tier exhausted: range eviction cannot bring "
                    f"the device table (capacity {self._capacity}) "
                    "under its growth budget"), shadow, discoveries)
            warn_spill_eventually()
            with self._timed("spill"):
                mask = np.zeros((1 << SPILL_PREFIX_BITS,), bool)
                mask[sorted(shadow.evicted_prefixes)] = True
                khi, klo, ecount_d = _evict_jit()(
                    carry.key_hi, carry.key_lo, jnp.asarray(mask))
                ecount = int(jax.device_get(ecount_d))
                rows, ebs, fps = shadow.pending()
                init_rows2 = [rows[i] for i in range(rows.shape[0])]
                n_init = len(init_rows2)
                self._h_pulled = 0
                self._hscan_tail = n_init
                self._base_fps = shadow.hot_keys()
                base_unique = len(generated)
                preload = max(occupancy - ecount, 0)
                qcap = self._device_qcap(n_init, headroom)
                hcap = (self._posthoc_cap
                        if self._host_props and want_reps_now() else 0)
                with self._timed("seed"):
                    carry = seed_carry(
                        model, qcap, self._capacity, init_rows2,
                        np.asarray(ebs, np.uint32),
                        symmetry=self._symmetry or self._sound,
                        hcap=hcap, init_fps=[int(f) for f in fps],
                        ecap=ecap, table=(khi, klo))
                shadow.seed_epoch([pack_qrows(init_rows2, ebs, fps,
                                              model.packed_width)])
            cur.update(q_size=n_init, q_tail=n_init, log_n=0, e_n=0)
            hgrow_pend.update(on=False, hovf=False, h_n=0)
            kovf_pend[:] = [0, 0, 0]
            # fresh epoch: re-zero the cc ring lazily (its entries stay
            # sound across a spill, but the epoch invariant — ring ⊆
            # this epoch's committed inserts — is the simplest one to
            # keep airtight)
            cc_ring[0] = None
            self._metrics.inc("spills")
            if ecount:
                self._metrics.inc("evicted_keys", ecount)
            self._metrics.set("host_tier_keys", shadow.host_tier_keys)
            if self._trace:
                self._trace.emit("evict", prefixes=len(plan[0]),
                                 keys=ecount)
                self._trace.emit("spill", capacity=self._capacity,
                                 hot=preload, reason=reason,
                                 host_tier_keys=shadow.host_tier_keys)
            chunk_fn = mk_chunk("spill")

        def reseed() -> None:
            # post-fault recovery: rebuild the device state from the
            # shadow — a fresh carry seeded with the pending frontier,
            # the visited table re-inserted from the complete host
            # mirror, the chunk program recompiled for the new n_init.
            # Dedup is set-semantics, so the rebuilt run explores
            # exactly the remaining graph: discoveries and fingerprint
            # sets match an uninterrupted run (tests/test_resilience.py)
            nonlocal carry, chunk_fn, qcap, hcap, ecap, n_init, \
                base_unique, seed_ovf, preload
            rows, ebs, fps = shadow.pending()
            init_rows2 = [rows[i] for i in range(rows.shape[0])]
            n_init = len(init_rows2)
            self._h_pulled = 0
            self._hscan_tail = n_init
            # the device table re-seeds with the HOT set only (== the
            # whole mirror until ranges have been evicted): a recovery
            # must not re-promote what a spill just moved host-side
            hot = shadow.hot_keys()
            self._base_fps = hot
            base_unique = len(generated)
            preload = len(hot)
            while self._grow_at * self._capacity <= headroom + preload \
                    and spill_pol.can_grow(self._capacity):
                self._capacity *= 4
            if self._grow_at * self._capacity <= headroom + preload:
                plan = (shadow.spill_plan(
                    int(self._grow_at * self._capacity) - headroom - 1)
                    if spill_on else None)
                if plan is None:
                    self._capacity_terminal(RuntimeError(
                        "device hash table budget (max_capacity="
                        f"{spill_pol.max_capacity}) cannot hold the "
                        f"re-seeded hot set ({preload} keys)"),
                        shadow, discoveries)
                hot = shadow.hot_keys()
                self._base_fps = hot
                preload = len(hot)
                self._metrics.inc("spills")
                if plan[2]:
                    self._metrics.inc("evicted_keys", plan[2])
                self._metrics.set("host_tier_keys",
                                  shadow.host_tier_keys)
                if self._trace:
                    self._trace.emit("evict", prefixes=len(plan[0]),
                                     keys=plan[2])
                    self._trace.emit("spill", capacity=self._capacity,
                                     hot=preload, reason="reseed",
                                     host_tier_keys=shadow.host_tier_keys)
            qcap = self._device_qcap(n_init, headroom)
            hcap = (self._posthoc_cap
                    if self._host_props and want_reps_now() else 0)
            if self._sound:
                ecap = max(ecap, self._capacity)
            with self._timed("seed"):
                carry = seed_carry(
                    model, qcap, self._capacity, init_rows2,
                    np.asarray(ebs, np.uint32),
                    symmetry=self._symmetry or self._sound, hcap=hcap,
                    init_fps=[int(f) for f in fps], ecap=ecap)
                key_hi, key_lo, seed_ovf = self._bulk_insert_async(
                    insert_fn, carry.key_hi, carry.key_lo, hot)
                carry = carry._replace(key_hi=key_hi, key_lo=key_lo)
            shadow.seed_epoch([pack_qrows(init_rows2, ebs, fps,
                                          model.packed_width)])
            cur.update(q_size=n_init, q_tail=n_init, log_n=0, e_n=0)
            hgrow_pend.update(on=False, hovf=False, h_n=0)
            kovf_pend[:] = [0, 0, 0]
            # the old ring arrays may be poisoned by the fault that got
            # us here; re-zero lazily on the next dispatch
            cc_ring[0] = None
            chunk_fn = mk_chunk("retry")

        fault_attempt = 0
        spill_attempt = 0
        corruption_attempt = 0
        recover_delay: "Optional[float]" = None
        while True:
            try:
                if recover_delay is not None:
                    # back off BEFORE touching the device again (give a
                    # restarting backend/tunnel time to come up); the
                    # reseed itself runs inside the retry envelope, so
                    # a still-dead backend just burns another attempt
                    if recover_delay > 0:
                        time.sleep(recover_delay)
                    recover_delay = None
                    reseed()
                dispatch()
                while True:
                    if pipeline and len(inflight) == 1:
                        dispatch()
                    acts = process(*inflight.popleft())
                    if not acts:
                        if not inflight:
                            dispatch()
                        yield  # step boundary: one chunk consumed
                        continue
                    # a host intervention (or an exit) is due: drain the
                    # one speculative chunk first — under any
                    # device-visible stop condition it ran zero
                    # iterations and its stats replay idempotently; past
                    # a host-only exit it is one extra chunk of real
                    # (merged) exploration
                    while inflight:
                        acts |= process(*inflight.popleft())
                    if hgrow_pend["on"]:
                        handle_hgrow()
                        acts.discard("hgrow")
                    if "kovf" in acts:
                        handle_kovf()
                    elif "done" in acts:
                        break
                    elif "egrow" in acts:
                        handle_egrow()
                    elif "grow" in acts:
                        # budget-aware growth: quadruple while the HBM
                        # budget allows, spill to the host tier once it
                        # does not (capacity-terminal only when tiering
                        # is off)
                        if spill_pol.can_grow(self._capacity):
                            handle_grow()
                        elif spill_on and shadow is not None:
                            handle_spill("budget")
                        else:
                            self._capacity_terminal(RuntimeError(
                                "device table growth past tpu_options("
                                f"max_capacity={spill_pol.max_capacity})"
                                " needed and spill is disabled"),
                                shadow, discoveries)
                    dispatch()
                    yield  # step boundary: intervention handled
                break
            except BaseException as exc:
                if shadow is None:
                    raise
                kind = classify_error(exc)
                if kind is FaultKind.CAPACITY:
                    # a capacity fault inside the retry envelope: a
                    # spill-eligible one (RESOURCE_EXHAUSTED, table
                    # pressure, a wedged kovf) recovers by shrinking the
                    # device-resident set (or growing the k-buffer) and
                    # re-seeding; everything else — and an exhausted
                    # spill budget — takes the capacity-terminal ending
                    # (checkpoint + flight dump + actionable raise)
                    if not (spill_on and spill_eligible(exc)):
                        self._capacity_terminal(exc, shadow, discoveries)
                    inflight.clear()
                    spill_attempt += 1
                    if spill_attempt > spill_pol.max_spills:
                        self._capacity_terminal(exc, shadow, discoveries)
                    cand = find_candidate_overflow(exc)
                    if cand is not None:
                        # satellite: the fused/sharded-style kovf abort
                        # re-routes through the envelope with a GROWN
                        # k-buffer instead of raising to the user
                        kraw = fa
                        hint_eff = 0
                        kmax = min(max(kmax * 2, cand.dmax
                                       + cand.dmax // 4), fa)
                        self._metrics.inc("kovfs")
                        if self._trace:
                            self._trace.emit("kovf", kraw=kraw,
                                             kmax=kmax, recovered=True)
                    else:
                        # a real allocation/table fault names the HBM
                        # budget better than any option could: clamp
                        # growth at the current capacity and spill
                        if spill_pol.max_capacity is None \
                                or spill_pol.max_capacity > self._capacity:
                            spill_pol.max_capacity = self._capacity
                        plan = shadow.spill_plan(max(0, min(
                            int((1.0 - spill_pol.frac)
                                * self._grow_at * self._capacity),
                            int(self._grow_at * self._capacity)
                            - headroom - 1)))
                        if plan is None:
                            self._capacity_terminal(exc, shadow,
                                                    discoveries)
                        warn_spill_eventually()
                        self._metrics.inc("spills")
                        if plan[2]:
                            self._metrics.inc("evicted_keys", plan[2])
                        self._metrics.set("host_tier_keys",
                                          shadow.host_tier_keys)
                        if self._trace:
                            self._trace.emit("evict",
                                             prefixes=len(plan[0]),
                                             keys=plan[2])
                            self._trace.emit(
                                "spill", capacity=self._capacity,
                                hot=plan[1], reason="fault",
                                host_tier_keys=shadow.host_tier_keys,
                                error=f"{type(exc).__name__}: {exc}")
                    recover_delay = 0.0
                    continue
                if kind is FaultKind.CORRUPTION:
                    # the auditor caught the chip lying: every fold
                    # since the last audited boundary is suspect — roll
                    # the shadow back to it (corrupt mirror entries are
                    # undone, so the final digest matches an
                    # uncorrupted oracle run) and replay from there. On
                    # a single chip there is nothing to quarantine
                    # AROUND, so the replay re-executes under audit
                    # with a bounded consecutive-corruption budget; the
                    # sharded engine degrades around the liar instead
                    # (parallel/engine.py).
                    inflight.clear()
                    blamed = blamed_device(exc)
                    self._quarantined.add(blamed if blamed is not None
                                          else 0)
                    self._metrics.set("fault_device",
                                      blamed if blamed is not None
                                      else 0)
                    self._metrics.set("quarantined",
                                      len(self._quarantined))
                    shadow.rollback_to_mark()
                    self._unique_state_count = len(generated)
                    if self._trace:
                        self._trace.emit(
                            "corruption", device=blamed,
                            error=f"{type(exc).__name__}: {exc}")
                        self._trace.emit(
                            "quarantine",
                            device=blamed if blamed is not None else 0,
                            quarantined=len(self._quarantined))
                    if corruption_attempt >= max(1, policy.retries):
                        self._flight_dump("corruption")
                        raise RuntimeError(
                            "chunk audit failed "
                            f"{corruption_attempt + 1} consecutive "
                            "times on the only device — the chip is "
                            "persistently returning wrong results and "
                            "there is no healthy silicon to replay on "
                            f"({exc})") from exc
                    corruption_attempt += 1
                    recover_delay = 0.0
                    continue
                if kind is not FaultKind.TRANSIENT:
                    raise
                # transient backend fault: the in-flight futures are
                # poisoned (or superseded — their un-consumed work
                # replays from the shadow); drop them, back off,
                # re-seed, resume. Programming errors re-raise:
                # retrying reproduces them.
                inflight.clear()
                blamed = blamed_device(exc)
                if blamed is not None:
                    self._metrics.set("fault_device", blamed)
                if fault_attempt >= policy.retries:
                    self._resilience_degrade(exc, shadow, discoveries)
                fault_attempt += 1
                recover_delay = policy.delay(fault_attempt)
                self._metrics.inc("retries")
                if self._trace:
                    self._trace.emit(
                        "retry", attempt=fault_attempt,
                        delay=round(recover_delay, 3),
                        error=f"{type(exc).__name__}: {exc}",
                        device=blamed)
        q_size = cur["q_size"]
        q_tail, log_n, e_n = cur["q_tail"], cur["log_n"], cur["e_n"]

        if (self._pause_event.is_set()
                and not self._cancel_event.is_set()
                and q_size > 0
                and len(discoveries) < prop_count
                and not (target is not None
                         and self._state_count >= target)):
            # pause exit (the run did NOT finish): the pipeline drained
            # above; gather the pending frontier — the shadow holds it
            # when resilience is on, otherwise pull it from the live
            # carry exactly like the resumable-frontier path — and land
            # the resume_from-loadable pause checkpoint
            if shadow is not None:
                p_rows, p_ebs, p_fps = shadow.pending()
            else:
                # complete the host mirror from the device log first:
                # the checkpoint needs the full (fp -> parent) record
                self._mirror_carry = (carry.log, carry.log_n)
                self._ensure_mirror()
                head = int(jax.device_get(carry.q_head))
                tail = int(jax.device_get(carry.q_tail))
                width = model.packed_width
                pend = np.asarray(jax.device_get(carry.q[head:tail]))
                p_rows = pend[:, :width]
                p_ebs = pend[:, width]
                p_fps = _combine64(pend[:, width + 1],
                                   pend[:, width + 2])
            self._write_pause_checkpoint(p_rows, p_ebs, p_fps,
                                         discoveries)
            self._discovery_fps.update(discoveries)
            return

        if self._sound and q_size == 0 and self._resume_path is not None:
            import warnings
            warnings.warn(
                "resume_from() + sound_eventually(): the post-exhaustion "
                "lasso sweep is SKIPPED on resumed runs (the "
                "pre-checkpoint subgraph's edges are not in this run's "
                "device logs), so liveness cycles entered through "
                "pre-checkpoint states go unreported. Re-run without "
                "resume_from() for a cycle-complete liveness verdict.",
                RuntimeWarning, stacklevel=2)
        if (self._sound and q_size == 0 and self._resume_path is None
                and not self._symmetry
                and not self._cancel_event.is_set()):
            # (not under symmetry: a cross-branch cycle witness cannot
            # be replayed through concrete orbit members — the host DFS
            # disables its sweep the same way, dfs.py)
            # full exhaustion under sound mode: run the shared lasso
            # sweep (checker/lasso.py) over the node graph rebuilt from
            # the device logs — insert edges from the main log, cross
            # edges (dedup hits with pending bits) from the round-5 edge
            # log. Cycles entered via cross edges into explored branches
            # are liveness counterexamples neither the per-row flush nor
            # the reference can see. Skipped on resume: the
            # pre-checkpoint subgraph's edges are not in this run's logs.
            with self._timed("lasso"):
                if shadow is not None:
                    # after a mid-run recovery the device logs cover
                    # only the last epoch; the shadow spans the run
                    self._shadow_lasso_sweep(shadow, int(full_ebits),
                                             discoveries)
                else:
                    self._device_lasso_sweep(carry, int(q_tail),
                                             int(log_n), int(e_n),
                                             n_init, int(full_ebits),
                                             discoveries)

        if self._tpu_options.get("resumable"):
            # pull the pending frontier eagerly so save() needs no pinned
            # device buffers; the queue's cached fps (canonical under
            # symmetry) ride along so resume never recomputes them
            head = int(jax.device_get(carry.q_head))
            tail = int(jax.device_get(carry.q_tail))
            width = model.packed_width
            pend = np.asarray(jax.device_get(carry.q[head:tail]))
            self._resume_frontier = (
                pend[:, :width].copy(), pend[:, width].copy(),
                _combine64(pend[:, width + 1], pend[:, width + 2]))
        # the mirror (fp -> parent fp) stays device-resident until someone
        # needs it (path reconstruction, checkpointing): the log pull is
        # pure host-link cost, pointless for count-only runs. Keep only
        # the log fields so the table/queue HBM is freed promptly. With
        # the shadow on, the host mirror is already complete — no pull.
        self._mirror_carry = (None if shadow is not None
                              else (carry.log, carry.log_n))
        self._discovery_fps.update(discoveries)

    def _device_lasso_sweep(self, carry, q_tail: int, log_n: int,
                            e_n: int, n_init: int, full_mask: int,
                            discoveries: Dict[str, object]) -> None:
        """Rebuild the (state, pending-ebits) node graph from the device
        logs and run the shared SCC sweep. Node masks come from the
        queue's at-enqueue ebits column (queue row ``n_init + i`` aligns
        with log row ``i``); witnesses land in ``discoveries`` as
        explicit fingerprint paths (stem + one cycle lap)."""
        import jax

        from .lasso import lasso_sweep

        from .lasso import add_log_block, add_seed_nodes

        model = self._model
        width = model.packed_width
        node_fp: Dict[int, int] = {}
        node_parent: Dict[int, tuple] = {}
        node_mask: Dict[int, int] = {}
        node_edges: Dict[int, list] = {}
        add_seed_nodes(node_fp, node_parent, node_mask, self._base_fps,
                       self._orig_of, full_mask)
        log_h = np.asarray(jax.device_get(carry.log[:max(log_n, 1)]))
        eb_h = np.asarray(jax.device_get(
            carry.q[n_init:n_init + max(log_n, 1), width]))
        edges_h = np.asarray(jax.device_get(carry.elog[:max(e_n, 1)]))
        add_log_block(node_fp, node_parent, node_mask, node_edges,
                      log_h[:log_n], eb_h[:log_n], edges_h[:e_n])
        lasso_sweep(self._properties, discoveries, node_edges,
                    node_mask, node_parent, node_fp)
        if self._trace:
            self._trace.emit(
                "lasso", nodes=len(node_mask),
                edges=sum(len(v) for v in node_edges.values()))

    def _visit_reached(self) -> None:
        """Drive the CheckerVisitor over every reached state — the device
        log IS the visitation record, so the visits replay post-hoc from
        the host mirror. The previous design forced visitors onto the
        per-level engine, which pays the ~0.15 s standalone-dispatch
        floor PLUS a sync per BFS level.

        Replay walks the parent FOREST depth-first from a
        children-by-parent index, with an explicit spine of
        (state, action) steps. Each state's transition is matched ONCE
        against its parent's decoded state — O(states) model-replay
        steps — and a node's decoded state is DROPPED at backtrack, when
        its last pending child has been matched (the per-parent
        refcount is the exhausted child iterator), so resident decoded
        states are bounded by the live path depth, not the reached-set
        size. The children index also replaces the old wave-based
        deferral for cross-shard mirrors (a child preceding its parent
        in the concatenated per-shard logs simply waits in the index) —
        the waves rescanned every still-pending key per round, O(states
        squared) on adversarial orders. Each visit still materializes
        its own O(depth) Path from the spine — that is the visitor API.
        Visit order is the DFS order of the parent forest (parents
        before children); the log's sibling interleaving is not
        preserved, matching the reference's unordered multithreaded
        visitors."""
        from .path import NondeterministicModelError, Path

        self._ensure_mirror()
        model = self._model
        translate = self._symmetry or self._sound
        children: Dict[int, list] = {}
        roots: list = []
        for key, parent_key in self._generated.items():
            if parent_key is not None and parent_key in self._generated:
                children.setdefault(parent_key, []).append(key)
            else:
                # an init state (or a resumed root whose chain is
                # outside the mirror): full reconstruction, once
                roots.append(key)
        visited = 0
        peak = 0
        for root in roots:
            base = self._reconstruct_path(root)._steps
            # spine[i] = [state_i, action taken from state_i]; the last
            # entry's action is None (the path ends there)
            spine = [[base[-1][0], None]]
            base = base[:-1]
            self._visitor.visit(
                model, Path(base + [(spine[0][0], None)]))
            visited += 1
            iters = [iter(children.get(root, ()))]
            while iters:
                if self._cancel_event.is_set():
                    return
                key = next(iters[-1], None)
                if key is None:
                    # refcount exhausted: this node's decoded state is
                    # no longer needed by any pending child — drop it
                    iters.pop()
                    spine.pop()
                    continue
                fp = self._orig_of.get(key, key) if translate else key
                parent_state = spine[-1][0]
                found = None
                for action, state in model.next_steps(parent_state):
                    if model.fingerprint(state) == fp:
                        found = (action, state)
                        break
                if found is None:
                    raise NondeterministicModelError(
                        "Unable to extend a visitation path: no "
                        f"successor of the parent state has fingerprint "
                        f"{fp}. This usually means Model.actions or "
                        "Model.next_state vary across calls.")
                spine[-1][1] = found[0]
                spine.append([found[1], None])
                iters.append(iter(children.get(key, ())))
                peak = max(peak, len(base) + len(spine))
                self._visitor.visit(
                    model,
                    Path(base + [(s, a) for s, a in spine]))
                visited += 1
        # observability for the refcounted drop: the maximum number of
        # decoded states resident at once during the replay
        self._metrics.observe_max("visit_peak_resident", peak)
        if self._trace:
            self._trace.emit("visit", visited=visited,
                             peak_resident=peak)
        if visited != len(self._generated):  # pragma: no cover
            raise NondeterministicModelError(
                "visitation replay stalled: a parent chain in the "
                "mirror is cyclic or incomplete "
                f"({len(self._generated) - visited} unreached keys)")

    def _device_qcap(self, n_init: int, headroom: int) -> int:
        """Queue rows needed between growths: every enqueued state is
        unique, so the tail never exceeds n_init + grow_limit + one
        iteration's appends. A ``target_state_count`` additionally bounds
        total appends (generated >= inserted), which keeps the queue — by
        far the biggest device buffer, and its memset is real seed-time on
        a tunneled device — proportional to the requested work."""
        grow_limit = int(min(self._grow_at * self._capacity,
                             self._capacity - headroom))
        if self._target_state_count is not None:
            grow_limit = min(grow_limit,
                             self._target_state_count + headroom)
        return n_init + grow_limit + 2 * headroom

    # ------------------------------------------------------------------
    def _grow_device(self, carry, qcap: int, n_init: int, headroom: int,
                     insert_fn):
        """Quadruple table+log capacity, relocate the live queue region to
        the front of a correspondingly larger queue, and re-insert all
        known fingerprints from the device-resident log. No host round trip
        for the fingerprints themselves."""
        import jax
        import jax.numpy as jnp

        from ..ops.hashtable import table_insert as table_insert_local

        old_capacity = self._capacity
        self._capacity = old_capacity * 4
        new_qcap = self._device_qcap(n_init, headroom)

        hist_on = carry.hidx.shape[0] > 1

        def rebuild(q, q_head, q_tail, log, log_n, hidx):
            # copy the whole queue prefix into the larger buffer at the
            # same positions: the [0, tail) region doubles as the list of
            # every unique state's packed row (post-hoc property eval,
            # checkpointing), so consumed rows are retained
            nq = jnp.zeros((new_qcap, q.shape[1]), jnp.uint32)
            nq = jax.lax.dynamic_update_slice(nq, q, (0, 0))
            nlog = jnp.zeros((self._capacity, log.shape[1]), jnp.uint32)
            nlog = jax.lax.dynamic_update_slice(nlog, log, (0, 0))
            if hist_on:
                nh_idx = jnp.zeros((self._capacity,), jnp.int32)
                nh_idx = jax.lax.dynamic_update_slice(nh_idx, hidx, (0,))
            else:
                nh_idx = hidx
            # fresh table (2-D bucket-major, like the chunk carry);
            # re-insert every logged fingerprint
            from ..ops.hashtable import _BUCKET
            key_hi = jnp.zeros(
                (self._capacity // _BUCKET, _BUCKET), jnp.uint32)
            key_lo = jnp.zeros(
                (self._capacity // _BUCKET, _BUCKET), jnp.uint32)
            valid = jnp.arange(old_capacity, dtype=jnp.int32) < log_n
            _, key_hi, key_lo, ovf = table_insert_local(
                key_hi, key_lo, log[:, 0], log[:, 1], valid)
            return (nq, key_hi, key_lo, nlog, nh_idx, ovf)

        rebuild = jax.jit(rebuild)
        nq, key_hi, key_lo, nlog, nh_idx, ovf = rebuild(
            carry.q, carry.q_head, carry.q_tail, carry.log, carry.log_n,
            carry.hidx)
        if bool(jax.device_get(ovf)):
            raise RuntimeError("overflow while re-inserting during growth")
        # fingerprints known at seed time (inits, or a resumed snapshot)
        # are not in the device log; re-insert them from the host
        key_hi, key_lo = self._bulk_insert(insert_fn, key_hi, key_lo,
                                           self._base_fps)
        carry = carry._replace(
            q=nq, key_hi=key_hi, key_lo=key_lo, log=nlog, hidx=nh_idx)
        return carry, new_qcap

    # ------------------------------------------------------------------
    _HPULL_JIT = None

    @classmethod
    def _hpull_jit(cls):
        """Process-wide jitted gather of fresh history representatives:
        rows + witness fingerprints for ``hidx[start : start + bucket)``.
        A pure gather program — it avoids the standalone-dispatch floor a
        while_loop program (the old post-hoc reduction) paid per chunk."""
        if cls._HPULL_JIT is None:
            import jax
            import jax.numpy as jnp

            def fn(q, hidx, log, start, n_init, bucket):
                sel = hidx[jnp.minimum(start + jnp.arange(bucket),
                                       hidx.shape[0] - 1)]
                # the queue matrix carries 3 bookkeeping columns past the
                # packed row (ebits + cached fp)
                rows = q[jnp.minimum(sel, q.shape[0] - 1)][:,
                                                           :q.shape[1] - 3]
                # queue row i >= n_init is log entry i - n_init (queue
                # and log append in lockstep); seed rows never appear in
                # hidx (they are evaluated host-side at seed time).
                # ONE output array: each transferred leaf pays its own
                # ~100 ms tunnel round trip, so the witness-fp columns
                # ride the row matrix
                li = jnp.clip(sel - n_init, 0, log.shape[0] - 1)
                return jnp.concatenate(
                    [rows, log[li, 0:1], log[li, 1:2]], axis=1)

            cls._HPULL_JIT = jax.jit(fn, static_argnums=(5,))
        return cls._HPULL_JIT

    def _pull_host_reps(self, carry, h_n: int, n_init: int,
                        discoveries: Dict[str, int]) -> None:
        """Host-evaluate the distinct-history representatives the chunk
        loop logged since the last pull (memoized per key)."""
        import jax
        import jax.numpy as jnp

        if all(p.name in discoveries for _i, p in self._host_props):
            return
        start = self._h_pulled
        if h_n <= start:
            return
        count = h_n - start
        bucket = _bucket(count)
        out_d = self._hpull_jit()(
            carry.q, carry.hidx, carry.log,
            jnp.int32(start), jnp.int32(n_init), bucket)
        out_h = np.asarray(jax.device_get(out_d))
        wfp = _combine64(out_h[:count, -2], out_h[:count, -1])
        self._eval_host_props_block(out_h[:count, :-2], wfp, discoveries)
        self._h_pulled = h_n

    def _regrow_history_table(self, carry, h_n: int, hcap: int):
        """Re-seed a larger history-key table from the logged
        representatives' queue rows (one rare standalone dispatch)."""
        import jax
        import jax.numpy as jnp

        from ..ops.hash_kernel import fp64_device
        from ..ops.hashtable import table_insert

        model = self._model
        cols = getattr(model, "host_property_cols", None)
        off, hw = cols if cols is not None else (0, model.packed_width)

        def reseed(q, hidx, n):
            khi = jnp.zeros((hcap,), jnp.uint32)
            klo = jnp.zeros((hcap,), jnp.uint32)
            sel = jnp.minimum(hidx, q.shape[0] - 1)
            hhi, hlo = fp64_device(q[sel][:, off:off + hw])
            valid = jnp.arange(hidx.shape[0], dtype=jnp.int32) < n
            _, khi, klo, ovf = table_insert(khi, klo, hhi, hlo, valid)
            return khi, klo, ovf

        bucket = min(_bucket(max(h_n, 1)), carry.hidx.shape[0])
        khi, klo, ovf = jax.jit(reseed)(carry.q,
                                        carry.hidx[:bucket],
                                        jnp.int32(h_n))
        if bool(jax.device_get(ovf)):
            raise RuntimeError(
                "history-key table overflow while re-seeding after "
                "growth; raise tpu_options(hcap=...)")
        return carry._replace(hkey_hi=khi, hkey_lo=klo,
                              hovf=jnp.bool_(False))

    def _rescan_history(self, carry, start: int, end: int, qcap: int,
                        n_init: int, discoveries: Dict[str, int]):
        """Recovery after an in-chunk history-table overflow: the
        overflowing iteration committed its rows, but its unresolved
        keys were neither inserted nor logged. Re-dedup the queue span
        ``[start, end)`` against the (re-grown) table, insert the
        missing keys, and host-evaluate their representatives (rare
        standalone dispatch; duplicate evaluations are memoized).
        Returns ``(carry, overflowed)`` — on overflow the caller grows
        the table again and retries."""
        import jax
        import jax.numpy as jnp

        from .device_loop import shrink_indices
        from ..ops.hash_kernel import fp64_device
        from ..ops.hashtable import table_insert

        if end <= start:
            return carry, False
        model = self._model
        width = model.packed_width
        cols = getattr(model, "host_property_cols", None)
        off, hw = cols if cols is not None else (0, width)
        rmax = min(_bucket(end - start), qcap)
        s0 = min(start, qcap - rmax)

        # recovered representatives are logged into hidx too, so later
        # table growths (which re-seed from hidx) keep their keys; when
        # the log lacks room for the write window, skip logging — later
        # duplicates just re-log and re-evaluate (memoized), benign
        log_reps = (int(jax.device_get(carry.h_n)) + rmax
                    <= carry.hidx.shape[0])

        def fn(q, log, khi, klo, hidx, h_n, s0_, q_off, q_len):
            region = jax.lax.dynamic_slice(q, (s0_, 0),
                                           (rmax, width + 3))
            hhi, hlo = fp64_device(region[:, off:off + hw])
            idx = jnp.arange(rmax, dtype=jnp.int32)
            valid = (idx >= q_off) & (idx < q_off + q_len)
            ins, khi, klo, ovf = table_insert(khi, klo, hhi, hlo, valid)
            src = shrink_indices(ins, rmax)
            rows = region[src][:, :width]
            hcnt = ins.sum(dtype=jnp.int32)
            if log_reps:
                hidx = jax.lax.dynamic_update_slice(
                    hidx, (src + s0_).astype(jnp.int32), (h_n,))
                h_n = h_n + hcnt
            li = jnp.clip(src + s0_ - n_init, 0, log.shape[0] - 1)
            return (rows, log[li, 0], log[li, 1], hcnt, ovf, khi, klo,
                    hidx, h_n)

        (rows_d, whi_d, wlo_d, hcnt_d, ovf_d, khi, klo, hidx_d,
         h_n_d) = jax.jit(fn)(
            carry.q, carry.log,
            carry.hkey_hi, carry.hkey_lo, carry.hidx, carry.h_n,
            jnp.int32(s0), jnp.int32(start - s0), jnp.int32(end - start))
        hcnt, ovf = jax.device_get((hcnt_d, ovf_d))
        if bool(ovf):
            return carry, True
        hcnt = int(hcnt)
        if log_reps:
            carry = carry._replace(hidx=hidx_d, h_n=h_n_d)
            self._h_pulled += hcnt  # evaluated below, stay in lockstep
        if hcnt:
            n = min(_bucket(hcnt), rmax)
            rows_h, whi_h, wlo_h = jax.device_get(
                (rows_d[:n], whi_d[:n], wlo_d[:n]))
            wfp = _combine64(whi_h[:hcnt], wlo_h[:hcnt])
            self._eval_host_props_block(rows_h[:hcnt], wfp, discoveries)
        return carry._replace(hkey_hi=khi, hkey_lo=klo), False

    def _ensure_mirror(self) -> None:
        """Pull the device-resident (child fp, parent fp) log — lazily, on
        first use — to complete the host mirror used for path
        reconstruction and checkpointing."""
        mirror = getattr(self, "_mirror_carry", None)
        if mirror is None:
            return
        self._mirror_carry = None
        log_d, log_n_d = mirror
        import jax

        with self._spans.span("mirror"), self._timed("mirror_pull"):
            log_n = int(jax.device_get(log_n_d))
            if self._trace:
                self._trace.emit("mirror_pull", n=log_n)
            if not log_n:
                return
            # pull only the live prefix (pow2-padded slice jitted on
            # device); the log matrix rides ONE transfer
            n = min(_bucket(log_n), log_d.shape[0])
            _slice, _take, take_rows_fn, _take2 = _level_helpers()
            log = np.asarray(jax.device_get(take_rows_fn(log_d, n)))
            child = _combine64(log[:log_n, 0], log[:log_n, 1])
            parent = _combine64(log[:log_n, 2], log[:log_n, 3])
            self._generated.update(zip(child.tolist(), parent.tolist()))
            if self._symmetry or self._sound:
                orig = _combine64(log[:log_n, 4], log[:log_n, 5])
                self._orig_of.update(zip(child.tolist(), orig.tolist()))
            self._unique_state_count = len(self._generated)

    # ------------------------------------------------------------------
    def _run_levels(self) -> None:
        import jax
        import jax.numpy as jnp

        from ..ops.hashtable import make_table, table_insert

        model = self._model
        properties = self._properties
        prop_count = len(properties)
        width = model.packed_width
        from ..ops.expand import eventually_indices
        full_ebits = np.uint32(sum(1 << i
                                   for i in eventually_indices(properties)))
        generated = self._generated
        discoveries = self._discovery_fps
        # host ALWAYS/SOMETIMES bits are placeholders on device; host
        # EVENTUALLY discoveries come from the device's terminal flush
        # over host-corrected ebits, so their device bits are authoritative
        host_prop_idx = {i for i, p in self._host_props
                         if p.expectation != Expectation.EVENTUALLY}
        host_ev = self._host_ev
        target = self._target_state_count
        visitor = self._visitor

        level_fn = build_level_fn(model, symmetry=self._symmetry)
        insert_fn = _insert_jit()
        slice_fn, take_fn, take_rows_fn, take2_fn = _level_helpers()

        # --- init -------------------------------------------------------
        init_rows = self._seed_inits()
        if self._host_props:
            # the reference evaluates properties on every popped unique
            # state; our per-level insertion pass covers everything except
            # the seeds, handled here on the host states directly
            for s in model.init_states():
                if model.within_boundary(s):
                    self._eval_host_props_state(s, self._canon_fp(s),
                                                discoveries)

        key_hi, key_lo = make_table(self._capacity)
        key_hi, key_lo = self._bulk_insert(
            insert_fn, key_hi, key_lo, list(generated.keys()))

        # segments reference (rows, ebits, start, length) on device
        segments: deque = deque()
        for start in range(0, len(init_rows), self._max_segment):
            chunk = init_rows[start:start + self._max_segment]
            fcount = len(chunk)
            bucket = _bucket(fcount)
            rows = np.zeros((bucket, width), dtype=np.uint32)
            rows[:fcount] = np.stack(chunk)
            ebs = np.full((bucket,), full_ebits, dtype=np.uint32)
            if host_ev:
                for j in range(fcount):
                    ebs[j] &= ~np.uint32(
                        self._host_ev_clear_bits(chunk[j]))
            segments.append((jnp.asarray(rows), jnp.asarray(ebs), 0, fcount))

        # --- search loop ------------------------------------------------
        while segments:
            if len(discoveries) == prop_count:
                return
            if self._cancel_event.is_set() or self._pause_event.is_set():
                # raced loser (checker/race.py) or a pause request:
                # stop promptly (the per-level engine has no
                # checkpointable loop, so a pause here is a plain stop)
                return
            rows, ebs, start, length = segments.popleft()
            bucket = _bucket(length)
            if rows.shape[0] == bucket and start == 0:
                frontier, ebits = rows, ebs
            else:
                frontier, ebits = slice_fn(rows, ebs, start, bucket)
            fvalid = jnp.arange(bucket) < length

            while True:
                (key_hi, key_lo, comp_rows, comp_chi, comp_clo, comp_phi,
                 comp_plo, comp_eb, count_d, disc_hit_d, disc_hi_d,
                 disc_lo_d, gen_d, ovf_d, fp_hi_d, fp_lo_d, xovf_d,
                 comp_ohi, comp_olo) = \
                    level_fn(frontier, fvalid, ebits, key_hi, key_lo)

                # small pull: scalars + per-property discovery candidates
                (count, disc_hit, disc_hi, disc_lo, gen_count, overflow,
                 xovf) = jax.device_get((count_d, disc_hit_d, disc_hi_d,
                                         disc_lo_d, gen_d, ovf_d, xovf_d))
                if bool(xovf):
                    raise RuntimeError(_XOVF_MESSAGE)
                if not overflow:
                    break
                # a single level's batch outran the table headroom: grow,
                # rebuild from the host mirror (which excludes this level's
                # partial inserts), and retry the level cleanly
                self._capacity *= 4
                key_hi, key_lo = make_table(self._capacity)
                key_hi, key_lo = self._bulk_insert(
                    insert_fn, key_hi, key_lo, list(generated.keys()))
            count = int(count)
            self._state_count += int(gen_count)

            if visitor is not None:
                # host-fallback feature: materialize each frontier state's
                # path (requires the frontier fingerprints — pull them)
                phi, plo = jax.device_get((fp_hi_d, fp_lo_d))
                fps = _combine64(phi, plo)
                for k in range(length):
                    visitor.visit(
                        model, self._reconstruct_path(int(fps[k])))

            disc_fps = _combine64(disc_hi, disc_lo)
            for i, prop in enumerate(properties):
                if i in host_prop_idx:
                    continue  # host-evaluated: device bits are placeholders
                if disc_hit[i] and prop.name not in discoveries:
                    discoveries[prop.name] = int(disc_fps[i])
                    self._note_discovery(prop.name, int(disc_fps[i]))

            # mirror the newly inserted (fingerprint, parent) pairs:
            # 16 bytes per new state over the host link
            if count:
                chi_h, clo_h, phi_h, plo_h = jax.device_get(take_fn(
                    comp_chi, comp_clo, comp_phi, comp_plo, _bucket(count)))
                fp_c = _combine64(chi_h[:count], clo_h[:count])
                fp_p = _combine64(phi_h[:count], plo_h[:count])
                generated.update(zip(fp_c.tolist(), fp_p.tolist()))
                if self._symmetry:
                    ohi_h, olo_h = jax.device_get(take2_fn(
                        comp_ohi, comp_olo, _bucket(count)))
                    fp_o = _combine64(ohi_h[:count], olo_h[:count])
                    self._orig_of.update(zip(fp_c.tolist(),
                                             fp_o.tolist()))
                if self._host_props and any(
                        p.name not in discoveries
                        for _i, p in self._host_props):
                    # skip the row pull + decode once every host property
                    # already has its discovery
                    nb = _bucket(count)
                    rows_h = np.asarray(jax.device_get(take_rows_fn(
                        comp_rows, nb)))
                    ev_clear = (np.zeros((nb,), np.uint32)
                                if host_ev else None)
                    for k in range(count):
                        self._eval_host_props_row(
                            rows_h[k], int(fp_c[k]), discoveries)
                        if host_ev:
                            ev_clear[k] = self._host_ev_clear_bits(
                                rows_h[k])
                    if host_ev and ev_clear.any():
                        # correct the new states' ebits BEFORE they are
                        # enqueued: the device cannot evaluate these
                        # conditions, so their bits only clear here
                        comp_eb = self._clear_ebits_jit(nb)(
                            comp_eb, jnp.asarray(ev_clear))
            self._unique_state_count = len(generated)
            # one "level" event per frontier segment (a level splits
            # into segments of at most max_segment rows)
            self._metrics.inc("levels")
            if self._trace:
                self._trace.emit(
                    "level", level=int(self._metrics.get("levels")),
                    frontier=length, gen=self._state_count,
                    unique=self._unique_state_count)

            if len(discoveries) == prop_count:
                return
            if target is not None and self._state_count >= target:
                return

            # grow the table before it saturates
            if len(generated) > self._grow_at * self._capacity:
                self._capacity *= 4
                key_hi, key_lo = make_table(self._capacity)
                key_hi, key_lo = self._bulk_insert(
                    insert_fn, key_hi, key_lo, list(generated.keys()))

            # next frontier: the compacted child buffer, segmented lazily
            for seg_start in range(0, count, self._max_segment):
                seg_len = min(self._max_segment, count - seg_start)
                segments.append((comp_rows, comp_eb, seg_start, seg_len))

    # ------------------------------------------------------------------
    def _eval_host_props_state(self, state, fp: int,
                               discoveries: Dict[str, int]) -> None:
        for i, prop in self._host_props:
            if prop.name in discoveries:
                continue
            res = bool(prop.condition(self._model, state))
            if prop.expectation == Expectation.ALWAYS and not res:
                discoveries[prop.name] = fp
                self._note_discovery(prop.name, fp)
            elif prop.expectation == Expectation.SOMETIMES and res:
                discoveries[prop.name] = fp
                self._note_discovery(prop.name, fp)

    _CLEAR_JITS: dict = {}

    @classmethod
    def _clear_ebits_jit(cls, n: int):
        """Jitted per-bucket helper: clear host-corrected eventually bits
        on the first ``n`` rows of a compacted child-ebits buffer."""
        fn = cls._CLEAR_JITS.get(n)
        if fn is None:
            import jax
            import jax.numpy as jnp

            def clear(eb, mask):
                return eb.at[:n].set(eb[:n] & ~mask)

            fn = cls._CLEAR_JITS[n] = jax.jit(clear)
        return fn

    def _host_ev_clear_bits(self, row) -> int:
        """Bitmask of host-evaluated EVENTUALLY properties whose condition
        holds on this packed state (memoized with the other host props)."""
        results = self._host_props_results(row)
        bits = 0
        for (i, prop), res in zip(self._host_props, results):
            if prop.expectation == Expectation.EVENTUALLY and res:
                bits |= 1 << i
        return bits

    def _host_props_results(self, row) -> List[bool]:
        model = self._model
        key = model.host_property_key(row)
        results = self._host_prop_cache.get(key)
        if results is None:
            fns = self._host_fns
            if fns is not None:
                # packed fast path: the model evaluates each host
                # property straight off the packed row (e.g. ABD's
                # linearizability needs only the history columns) —
                # the full decode() built the whole actor/network state
                # per representative, ~4x the cost of the history walk
                results = [bool(fn(row)) for fn in fns]
            else:
                state = model.decode(row)
                results = [bool(prop.condition(model, state))
                           for _i, prop in self._host_props]
            self._host_prop_cache[key] = results
        return results

    def _eval_host_props_row(self, row, fp: int,
                             discoveries: Dict[str, int]) -> None:
        """Evaluate host properties on one newly inserted packed state,
        memoized by ``model.host_property_key`` (e.g. distinct histories
        recur across thousands of states)."""
        results = self._host_props_results(row)
        for (i, prop), res in zip(self._host_props, results):
            if prop.name in discoveries:
                continue
            if prop.expectation == Expectation.ALWAYS and not res:
                discoveries[prop.name] = fp
                self._note_discovery(prop.name, fp)
            elif prop.expectation == Expectation.SOMETIMES and res:
                discoveries[prop.name] = fp
                self._note_discovery(prop.name, fp)

    def _eval_host_props_block(self, rows, fps,
                               discoveries: Dict[str, int]) -> None:
        """Evaluate host properties over a whole pulled block of packed
        states at once: one vectorized key pass
        (``model.host_property_key_block`` when the model provides it),
        then one in-order scan that decodes/evaluates only cache-missing
        keys — the per-row slice+hash overhead of the old
        ``_eval_host_props_row`` loop was the dominant host cost per
        representative. Scan order is block order and stops at the first
        point every host property has a discovery, so the witnessing
        fingerprints are identical to the per-row path's."""
        host_props = self._host_props
        n = len(rows)
        if not n or not host_props or all(
                p.name in discoveries for _i, p in host_props):
            return
        model = self._model
        block_fn = getattr(model, "host_property_key_block", None)
        keys = (block_fn(rows) if block_fn is not None
                else [model.host_property_key(row) for row in rows])
        cache = self._host_prop_cache
        fns = self._host_fns
        for j in range(n):
            results = cache.get(keys[j])
            if results is None:
                row = rows[j]
                if fns is not None:
                    results = [bool(fn(row)) for fn in fns]
                else:
                    state = model.decode(row)
                    results = [bool(prop.condition(model, state))
                               for _i, prop in host_props]
                cache[keys[j]] = results
            fp = int(fps[j])
            for (i, prop), res in zip(host_props, results):
                if prop.name in discoveries:
                    continue
                if prop.expectation == Expectation.ALWAYS and not res:
                    discoveries[prop.name] = fp
                    self._note_discovery(prop.name, fp)
                elif prop.expectation == Expectation.SOMETIMES and res:
                    discoveries[prop.name] = fp
                    self._note_discovery(prop.name, fp)
            if all(p.name in discoveries for _i, p in host_props):
                return

    def _bulk_insert_async(self, insert_fn, key_hi, key_lo,
                           fps: List[int]):
        """(Re)insert known fingerprints without syncing; returns
        ``(key_hi, key_lo, overflow)`` with ``overflow`` a device bool
        scalar the caller must eventually check."""
        import jax.numpy as jnp
        overflow = None  # stays None when fps is empty
        chunk_size = 1 << 16
        for start in range(0, len(fps), chunk_size):
            chunk = fps[start:start + chunk_size]
            n = _bucket(len(chunk))
            arr = np.zeros((n,), dtype=np.uint64)
            arr[:len(chunk)] = np.asarray(chunk, dtype=np.uint64)
            valid = np.arange(n) < len(chunk)
            _, key_hi, key_lo, ovf = insert_fn(
                key_hi, key_lo,
                jnp.asarray((arr >> np.uint64(32)).astype(np.uint32)),
                jnp.asarray(arr.astype(np.uint32)),
                jnp.asarray(valid))
            overflow = ovf if overflow is None else (overflow | ovf)
        return key_hi, key_lo, overflow

    def _bulk_insert(self, insert_fn, key_hi, key_lo, fps: List[int]):
        """(Re)insert known fingerprints, e.g. after growth (synced)."""
        key_hi, key_lo, overflow = self._bulk_insert_async(
            insert_fn, key_hi, key_lo, fps)
        if overflow is not None and bool(overflow):
            raise RuntimeError(
                "device hash table overflow during bulk insert")
        return key_hi, key_lo

    def _canon_fp(self, state) -> int:
        """The fingerprint dedup works in canonical-orbit space under
        symmetry reduction, plain state space otherwise."""
        if self._symmetry:
            return self._model.fingerprint(self._symmetry_fn(state))
        return self._model.fingerprint(state)

    def generated_fingerprints(self):
        """All visited STATE fingerprints (pulls the device log if
        pending; under ``sound_eventually`` the node-keyed dedup record is
        translated back to state fingerprints)."""
        self._ensure_mirror()
        if self._sound:
            return {self._orig_of.get(k, k)
                    for k in self._generated.keys()}
        return set(self._generated.keys())

    # ------------------------------------------------------------------
    def save(self, path) -> None:
        """Checkpoint a finished (typically ``target_state_count``-bounded)
        run: the complete (fingerprint -> parent) search record plus the
        pending frontier rows, from which ``CheckerBuilder.resume_from``
        continues the search (SURVEY.md §5; the record is the TLC
        technique, `bfs.rs:314-342`)."""
        if not self.is_done():
            raise RuntimeError(
                "save() requires a finished run; bound it with "
                "target_state_count(...) to checkpoint mid-search")
        if self._resume_frontier is None:
            raise RuntimeError(
                "save() needs the pending frontier: run with "
                "tpu_options(resumable=True) on the device engine")
        self._ensure_mirror()
        rows, ebits, ffps = self._resume_frontier
        # the shared crash-safe writer (resilience.atomic_savez under
        # _checkpoint_save): mirror + pending frontier, with the
        # canonical/node-key -> original-fp translation and the
        # dedup-key semantics (symmetry/sound) in the metadata;
        # list-valued discoveries are explicit fingerprint paths
        # (lasso witnesses) and round-trip as lists
        self._checkpoint_save(path, rows, ebits, ffps,
                              self._discovery_fps)

    def _model_tag(self) -> str:
        return model_tag(self._model)

    def _read_checkpoint(self, path):
        """Open and verify ONE checkpoint file. Structural load errors
        (truncated archive, missing entries, bad JSON) and integrity-
        chain mismatches both raise one actionable RuntimeError, so the
        caller's generation-rollback logic has a single failure
        surface."""
        import json

        from .resilience import chain_integrity, payload_digest

        try:
            data = np.load(path)
            meta = json.loads(str(data["meta"]))
            arrays = {}
            for key in ("child", "parent", "rows", "ebits",
                        "state_count"):
                arrays[key] = data[key]
            for key in data.files:
                if key != "meta":
                    arrays[key] = data[key]
        except Exception as e:
            # anything the load raises — zipfile.BadZipFile for a
            # truncated archive, KeyError for missing entries, OSError,
            # json decode errors — means the file is not a usable
            # checkpoint; surface ONE actionable error instead of a
            # numpy/zipfile traceback
            raise RuntimeError(
                f"cannot resume from {path!r}: the "
                "checkpoint file is corrupt, truncated, or not a "
                f"Checker.save() file ({type(e).__name__}: {e}). "
                "Re-create it with save() on a finished resumable "
                "run.") from e
        want = meta.get("integrity")
        if want is not None and chain_integrity(
                payload_digest(arrays),
                meta.get("chain_head") or "") != want:
            raise RuntimeError(
                f"cannot resume from {path!r}: integrity chain "
                "mismatch — the payload no longer matches the sha256 "
                "it was written under (bit rot, tampering, or a "
                "partial write)")
        return data, meta

    def _load_checkpoint(self, discoveries: Dict[str, int]):
        """Seed state from a ``save()`` file: the mirror (and its
        canonical/node-key -> original-fp translation), the saved
        discoveries, and the pending frontier (whose rows become the seed
        'inits' — their parents are already in the mirror). Returns
        ``(rows, ebits, cache_fps)`` with ``cache_fps`` the frontier's
        queue-cached state fingerprints (canonical under symmetry).

        Every checkpoint is verified against its integrity chain
        (payload sha256 chained to the writing run's chunk-digest head
        — ``_checkpoint_save``) BEFORE anything is seeded; a corrupt,
        truncated, or tampered newest file rolls back to the previous
        autosave generation (``<path>.g1``) when one exists instead of
        resuming from garbage."""
        from .resilience import AUTOSAVE_PREV_SUFFIX

        try:
            data, meta = self._read_checkpoint(self._resume_path)
        except RuntimeError as first:
            prev = os.fspath(self._resume_path) + AUTOSAVE_PREV_SUFFIX
            if not os.path.exists(prev):
                raise
            # generation rollback: the newest autosave is unusable but
            # the one before it survived rotation — resume from that
            # (strictly older progress; the run re-explores the gap)
            data, meta = self._read_checkpoint(prev)
            if self._trace:
                self._trace.emit(
                    "corruption", device=None,
                    error=f"autosave rollback to {prev!r}: {first}")
        if meta["model"] != self._model_tag():
            raise RuntimeError(
                "checkpoint was written by a different model config: "
                f"saved {meta['model']!r}, resuming {self._model_tag()!r}")
        if (bool(meta.get("symmetry")) != self._symmetry
                or bool(meta.get("sound")) != self._sound):
            raise RuntimeError(
                "checkpoint dedup-key semantics do not match this run: "
                f"saved symmetry={meta.get('symmetry')} "
                f"sound={meta.get('sound')}, resuming "
                f"symmetry={self._symmetry} sound={self._sound}")
        child = data["child"].tolist()
        parent = [None if p == 0 else int(p)
                  for p in data["parent"].tolist()]
        self._generated.update(zip(child, parent))
        if "okeys" in data:
            self._orig_of.update(zip(data["okeys"].tolist(),
                                     data["ovals"].tolist()))
        self._state_count = int(data["state_count"])
        self._unique_state_count = len(self._generated)
        for name, fp in meta["discoveries"].items():
            discoveries[name] = ([int(f) for f in fp]
                                 if isinstance(fp, list) else int(fp))
        rows = [np.asarray(r, np.uint32) for r in data["rows"]]
        if "ffps" in data:
            fps = [int(f) for f in data["ffps"]]
        else:  # pre-round-4 checkpoint: plain mode only, recompute
            from ..fingerprint import fp64_words
            fps = [fp64_words(r.tolist()) for r in rows]
        return rows, np.asarray(data["ebits"], np.uint32), fps

    def _reconstruct_path(self, fp: int) -> Path:
        self._ensure_mirror()
        if not (self._symmetry or self._sound):
            return super()._reconstruct_path(fp)
        # the mirror chain is canonical; translate each node to the
        # ORIGINAL explored state's fingerprint (recorded device-side), so
        # the replayed trace is a concrete path — the DFS engine's
        # enqueue-original rule (`dfs.rs:260-285`) carried to the mirror
        fingerprints: deque = deque()
        nxt = fp
        while nxt in self._generated:
            fingerprints.appendleft(self._orig_of.get(nxt, nxt))
            parent = self._generated[nxt]
            if parent is None:
                break
            nxt = parent
        return Path.from_fingerprints(self._model, fingerprints)
