"""Checker engines and results interface.

Mirrors the reference's re-export surface (`/root/reference/src/checker.rs`):
``CheckerBuilder``, ``Checker``, ``Path``, visitors, and symmetry-reduction
helpers — plus the TPU-native engine entry point.
"""

from .builder import Checker, CheckerBuilder
from .path import NondeterministicModelError, Path
from .representative import Representative, RewritePlan, rewrite_value
from .visitor import CheckerVisitor, PathRecorder, StateRecorder, as_visitor

__all__ = [
    "Checker",
    "CheckerBuilder",
    "CheckerVisitor",
    "NondeterministicModelError",
    "Path",
    "PathRecorder",
    "Representative",
    "RewritePlan",
    "StateRecorder",
    "as_visitor",
    "rewrite_value",
]
