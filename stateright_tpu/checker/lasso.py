"""Post-exhaustion lasso sweep for ``sound_eventually`` checking.

Around any cycle of the (state, pending-ebits) node graph the pending
mask is invariant (bits only ever clear along a path and the cycle
returns to the same node), so a cyclic SCC whose mask still holds bit
``i`` is an infinite run on which property ``i`` never holds — a
liveness counterexample the reference cannot see at all
(`/root/reference/src/checker/bfs.rs:239-256`). The sweep must run at
exhaustion only (an early exit leaves the node graph partial).

Shared by the host DFS engine (which feeds it the node maps it built
during the search) and the device engines (which rebuild the maps from
the device-resident insert log plus the round-5 cross-edge log);
witnesses come back as concrete state-fingerprint paths — stem
(init -> cycle entry, via the parent map) plus one full lap.
"""

from __future__ import annotations

from typing import Dict, List

from ..core import Expectation


def add_log_block(node_fp: Dict[int, int], node_parent: Dict[int, tuple],
                  node_mask: Dict[int, int],
                  node_edges: Dict[int, list],
                  log_block, eb_block, elog_block) -> None:
    """Merge one device log block into the node-graph maps.

    ``log_block`` rows are the device engines' sound-mode log layout —
    [child node key hi/lo, parent node key hi/lo, original state fp
    hi/lo]; ``eb_block`` is the matching slice of the queue's at-enqueue
    ebits column (log row i aligns with queue row n_init + i);
    ``elog_block`` rows are [parent node key hi/lo, child node key
    hi/lo] cross edges. Shared by the single-chip and sharded engines so
    the layout is interpreted in exactly one place.
    """

    def comb(hi, lo):
        import numpy as np
        return (np.asarray(hi).astype(np.uint64) << np.uint64(32)) \
            | np.asarray(lo).astype(np.uint64)

    ck = comb(log_block[:, 0], log_block[:, 1])
    pk = comb(log_block[:, 2], log_block[:, 3])
    of = comb(log_block[:, 4], log_block[:, 5])
    for i in range(log_block.shape[0]):
        c_k = int(ck[i])
        node_fp[c_k] = int(of[i])
        node_parent.setdefault(c_k, (int(pk[i]), int(of[i])))
        mask = int(eb_block[i])
        if mask:
            node_mask[c_k] = mask
            node_edges.setdefault(int(pk[i]), []).append(c_k)
    ep = comb(elog_block[:, 0], elog_block[:, 1])
    ec = comb(elog_block[:, 2], elog_block[:, 3])
    for i in range(elog_block.shape[0]):
        node_edges.setdefault(int(ep[i]), []).append(int(ec[i]))


def add_seed_nodes(node_fp: Dict[int, int],
                   node_parent: Dict[int, tuple],
                   node_mask: Dict[int, int],
                   seed_keys, orig_of: Dict[int, int],
                   full_mask: int) -> None:
    """Register the init nodes (roots of the node graph)."""
    for key in seed_keys:
        ofp = orig_of.get(key, key)
        node_fp[key] = ofp
        node_parent[key] = (None, ofp)
        if full_mask:
            node_mask[key] = full_mask


def lasso_sweep(properties, discoveries: Dict[str, object],
                node_edges: Dict[int, List[int]],
                node_mask: Dict[int, int],
                node_parent: Dict[int, tuple],
                node_fp: Dict[int, int]) -> None:
    """Iterative-Tarjan SCC pass; for every cyclic SCC whose invariant
    mask still holds an undiscovered eventually-property bit, record a
    stem+lap fingerprint path in ``discoveries``.

    ``node_edges``: node -> successor nodes (insert AND cross/dedup-hit
    edges — completeness needs both). ``node_mask``: node -> pending
    bits at enqueue (nodes with mask 0 may be omitted). ``node_parent``:
    node -> (parent node or None, the node's state fingerprint).
    ``node_fp``: node -> state fingerprint.
    """
    want = [i for i, p in enumerate(properties)
            if p.expectation == Expectation.EVENTUALLY
            and p.name not in discoveries]
    if not want:
        return

    # iterative Tarjan
    index: Dict[int, int] = {}
    low: Dict[int, int] = {}
    on_stack: set = set()
    stack: List[int] = []
    counter = 0
    for root in list(node_mask.keys()):
        if root in index:
            continue
        work = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter
                counter += 1
                stack.append(node)
                on_stack.add(node)
            nbrs = node_edges.get(node, ())
            advanced = False
            for j in range(pi, len(nbrs)):
                w = nbrs[j]
                if w not in index:
                    work[-1] = (node, j + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                cyclic = len(comp) > 1 or node in node_edges.get(node, ())
                if cyclic:
                    mask = node_mask.get(comp[0], 0)
                    hit = [i for i in want
                           if (mask >> i) & 1
                           and properties[i].name not in discoveries]
                    if hit:
                        witness = _lasso_witness(comp, node_edges,
                                                 node_parent, node_fp)
                        for i in hit:
                            discoveries[properties[i].name] = witness
            if work:
                pnode = work[-1][0]
                low[pnode] = min(low[pnode], low[node])


def _lasso_witness(comp: List[int], node_edges, node_parent,
                   node_fp) -> List[int]:
    """Concrete fingerprint path: init -> SCC entry, then one lap of a
    cycle through the entry (every recorded edge is a real transition)."""
    entry = comp[0]
    chain: List[int] = []
    k = entry
    while k is not None:
        pk, fp = node_parent[k]
        chain.append(fp)
        k = pk
    chain.reverse()
    compset = set(comp)
    frontier = [(entry, [])]
    visited = set()
    while frontier:
        node, path = frontier.pop()
        for w in node_edges.get(node, ()):
            if w == entry:
                return (chain + [node_fp[x] for x in path]
                        + [node_fp[entry]])
            if w in compset and w not in visited:
                visited.add(w)
                frontier.append((w, path + [w]))
    return chain  # unreachable: a cyclic SCC always closes a lap
