"""Checker builder and results interface.

Reference: ``CheckerBuilder`` (`/root/reference/src/checker.rs:35-179`) and the
``Checker`` trait (`src/checker.rs:185-338`). ``spawn_tpu`` is the new
TPU-native strategy added alongside the reference's ``spawn_bfs``/``spawn_dfs``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..core import Expectation, Model
from .path import Path
from .visitor import as_visitor


class CheckerBuilder:
    """Builder for checking runs (`src/checker.rs:35-179`)."""

    def __init__(self, model: Model):
        self.model = model
        self.symmetry_fn_: Optional[Callable[[Any], Any]] = None
        self.target_state_count_: Optional[int] = None
        self.thread_count_: int = 1
        self.visitor_ = None
        self.tpu_options_: dict = {}
        self.resume_path_ = None
        self.sound_eventually_: bool = False

    def symmetry(self) -> "CheckerBuilder":
        """Enable symmetry reduction via ``state.representative()``
        (`src/checker.rs:150-154`)."""
        return self.symmetry_fn(lambda state: state.representative())

    def symmetry_fn(self, representative: Callable[[Any], Any]) -> "CheckerBuilder":
        self.symmetry_fn_ = representative
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        """The checker may exceed this count but never stops short of it
        while more states exist (`src/checker.rs:163-167`)."""
        self.target_state_count_ = count if count > 0 else None
        return self

    def threads(self, thread_count: int) -> "CheckerBuilder":
        """Host-engine worker count (`src/checker.rs:171-173`). With
        ``thread_count > 1``, ``spawn_bfs`` runs the level-synchronous
        multi-process engine and ``spawn_dfs`` the job-market
        multi-process DFS (the GIL rules out shared-memory threads;
        workers are separate processes sharing the visited table)."""
        self.thread_count_ = thread_count
        return self

    def visitor(self, visitor) -> "CheckerBuilder":
        self.visitor_ = as_visitor(visitor)
        return self

    def sound_eventually(self) -> "CheckerBuilder":
        """Include the pending ``eventually`` bits in the dedup identity.

        The reference accepts missed ``eventually`` counterexamples when a
        state is revisited with different pending bits (the documented
        FIXME at `/root/reference/src/checker/bfs.rs:239-244`; pinned by
        its ``fixme_can_miss_counterexample_when_revisiting_a_state``
        test). This opt-in goes beyond the reference: dedup works on
        (state, pending-bits) NODES, so DAG rejoins can no longer mask a
        counterexample, at the cost of exploring a state once per distinct
        pending-bits value (``unique_state_count`` counts nodes). The DFS
        engine is additionally lasso-COMPLETE (without symmetry
        reduction): expansion rejoining the CURRENT path with bits still
        pending reports immediately, and a post-exhaustion SCC sweep
        over the explored (state, pending-bits) node graph reports
        cycles entered via cross edges into already-explored branches —
        around any node-graph cycle the pending mask is invariant, so a
        cyclic SCC with bit ``i`` still set is an infinite run on which
        property ``i`` never holds. Under symmetry reduction only the
        on-path check runs (a cross-branch lap cannot be replayed
        through concrete orbit members). Supported by ``spawn_bfs``
        (single worker), ``spawn_dfs``, and the single-chip
        ``spawn_tpu`` device mode. A model with no ``eventually``
        properties is unaffected."""
        self.sound_eventually_ = True
        return self

    def tpu_options(self, **options) -> "CheckerBuilder":
        """Tuning knobs for ``spawn_tpu`` (table capacity, batch caps,
        mesh selection, ...). Notable:

        * ``fused`` (default ``'auto'``) selects the fused Pallas
          expand→fingerprint→dedup kernel (``ops/fused.py``; README
          § Fused device kernel). ``'auto'`` tries the Pallas build on
          TPU backends and, on any lowering/compile failure (the `axon`
          backend is experimental), classifies the error, emits a
          ``fused_fallback`` trace event plus the ``fused_fallbacks``
          metric, and runs the staged path — never a hard error; off
          TPU, ``'auto'`` resolves to staged without an attempt
          (``fused_attempt=True`` forces the attempt through the
          interpreter — a testing/debug knob). ``True`` forces the
          fused build (interpret mode off TPU — how the CPU parity
          suite pins bit-identical behavior); ``False`` forces staged.
          Configurations outside the fused support matrix
          (sound-eventually, host-evaluated properties, ``hint=``) stay
          staged under ``'auto'`` and raise under ``True``;
        * ``pipeline`` (default ``True``) double-buffers the chunk
          loop — chunk N+1 is dispatched while the host consumes chunk
          N's stats; ``pipeline=False`` forces the synchronous loop
          (debugging, latency A/B — observable results are identical
          either way, measurable via ``profile()``'s overlap timers);
        * ``trace=<path | file | callable | list>`` enables the
          structured run-trace: every engine (host engines included)
          emits timestamped JSONL events (chunk completed, growth and
          resize interventions, compiles, discoveries, ...) to the
          sink. Format and the metrics key glossary: README.md
          § Observability and ``stateright_tpu.obs``;
        * ``flight`` (default ``True``) keeps the **flight recorder**
          on: a bounded ring of the most recent trace events (no sink
          needed) dumped as a JSONL postmortem artifact on any engine
          error, watchdog expiry, exhausted retries, and each
          degradation rung — ``checker.flight_path()`` names the
          artifact, ``tools/trace_report.py`` reads it. ``flight=N``
          resizes the ring, ``flight=False`` disables it (restoring
          the zero-cost NULL trace), ``flight_path=...`` pins the
          artifact destination (default: next to ``autosave``, else
          the temp dir);
        * ``profile_dir=path`` captures a ``jax.profiler`` trace of
          the whole run into the directory (TensorBoard/Perfetto) —
          the deep-dive tier above the per-chunk ``device_s``/
          ``xfer_s`` attribution in ``profile()``;
        * resilience (README § Resilience, ``checker/resilience.py``):
          ``retries=N`` retries a transient backend fault (UNAVAILABLE,
          DEADLINE_EXCEEDED, tunnel resets) up to N consecutive times,
          re-seeding the device from the host-side shadow;
          ``backoff=s`` is the first retry delay (exponential,
          jittered); ``chunk_deadline=s`` converts a hung chunk sync
          into a classified transient fault (watchdog);
          ``autosave=path`` + ``autosave_interval=chunks`` checkpoint
          progress periodically and on exhausted retries (resume via
          ``resume_from``); ``retry_seed=n`` pins the backoff jitter
          to a private RNG stream (deterministic fault tests);
          ``degrade=True`` (default) + ``min_mesh=1`` gate the mesh
          degradation ladder — a sharded run that exhausts its retries
          (or whose faults pin on one chip) re-routes the pending
          frontier onto the surviving power-of-two device subset,
          D -> D/2 -> ... -> single chip, before any host fallback;
          ``failover=False`` opts a raced run out of the final
          device->host rung."""
        self.tpu_options_.update(options)
        return self

    def resume_from(self, path) -> "CheckerBuilder":
        """Resume a ``spawn_tpu`` run from a checkpoint written by
        ``Checker.save`` (the TLC-style fingerprint record + pending
        frontier; SURVEY.md §5 checkpoint note)."""
        self.resume_path_ = path
        return self

    def spawn_bfs(self) -> "Checker":
        """Breadth-first host engine (`src/checker.rs:116-130`); with
        ``threads(n > 1)``, multi-process over frontier blocks."""
        if self.thread_count_ > 1 and self.visitor_ is None:
            from .parallel_bfs import ParallelBfsChecker
            return ParallelBfsChecker(self)
        from .bfs import BfsChecker
        return BfsChecker(self)

    def spawn_dfs(self) -> "Checker":
        """Depth-first host engine (`src/checker.rs:132-145`). The only host
        engine supporting symmetry reduction, as in the reference; with
        ``threads(n > 1)``, the job-market multi-process DFS
        (`dfs.rs:76-159`)."""
        if (self.thread_count_ > 1 and self.visitor_ is None
                and not self.sound_eventually_):
            from .parallel_dfs import ParallelDfsChecker
            return ParallelDfsChecker(self)
        from .dfs import DfsChecker
        return DfsChecker(self)

    def spawn_tpu(self) -> "Checker":
        """TPU-native engine: vmapped frontier expansion with device-resident
        fingerprint dedup. Requires the model to implement the
        :class:`~stateright_tpu.models.packed.PackedModel` protocol.
        With ``tpu_options(mesh=jax.sharding.Mesh(...))`` the search runs
        SPMD over the mesh: frontier, visited table and logs sharded by
        fingerprint prefix, children routed to owner shards over ICI."""
        from .race import RacingChecker, race_eligible
        if race_eligible(self):
            # small-model latency: the device engine's fixed dispatch +
            # tunnel-sync costs dwarf tiny models, so a budgeted host BFS
            # races the device run and the first finisher wins (see
            # checker/race.py); tpu_options(race=False) opts out. Mesh
            # runs race only on explicit race=True (the device lane is
            # then the sharded engine).
            return RacingChecker(self)
        if "mesh" in self.tpu_options_:
            from ..parallel.engine import ShardedTpuChecker
            return ShardedTpuChecker(self)
        from .tpu import TpuChecker
        return TpuChecker(self)

    def serve(self, address, engine: str = "bfs") -> "Checker":
        """Start the Explorer web service (`src/checker.rs:99-114`).
        ``engine="tpu"`` runs the device engine behind the browser (the
        reference always spawns BFS, `explorer.rs:85-88`)."""
        from .explorer import serve as explorer_serve
        return explorer_serve(self, address, engine=engine)


class Checker:
    """Results interface shared by all engines (`src/checker.rs:185-338`)."""

    # --- engine-provided -------------------------------------------------
    def model(self) -> Model:
        raise NotImplementedError

    def state_count(self) -> int:
        """Total states generated including repeats (>= unique)."""
        raise NotImplementedError

    def unique_state_count(self) -> int:
        raise NotImplementedError

    def discoveries(self) -> Dict[str, Path]:
        raise NotImplementedError

    def join(self) -> "Checker":
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    # --- shared helpers --------------------------------------------------
    def error(self) -> Optional[BaseException]:
        """The engine's failure, if any (overridden by engines)."""
        return None

    def profile(self) -> Dict[str, float]:
        """Snapshot of the run's metrics registry (phase timers,
        counters, observed maxima). Key meanings are documented once,
        in ``stateright_tpu.obs.GLOSSARY`` (rendered in README.md
        § Observability). Engines without instrumentation report {}."""
        return {}

    def _metrics_summary(self, elapsed: float) -> str:
        """One compact ``# key=value ...`` line from the metrics
        registry (empty when there is nothing beyond the raw timer)."""
        prof = self.profile()
        parts: List[str] = []
        if "engine" in prof:
            parts.append(f"engine={prof['engine']}")
        for key in ("chunks", "levels", "jobs", "grows", "hgrows",
                    "kovfs", "compiles", "retries", "failovers",
                    "degrades", "autosaves"):
            if prof.get(key):
                parts.append(f"{key}={int(prof[key])}")
        if prof.get("degrades"):
            # a degraded run finished on fewer chips; name the final
            # width and the blamed device so the line says WHY
            if "mesh_shards" in prof:
                parts.append(f"mesh={int(prof['mesh_shards'])}")
            if "fault_device" in prof:
                parts.append(f"fault_device={int(prof['fault_device'])}")
        if prof.get("fused_unsupported"):
            # a fused='auto' run stayed staged because the config is
            # outside the kernel's support matrix — name the reason
            # (also a one-time fused_unsupported trace event)
            reason = getattr(self, "_fused_unsupported_reason", None)
            parts.append("fused=unsupported"
                         + (f" ({reason})" if reason else ""))
        if elapsed > 0 and "sync_stall" in prof:
            parts.append(f"stall={prof['sync_stall'] / elapsed:.0%}")
        if elapsed > 0 and "host_overlap" in prof:
            parts.append(
                f"overlap={prof['host_overlap'] / elapsed:.0%}")
        if "shard_balance" in prof:
            parts.append(f"shard_balance={prof['shard_balance']}")
        return "# " + " ".join(parts) if parts else ""

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def report(self, w) -> "Checker":
        """Periodic status lines + discovery summary (`src/checker.rs:217-242`).

        Emits ``Checking. states=N, unique=N`` once per second while running,
        then ``Done. states=N, unique=N, sec=S[, rate=R/s]``, a compact
        ``# chunks=... stall=...`` metrics line when the engine recorded
        any (key glossary: ``stateright_tpu.obs.GLOSSARY``), and one
        block per discovery.
        """
        start = time.monotonic()
        if not self.is_done():
            w.write(f"Checking. states={self.state_count()}, "
                    f"unique={self.unique_state_count()}\n")
            self._start_background()
            last_print = time.monotonic()
            while not self.is_done():
                time.sleep(0.01)
                now = time.monotonic()
                if now - last_print >= 1.0:
                    w.write(f"Checking. states={self.state_count()}, "
                            f"unique={self.unique_state_count()}\n")
                    last_print = now
        err = self.error()
        if err is not None:
            raise err
        elapsed = time.monotonic() - start
        rate = (f", rate={self.state_count() / elapsed:.0f}/s"
                if elapsed > 0.1 else "")
        w.write(f"Done. states={self.state_count()}, "
                f"unique={self.unique_state_count()}, "
                f"sec={int(elapsed)}{rate}\n")
        summary = self._metrics_summary(elapsed)
        if summary:
            w.write(summary + "\n")
        for name, path in self.discoveries().items():
            w.write(f'Discovered "{name}" '
                    f"{self.discovery_classification(name)} {path}")
        return self

    def _start_background(self) -> None:
        """Hook for engines that can make progress concurrently."""
        pass

    def discovery_classification(self, name: str) -> str:
        prop = self.model().property(name)
        if prop.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY):
            return "counterexample"
        return "example"

    def assert_properties(self) -> None:
        """Examples exist for every ``sometimes``; no counterexamples for any
        ``always``/``eventually`` (`src/checker.rs:256-267`)."""
        for p in self.model().properties():
            if p.expectation == Expectation.SOMETIMES:
                self.assert_any_discovery(p.name)
            else:
                self.assert_no_discovery(p.name)

    def _raise_engine_error(self) -> None:
        """A crashed engine must not read as "checked clean"."""
        err = self.error()
        if err is not None:
            raise err

    def assert_any_discovery(self, name: str) -> Path:
        found = self.discovery(name)
        if found is not None:
            return found
        self._raise_engine_error()
        assert self.is_done(), (
            f'Discovery for "{name}" not found, but model checking is '
            "incomplete.")
        raise AssertionError(f'Discovery for "{name}" not found.')

    def assert_no_discovery(self, name: str) -> None:
        found = self.discovery(name)
        if found is not None:
            raise AssertionError(
                f'Unexpected "{name}" {self.discovery_classification(name)} '
                f"{found}Last state: {found.last_state()!r}\n")
        self._raise_engine_error()
        assert self.is_done(), (
            f'Discovery for "{name}" not found, but model checking is '
            "incomplete.")

    def assert_discovery(self, name: str, actions: Sequence[Any]) -> None:
        """Panics unless ``actions`` also witness the property
        (`src/checker.rs:291-338`)."""
        additional_info: List[str] = []
        found = self.assert_any_discovery(name)
        model = self.model()
        for init_state in model.init_states():
            path = Path.from_actions(model, init_state, actions)
            if path is None:
                continue
            prop = model.property(name)
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, path.last_state()):
                    return
            elif prop.expectation == Expectation.EVENTUALLY:
                states = path.into_states()
                is_satisfied = any(prop.condition(model, s) for s in states)
                acts: List[Any] = []
                model.actions(states[-1], acts)
                is_terminal = not acts
                if not is_satisfied and is_terminal:
                    return
                if is_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property")
                if not is_terminal:
                    additional_info.append(
                        "incorrect counterexample is nonterminal")
            else:
                if prop.condition(model, path.last_state()):
                    return
        info = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{info}, but a valid one was '
            f"found. found={found.into_actions()!r}")
