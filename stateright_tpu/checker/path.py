"""Trace reconstruction by model replay.

Reference: ``Path`` (`/root/reference/src/checker/path.rs`). Engines store
only 64-bit fingerprints; counterexample traces are materialized by replaying
the model forward and matching fingerprints at every step (the TLC
fingerprint technique).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple


class NondeterministicModelError(RuntimeError):
    """Raised when replay cannot re-derive a state recorded earlier.

    Mirrors the diagnostic panics at `src/checker/path.rs:35-49` and
    `:62-79`: this usually means ``init_states``/``actions``/``next_state``
    vary across calls with the same inputs (hidden external state,
    unordered-container iteration, randomness).
    """


class Path:
    """A trace ``state --action--> state ... --action--> state``."""

    def __init__(self, steps: List[Tuple[Any, Optional[Any]]]):
        self._steps = steps

    @staticmethod
    def from_fingerprints(model, fingerprints: Sequence[int]) -> "Path":
        """Reconstruct a path by replaying ``model`` along ``fingerprints``.

        Reference: `src/checker/path.rs:20-86`.
        """
        fps = list(fingerprints)
        if not fps:
            raise NondeterministicModelError("empty path is invalid")
        init_fp = fps[0]
        last_state = None
        for s in model.init_states():
            if model.fingerprint(s) == init_fp:
                last_state = s
                break
        if last_state is None:
            raise NondeterministicModelError(
                "Unable to reconstruct a Path from fingerprints: no init state "
                f"has the expected fingerprint ({init_fp}). This usually means "
                "Model.init_states varies across calls (hidden external state, "
                "unordered iteration, or randomness). Available init "
                f"fingerprints: {[model.fingerprint(s) for s in model.init_states()]}")
        steps: List[Tuple[Any, Optional[Any]]] = []
        for next_fp in fps[1:]:
            found = None
            for action, state in model.next_steps(last_state):
                if model.fingerprint(state) == next_fp:
                    found = (action, state)
                    break
            if found is None:
                raise NondeterministicModelError(
                    f"Unable to reconstruct a Path: {1 + len(steps)} previous "
                    "state(s) were reconstructed, but no successor has the "
                    f"next fingerprint ({next_fp}). This usually means "
                    "Model.actions or Model.next_state vary across calls. "
                    "Available next fingerprints: "
                    f"{[model.fingerprint(s) for s in model.next_states(last_state)]}")
            steps.append((last_state, found[0]))
            last_state = found[1]
        steps.append((last_state, None))
        return Path(steps)

    @staticmethod
    def from_actions(model, init_state: Any,
                     actions: Sequence[Any]) -> Optional["Path"]:
        """Build a path from an init state and action list (`path.rs:90-112`)."""
        if init_state not in model.init_states():
            return None
        steps: List[Tuple[Any, Optional[Any]]] = []
        prev_state = init_state
        for action in actions:
            found = None
            for a, s in model.next_steps(prev_state):
                if a == action:
                    found = (a, s)
                    break
            if found is None:
                return None
            steps.append((prev_state, found[0]))
            prev_state = found[1]
        steps.append((prev_state, None))
        return Path(steps)

    @staticmethod
    def final_state(model, fingerprints: Sequence[int]) -> Optional[Any]:
        """Final state of a fingerprint path, or None (`path.rs:115-136`)."""
        fps = list(fingerprints)
        if not fps:
            return None
        state = None
        for s in model.init_states():
            if model.fingerprint(s) == fps[0]:
                state = s
                break
        if state is None:
            return None
        for next_fp in fps[1:]:
            nxt = None
            for s in model.next_states(state):
                if model.fingerprint(s) == next_fp:
                    nxt = s
                    break
            if nxt is None:
                return None
            state = nxt
        return state

    def last_state(self) -> Any:
        return self._steps[-1][0]

    def into_states(self) -> List[Any]:
        return [s for s, _a in self._steps]

    def into_actions(self) -> List[Any]:
        return [a for _s, a in self._steps if a is not None]

    def into_vec(self) -> List[Tuple[Any, Optional[Any]]]:
        return list(self._steps)

    def encode(self, model) -> str:
        """Path as `/`-joined fingerprints — the Explorer address scheme."""
        return "/".join(str(model.fingerprint(s)) for s, _a in self._steps)

    def __len__(self) -> int:
        return len(self._steps) - 1

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._steps == other._steps

    def __hash__(self) -> int:
        return hash(tuple((repr(s), repr(a)) for s, a in self._steps))

    def __repr__(self) -> str:
        return f"Path({self._steps!r})"

    def __str__(self) -> str:
        """Reference display format (`path.rs:174-187`)."""
        lines = [f"Path[{len(self)}]:"]
        for _state, action in self._steps:
            if action is not None:
                lines.append(f"- {action!r}")
        return "\n".join(lines) + "\n"
