"""Multi-core host BFS: ``spawn_bfs()`` honoring ``threads(n)``.

The reference's host engines scale with shared-memory worker threads and a
condvar job market (`/root/reference/src/checker/bfs.rs:29-30`, sharing at
`:138-150`). Python threads serialize on the GIL, so the host-parallel
analog here is **level-synchronous multiprocessing over frontier blocks**:
the master keeps the ``generated`` dedup map and the frontier; each BFS
level is split into blocks that forked workers expand independently
(property evaluation, action enumeration, fingerprinting, boundary
filtering — everything the reference does per state in ``check_block``,
`bfs.rs:165-274`); the master merges children, dedups, and records
discoveries first-wins.

Workers receive the model once, via **cloudpickle over a ``forkserver``
start** (models hold lambdas, which the stdlib pickler rejects); only
states cross process boundaries afterwards. The forkserver process never
inherits this process's native threads, so constructing a ``threads(n)``
checker after an XLA engine (``spawn_tpu``) initialized in-process is
safe — unlike a raw ``fork``, which POSIX makes undefined with live
threads (and which Python 3.12+ deprecates from threaded processes).
Like the reference's multithreaded runs, which worker wins a discovery
(and which parent a state records) is nondeterministic; full-enumeration
unique counts match exactly.

The ``eventually`` semantics ride per-frontier-entry bit sets with the
same documented caveats as the sequential engines (`bfs.rs:239-256`).
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from ..core import Expectation
from .builder import CheckerBuilder
from .host import HostChecker

# worker globals, populated by the pool initializer from the cloudpickle
# payload shipped at pool construction
_WORK_MODEL = None
_WORK_PROPS = None


def _init_worker(payload: bytes) -> None:
    import cloudpickle

    global _WORK_MODEL, _WORK_PROPS
    _WORK_MODEL, _WORK_PROPS = cloudpickle.loads(payload)


def _expand_block(batch: List[Tuple[Any, int, FrozenSet[int]]]):
    """Expand one frontier block: returns (generated_count, discoveries,
    children) where children are (child_fp, parent_fp, child_state,
    ebits)."""
    model, properties = _WORK_MODEL, _WORK_PROPS
    discoveries: Dict[str, int] = {}
    children: List[Tuple[int, int, Any, FrozenSet[int]]] = []
    gen_count = 0
    for state, state_fp, ebits in batch:
        # property evaluation (bfs.rs:192-226)
        for i, prop in enumerate(properties):
            if prop.name in discoveries:
                continue
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, state):
                    discoveries.setdefault(prop.name, state_fp)
            elif prop.expectation == Expectation.SOMETIMES:
                if prop.condition(model, state):
                    discoveries.setdefault(prop.name, state_fp)
            else:  # EVENTUALLY: clear satisfied bits
                if prop.condition(model, state):
                    ebits = ebits - {i}

        # expansion (bfs.rs:229-264)
        actions: List = []
        model.actions(state, actions)
        is_terminal = True
        for action in actions:
            next_state = model.next_state(state, action)
            if next_state is None:
                continue
            if not model.within_boundary(next_state):
                continue
            gen_count += 1
            is_terminal = False
            next_fp = model.fingerprint(next_state)
            children.append((next_fp, state_fp, next_state, ebits))
        if is_terminal:
            for i, prop in enumerate(properties):
                if i in ebits:
                    discoveries.setdefault(prop.name, state_fp)
    return gen_count, discoveries, children


class ParallelBfsChecker(HostChecker):
    """Level-synchronous multi-process BFS (`threads(n)`, n > 1)."""

    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        if builder.visitor_ is not None:
            raise ValueError(
                "per-state visitors require the sequential engine; drop "
                "threads(...) or the visitor")
        if builder.sound_eventually_ and any(
                p.expectation == Expectation.EVENTUALLY
                for p in self._properties):
            raise NotImplementedError(
                "sound_eventually() is not supported by the multi-process "
                "engine; use threads(1) spawn_bfs, spawn_dfs, or the "
                "single-chip spawn_tpu")
        self._workers = max(2, builder.thread_count_)
        self._generated: Dict[int, Optional[int]] = {}
        import multiprocessing as mp

        import cloudpickle

        payload = cloudpickle.dumps((self._model, self._properties))
        self._pool = mp.get_context("forkserver").Pool(
            self._workers, initializer=_init_worker,
            initargs=(payload,))

    def _run(self) -> None:
        model = self._model
        properties = self._properties
        generated = self._generated
        discoveries = self._discovery_fps
        target = self._target_state_count
        eventually_idx = frozenset(
            i for i, p in enumerate(properties)
            if p.expectation == Expectation.EVENTUALLY)

        try:
            init_states = [s for s in model.init_states()
                           if model.within_boundary(s)]
            self._state_count = len(init_states)
            frontier: List[Tuple[Any, int, FrozenSet[int]]] = []
            for s in init_states:
                fp = model.fingerprint(s)
                if fp not in generated:
                    generated[fp] = None
                    frontier.append((s, fp, eventually_idx))
            self._unique_state_count = len(generated)
            if not properties:
                return

            trace = self._trace
            while frontier:
                flen = len(frontier)
                n_blocks = min(len(frontier), self._workers * 4)
                size = -(-len(frontier) // n_blocks)
                blocks = [frontier[i:i + size]
                          for i in range(0, len(frontier), size)]
                results = self._pool.map(_expand_block, blocks)
                frontier = []
                for gen_count, block_disc, children in results:
                    self._state_count += gen_count
                    for name, fp in block_disc.items():
                        if name not in discoveries:
                            discoveries[name] = fp
                            self._note_discovery(name, fp)
                    for fp, parent_fp, child, ebits in children:
                        if fp in generated:
                            continue
                        generated[fp] = parent_fp
                        frontier.append((child, fp, ebits))
                self._unique_state_count = len(generated)
                self._metrics.inc("levels")
                if trace:
                    trace.emit(
                        "level",
                        level=int(self._metrics.get("levels")),
                        frontier=flen, gen=self._state_count,
                        unique=self._unique_state_count)
                if len(discoveries) == len(properties):
                    return
                if target is not None and self._state_count >= target:
                    return
        finally:
            self._pool.terminate()
            self._pool.join()

