"""Deterministic fixture models for engine tests.

Ports of the reference's test fixtures (`/root/reference/src/test_util.rs`):
``BinaryClock`` (2-state toggle), ``DGraph`` (digraph from path lists; pins
the exact — including knowingly unsound — ``eventually`` semantics),
``FnModel`` (lambda models), and ``LinearEquation`` (2^16-state Diophantine
search, the engine-test workhorse). Their exact state counts anchor many
tests and are the correctness oracle for the TPU engine.
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, List, Optional, Set

from ..core import Model, Property


class BinaryClockAction(enum.Enum):
    GO_LOW = "GoLow"
    GO_HIGH = "GoHigh"


class BinaryClock(Model):
    """Cycles between two states (`test_util.rs:4-46`)."""

    def init_states(self):
        return [0, 1]

    def actions(self, state, actions):
        if state == 0:
            actions.append(BinaryClockAction.GO_HIGH)
        else:
            actions.append(BinaryClockAction.GO_LOW)

    def next_state(self, state, action):
        return 1 if action == BinaryClockAction.GO_HIGH else 0

    def properties(self):
        return [Property.always("in [0, 1]", lambda _, s: 0 <= s <= 1)]


class DGraph(Model):
    """A digraph specified via paths from initial states
    (`test_util.rs:49-117`)."""

    def __init__(self, prop: Property):
        self.inits: Set[int] = set()
        self.edges: Dict[int, Set[int]] = {}
        self.prop = prop

    @staticmethod
    def with_property(prop: Property) -> "DGraph":
        return DGraph(prop)

    def with_path(self, path: List[int]) -> "DGraph":
        g = DGraph(self.prop)
        g.inits = set(self.inits)
        g.edges = {k: set(v) for k, v in self.edges.items()}
        src = path[0]
        g.inits.add(src)
        for dst in path[1:]:
            g.edges.setdefault(src, set()).add(dst)
            src = dst
        return g

    def check(self):
        return self.checker().spawn_bfs().join()

    def init_states(self):
        return sorted(self.inits)

    def actions(self, state, actions):
        actions.extend(sorted(self.edges.get(state, ())))

    def next_state(self, state, action):
        return action

    def properties(self):
        return [self.prop]


class FnModel(Model):
    """A model defined by one function, mirroring the reference's
    ``fn(Option<&T>, &mut Vec<T>)`` models (`test_util.rs:120-138`).

    ``fn(prev_state_or_None, out_list)`` appends init states when given
    ``None`` and successor "actions" (which are the states) otherwise.
    """

    def __init__(self, fn: Callable[[Optional[object], List], None]):
        self.fn = fn

    def init_states(self):
        out: List = []
        self.fn(None, out)
        return out

    def actions(self, state, actions):
        self.fn(state, actions)

    def next_state(self, state, action):
        return action


class Guess(enum.Enum):
    INCREASE_X = "IncreaseX"
    INCREASE_Y = "IncreaseY"

    def __repr__(self):
        return self.value


class LinearEquation(Model):
    """Finds x, y in u8 with a*x + b*y == c (mod 256)
    (`test_util.rs:141-188`)."""

    def __init__(self, a: int, b: int, c: int):
        self.a, self.b, self.c = a, b, c

    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions):
        actions.append(Guess.INCREASE_X)
        actions.append(Guess.INCREASE_Y)

    def next_state(self, state, action):
        x, y = state
        if action == Guess.INCREASE_X:
            return ((x + 1) & 0xFF, y)
        return (x, (y + 1) & 0xFF)

    def properties(self):
        def solvable(model, solution):
            x, y = solution
            return (model.a * x + model.b * y) & 0xFF == model.c
        return [Property.sometimes("solvable", solvable)]
