"""Deterministic fixture models for engine tests.

Ports of the reference's test fixtures (`/root/reference/src/test_util.rs`):
``BinaryClock`` (2-state toggle), ``DGraph`` (digraph from path lists; pins
the exact — including knowingly unsound — ``eventually`` semantics),
``FnModel`` (lambda models), and ``LinearEquation`` (2^16-state Diophantine
search, the engine-test workhorse). Their exact state counts anchor many
tests and are the correctness oracle for the TPU engine.
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, List, Optional, Set

from ..core import Model, Property


class BinaryClockAction(enum.Enum):
    GO_LOW = "GoLow"
    GO_HIGH = "GoHigh"


class BinaryClock(Model):
    """Cycles between two states (`test_util.rs:4-46`)."""

    def init_states(self):
        return [0, 1]

    def actions(self, state, actions):
        if state == 0:
            actions.append(BinaryClockAction.GO_HIGH)
        else:
            actions.append(BinaryClockAction.GO_LOW)

    def next_state(self, state, action):
        return 1 if action == BinaryClockAction.GO_HIGH else 0

    def properties(self):
        return [Property.always("in [0, 1]", lambda _, s: 0 <= s <= 1)]


class DGraph(Model):
    """A digraph specified via paths from initial states
    (`test_util.rs:49-117`)."""

    def __init__(self, prop: Property):
        self.inits: Set[int] = set()
        self.edges: Dict[int, Set[int]] = {}
        self.prop = prop

    @staticmethod
    def with_property(prop: Property) -> "DGraph":
        return DGraph(prop)

    def with_path(self, path: List[int]) -> "DGraph":
        g = DGraph(self.prop)
        g.inits = set(self.inits)
        g.edges = {k: set(v) for k, v in self.edges.items()}
        src = path[0]
        g.inits.add(src)
        for dst in path[1:]:
            g.edges.setdefault(src, set()).add(dst)
            src = dst
        return g

    def check(self):
        return self.checker().spawn_bfs().join()

    def init_states(self):
        return sorted(self.inits)

    def actions(self, state, actions):
        actions.extend(sorted(self.edges.get(state, ())))

    def next_state(self, state, action):
        return action

    def properties(self):
        return [self.prop]


class FnModel(Model):
    """A model defined by one function, mirroring the reference's
    ``fn(Option<&T>, &mut Vec<T>)`` models (`test_util.rs:120-138`).

    ``fn(prev_state_or_None, out_list)`` appends init states when given
    ``None`` and successor "actions" (which are the states) otherwise.
    """

    def __init__(self, fn: Callable[[Optional[object], List], None]):
        self.fn = fn

    def init_states(self):
        out: List = []
        self.fn(None, out)
        return out

    def actions(self, state, actions):
        self.fn(state, actions)

    def next_state(self, state, action):
        return action


class Guess(enum.Enum):
    INCREASE_X = "IncreaseX"
    INCREASE_Y = "IncreaseY"

    def __repr__(self):
        return self.value


class LinearEquation(Model):
    """Finds x, y in u8 with a*x + b*y == c (mod 256)
    (`test_util.rs:141-188`)."""

    def __init__(self, a: int, b: int, c: int):
        self.a, self.b, self.c = a, b, c

    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions):
        actions.append(Guess.INCREASE_X)
        actions.append(Guess.INCREASE_Y)

    def next_state(self, state, action):
        x, y = state
        if action == Guess.INCREASE_X:
            return ((x + 1) & 0xFF, y)
        return (x, (y + 1) & 0xFF)

    def properties(self):
        def solvable(model, solution):
            x, y = solution
            return (model.a * x + model.b * y) & 0xFF == model.c
        return [Property.sometimes("solvable", solvable)]


class PackedDGraph(DGraph):
    """A :class:`DGraph` with a packed device encoding, used to pin the
    ``eventually``-property semantics (including the reference's accepted
    unsoundness, `src/checker.rs:350-415` / `bfs.rs:239-256`) on the TPU
    engines.

    The node set is finite and known up front, so the device side is pure
    table lookup: a sorted node-value array, an out-edge matrix, and
    property bits PRE-EVALUATED on the host per node — which lets any
    host predicate ride along unchanged.
    """

    packed_width = 1

    @staticmethod
    def with_property(prop: Property) -> "PackedDGraph":
        return PackedDGraph(prop)

    def with_path(self, path: List[int]) -> "PackedDGraph":
        g = PackedDGraph(self.prop)
        g.inits = set(self.inits)
        g.edges = {k: set(v) for k, v in self.edges.items()}
        src = path[0]
        g.inits.add(src)
        for dst in path[1:]:
            g.edges.setdefault(src, set()).add(dst)
            src = dst
        return g

    _SENTINEL = 0xFFFFFFFF

    def _tables(self):
        import numpy as np

        nodes = sorted(self.inits | set(self.edges)
                       | {d for ds in self.edges.values() for d in ds})
        max_deg = max((len(v) for v in self.edges.values()), default=0)
        n = len(nodes)
        edge = np.full((n, max(max_deg, 1)), self._SENTINEL, np.uint32)
        for i, node in enumerate(nodes):
            for j, dst in enumerate(sorted(self.edges.get(node, ()))):
                edge[i, j] = dst
        pbits = np.array([[bool(p.condition(self, node))
                           for p in self.properties()]
                          for node in nodes], bool)
        return np.asarray(nodes, np.uint32), edge, pbits

    @property
    def max_actions(self) -> int:
        return max((len(v) for v in self.edges.values()), default=1)

    def cache_key(self):
        # the predicate itself must key the compiled program (its bits are
        # baked into the pbits table); the cache entry's closure keeps the
        # condition object alive, so its id cannot be recycled while the
        # entry exists
        return ("pdgraph",
                tuple(sorted(self.inits)),
                tuple(sorted((k, tuple(sorted(v)))
                             for k, v in self.edges.items())),
                self.prop.name, self.prop.expectation,
                id(self.prop.condition))

    def encode(self, state):
        import numpy as np
        return np.asarray([state], np.uint32)

    def decode(self, words):
        return int(words[0])

    def packed_step(self, words):
        import jax.numpy as jnp
        nodes, edge, _ = self._tables()
        nodes_d = jnp.asarray(nodes)
        edge_d = jnp.asarray(edge)
        idx = jnp.searchsorted(nodes_d, words[0])
        idx = jnp.minimum(idx, len(nodes) - 1)
        succ = edge_d[idx][:, None]
        valid = succ[:, 0] != jnp.uint32(self._SENTINEL)
        return succ, valid

    def packed_properties(self, words):
        import jax.numpy as jnp
        nodes, _, pbits = self._tables()
        nodes_d = jnp.asarray(nodes)
        idx = jnp.searchsorted(nodes_d, words[0])
        idx = jnp.minimum(idx, len(nodes) - 1)
        return jnp.asarray(pbits)[idx]

    def fingerprint(self, state) -> int:
        from ..fingerprint import fp64_words
        return fp64_words(self.encode(state).tolist())
