"""Model library: test fixtures and packed (TPU-checkable) models."""

from .fixtures import (
    BinaryClock,
    BinaryClockAction,
    DGraph,
    FnModel,
    Guess,
    LinearEquation,
)

__all__ = [
    "BinaryClock",
    "BinaryClockAction",
    "DGraph",
    "FnModel",
    "Guess",
    "LinearEquation",
]
