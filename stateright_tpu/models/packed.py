"""The packed-model protocol: the keystone of the TPU engine.

A :class:`PackedModel` is an ordinary :class:`~stateright_tpu.core.Model`
that additionally defines a canonical fixed-width ``uint32``-word encoding of
its states and batched (vmappable) JAX implementations of its transition
relation and properties. The TPU engine (`checker/tpu.py`) runs entirely on
the packed representation; the inherited host methods remain the oracle for
differential testing and for trace replay of device-discovered
counterexamples.

The host/device contract (checked by :func:`validate_packed_model`):
  * ``fingerprint(state) == fp64_words(encode(state))`` — host and device
    fingerprints agree bit-for-bit;
  * the multiset of valid successors of ``packed_step(encode(s))`` equals
    ``{encode(t) for t in next_states(s)}``;
  * ``packed_properties(encode(s))[i] == properties()[i].condition(self, s)``;
  * states outside ``within_boundary`` are masked invalid by ``packed_step``.

This plays the role of the reference's ``Hash``-derived state encoding
(`/root/reference/src/lib.rs:303-311`) — but as an explicit, device-resident
struct-of-words layout rather than a hasher side effect.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import numpy as np

from ..core import Model
from ..fingerprint import fp64_words


class PackedModel(Model):
    """Mixin adding the packed/TPU interface to a model."""

    #: number of uint32 words per packed state
    packed_width: int
    #: static upper bound on actions per state
    max_actions: int
    #: (offset, width) of the packed columns host-evaluated properties
    #: depend on (None = the whole row). Lets the device engine dedup
    #: states by host-property key before the host evaluates them.
    host_property_cols = None

    def cache_key(self):
        """Hashable identity of this model's *compiled program* — two
        models with the same key must trace identically (same config, same
        packed layout). Lets the engines reuse jitted step functions across
        checker runs (compilation dwarfs execution for small state spaces).
        Return ``None`` (the default) to disable cross-run reuse."""
        return None

    def encode(self, state: Any) -> np.ndarray:
        """Canonical ``uint32[packed_width]`` encoding of ``state``."""
        raise NotImplementedError

    def decode(self, words) -> Any:
        """Inverse of :meth:`encode` (used for debugging/witness dumps)."""
        raise NotImplementedError

    def packed_step(self, words):
        """JAX transition relation for one packed state.

        Args:
          words: uint32[packed_width] traced array.
        Returns:
          (successors uint32[max_actions, packed_width],
           valid bool[max_actions]) — row ``a`` is the result of action
          ``a``; invalid rows cover disabled actions, no-op transitions
          (the reference's ``next_state -> None``), and out-of-boundary
          successors. Models whose encoding can overflow (e.g. a fixed
          number of network slots) may return a third array
          ``overflow bool[max_actions]``: any set bit aborts the engines
          with a hard error rather than silently under-exploring.
        """
        raise NotImplementedError

    def packed_properties(self, words):
        """JAX evaluation of all properties for one packed state.

        For indices in ``host_property_indices`` (irregular predicates the
        device cannot express, e.g. the linearizability search) return a
        neutral placeholder (True for ALWAYS, False for SOMETIMES); the
        TPU engine evaluates those host-side per level on new states.

        Returns bool[P] in ``self.properties()`` order.
        """
        raise NotImplementedError

    #: property indices evaluated host-side by the TPU engine
    host_property_indices: Tuple[int, ...] = ()

    def host_property_key(self, row) -> bytes:
        """Memoization key for host-property evaluation of a packed row.

        Must discriminate at least as finely as every host property's
        dependence on the state; defaults to the whole row. Models whose
        host properties depend only on a state slice (e.g. the history
        words) override this so the expensive predicate runs once per
        distinct slice.
        """
        return np.asarray(row, dtype=np.uint32).tobytes()

    def fingerprint(self, state: Any) -> int:
        return fp64_words(self.encode(state).tolist())


def validate_packed_model(model: PackedModel, max_states: int = 2000,
                          batch: int = 256) -> int:
    """BFS-walk the host model, checking the host/device contract for
    every reachable state (up to ``max_states``). Device calls are
    BATCHED — one vmapped dispatch per ``batch`` states — so full
    reachable-space checks stay fast. Returns the number of states
    validated. Test helper."""
    import jax
    import jax.numpy as jnp

    from ..ops.hash_kernel import fp64_device

    step = jax.jit(jax.vmap(model.packed_step))
    props = jax.jit(jax.vmap(model.packed_properties))
    properties = model.properties()

    # host-side reachable walk first
    seen = set()
    states = []
    queue = list(model.init_states())
    while queue and len(states) < max_states:
        state = queue.pop()
        fp = model.fingerprint(state)
        if fp in seen:
            continue
        seen.add(fp)
        states.append((state, fp))
        queue.extend(t for t in model.next_states(state)
                     if model.within_boundary(t))

    for start in range(0, len(states), batch):
        chunk = states[start:start + batch]
        encs = []
        for state, fp in chunk:
            enc = model.encode(state)
            assert enc.dtype == np.uint32 \
                and enc.shape == (model.packed_width,), \
                f"encode() must return uint32[{model.packed_width}], " \
                f"got {enc.dtype}[{enc.shape}]"
            redec = model.decode(enc)
            assert np.array_equal(model.encode(redec), enc), \
                f"decode(encode(s)) != s for {state!r}"
            encs.append(enc)
        # pad the final partial chunk so every dispatch shares one
        # compiled shape (pad rows replicate row 0 and are never checked)
        pad = batch - len(encs)
        if pad and start:
            encs = encs + [encs[0]] * pad
        rows = jnp.asarray(np.stack(encs))
        dhi, dlo = fp64_device(rows)
        dhi, dlo = np.asarray(dhi), np.asarray(dlo)
        out = step(rows)
        succ, valid = np.asarray(out[0]), np.asarray(out[1])
        if len(out) == 3:
            ovf = np.asarray(out[2])
        else:
            ovf = np.zeros_like(valid)
        _validate_batch(model, chunk, dhi, dlo, succ, valid, ovf)
        _validate_props_batch(model, chunk, np.asarray(props(rows)),
                              properties)
    return len(states)


def _validate_batch(model, chunk, dhi, dlo, succ, valid, ovf) -> None:
    for k, (state, fp) in enumerate(chunk):
        dev_fp = (int(dhi[k]) << 32) | int(dlo[k])
        assert dev_fp == fp, \
            f"device fp {dev_fp:#x} != host fp {fp:#x} for {state!r}"
        assert not ovf[k].any(), \
            f"packed_step reports encoding overflow for {state!r}"
        packed_succ = sorted(tuple(succ[k, a].tolist())
                             for a in range(model.max_actions)
                             if valid[k, a])
        host_succ = sorted(tuple(model.encode(t).tolist())
                           for t in model.next_states(state)
                           if model.within_boundary(t))
        assert packed_succ == host_succ, \
            "packed successors disagree with host successors for " \
            f"{state!r}:\n packed={packed_succ}\n host={host_succ}"


def _validate_props_batch(model, chunk, pb, properties) -> None:
    # packed properties match host property conditions (host-evaluated
    # properties return a neutral placeholder on device — skip them)
    host_props = set(getattr(model, "host_property_indices", ()))
    for k, (state, _fp) in enumerate(chunk):
        for i, prop in enumerate(properties):
            if i in host_props:
                continue
            want = bool(prop.condition(model, state))
            assert bool(pb[k, i]) == want, \
                f"packed property {prop.name!r} = {bool(pb[k, i])} != " \
                f"host {want} for {state!r}"


class PackedLinearEquation(PackedModel):
    """Packed version of the LinearEquation fixture
    (`/root/reference/src/test_util.rs:141-188`): state (x, y) in u8 x u8,
    two increment actions. The minimal differential workload for the TPU
    engine (full enumeration = 65,536 unique states, `bfs.rs:371`)."""

    packed_width = 2
    max_actions = 2

    def cache_key(self):
        return ("lineq", self.a, self.b, self.c)

    def __init__(self, a: int, b: int, c: int):
        self.a, self.b, self.c = a, b, c

    # --- host side (mirrors models.fixtures.LinearEquation) -------------
    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions):
        actions.extend(["IncreaseX", "IncreaseY"])

    def next_state(self, state, action):
        x, y = state
        return ((x + 1) & 0xFF, y) if action == "IncreaseX" \
            else (x, (y + 1) & 0xFF)

    def properties(self):
        from ..core import Property

        def solvable(model, solution):
            x, y = solution
            return (model.a * x + model.b * y) & 0xFF == model.c
        return [Property.sometimes("solvable", solvable)]

    # --- packed side -----------------------------------------------------
    def encode(self, state):
        return np.array(state, dtype=np.uint32)

    def decode(self, words):
        return (int(words[0]), int(words[1]))

    def packed_step(self, words):
        import jax.numpy as jnp
        x, y = words[0], words[1]
        succ = jnp.stack([
            jnp.stack([(x + 1) & 0xFF, y]),
            jnp.stack([x, (y + 1) & 0xFF]),
        ]).astype(jnp.uint32)
        valid = jnp.ones((2,), dtype=bool)
        return succ, valid

    def packed_properties(self, words):
        import jax.numpy as jnp
        x, y = words[0], words[1]
        sat = ((jnp.uint32(self.a) * x + jnp.uint32(self.b) * y) & 0xFF) \
            == self.c
        return jnp.stack([sat])
