"""Two-phase commit, from Gray & Lamport's "Consensus on Transaction Commit".

Same protocol as the reference example (`/root/reference/examples/2pc.rs`):
N resource managers (RMs) and one transaction manager (TM) exchange messages
through a persistent message set. Deterministic oracle counts: 3 RMs -> 288
unique states, 5 RMs -> 8,832, 5 RMs + symmetry -> 665 (`2pc.rs:125-139`).

This is the TPU engine's minimum end-to-end model: the whole state packs
into 4 uint32 words (RM states as 2-bit fields, TM state, a prepared bitmask
and a message bitset), so expansion, hashing and property evaluation all run
as pure uint32 bit-ops on device.

State (host view): ``(rm_state, tm_state, tm_prepared, msgs)`` where
``rm_state`` is a tuple of per-RM codes, ``tm_prepared`` a tuple of 0/1 and
``msgs`` a frozenset of message codes. Message codes: ``rm`` for
Prepared{rm}, 16 for Commit, 17 for Abort (N <= 16).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..core import Property
from ..checker.representative import RewritePlan
from .packed import PackedModel

# RM state codes, in the reference's Ord order (RmState in 2pc.rs:27).
WORKING, PREPARED, COMMITTED, ABORTED = 0, 1, 2, 3
# TM state codes (TmState in 2pc.rs:30).
TM_INIT, TM_COMMITTED, TM_ABORTED = 0, 1, 2
MSG_COMMIT = 16
MSG_ABORT = 17

State = Tuple[Tuple[int, ...], int, Tuple[int, ...], frozenset]


class TwoPhaseSys(PackedModel):
    packed_width = 4

    def __init__(self, n: int, complete_symmetry: bool = False):
        """``complete_symmetry=True`` swaps the reference's sort-by-RM-
        state representative (`2pc.rs:165-182` — ties broken by original
        position, so reduced counts are exploration-order-specific; the
        reference's own DFS pins 665 at n=5) for an ORBIT-INVARIANT one
        that sorts the complete per-RM record (state, prepared bit,
        Prepared-message bit). Every engine then reduces to exactly the
        orbit partition — 314 classes at n=5, computed by brute force
        over all 120 RM permutations (NOTES.md)."""
        assert 1 <= n <= 16, "packed 2pc supports up to 16 RMs"
        self.n = n
        self.complete_symmetry = complete_symmetry
        self.max_actions = 2 + 5 * n
        # measured batch branching is ~12 valid children per state at
        # n=7 (profile()['vmax'] / fmax) — high enough that the engine's
        # fa//2 default candidate buffer is already right; no hint

    def cache_key(self):
        return ("twopc", self.n, self.complete_symmetry)

    # ------------------------------------------------------------------
    # Host side (2pc.rs:43-121)
    # ------------------------------------------------------------------
    def init_states(self) -> List[State]:
        return [((WORKING,) * self.n, TM_INIT, (0,) * self.n, frozenset())]

    def actions(self, state: State, actions: List) -> None:
        rm_state, tm_state, tm_prepared, msgs = state
        if tm_state == TM_INIT and all(tm_prepared):
            actions.append(("TmCommit",))
        if tm_state == TM_INIT:
            actions.append(("TmAbort",))
        for rm in range(self.n):
            if tm_state == TM_INIT and rm in msgs:
                actions.append(("TmRcvPrepared", rm))
            if rm_state[rm] == WORKING:
                actions.append(("RmPrepare", rm))
            if rm_state[rm] == WORKING:
                actions.append(("RmChooseToAbort", rm))
            if MSG_COMMIT in msgs:
                actions.append(("RmRcvCommitMsg", rm))
            if MSG_ABORT in msgs:
                actions.append(("RmRcvAbortMsg", rm))

    def next_state(self, state: State, action) -> State:
        rm_state, tm_state, tm_prepared, msgs = state
        kind = action[0]
        if kind == "TmRcvPrepared":
            rm = action[1]
            tm_prepared = tm_prepared[:rm] + (1,) + tm_prepared[rm + 1:]
        elif kind == "TmCommit":
            tm_state = TM_COMMITTED
            msgs = msgs | {MSG_COMMIT}
        elif kind == "TmAbort":
            tm_state = TM_ABORTED
            msgs = msgs | {MSG_ABORT}
        elif kind == "RmPrepare":
            rm = action[1]
            rm_state = rm_state[:rm] + (PREPARED,) + rm_state[rm + 1:]
            msgs = msgs | {rm}
        elif kind == "RmChooseToAbort":
            rm = action[1]
            rm_state = rm_state[:rm] + (ABORTED,) + rm_state[rm + 1:]
        elif kind == "RmRcvCommitMsg":
            rm = action[1]
            rm_state = rm_state[:rm] + (COMMITTED,) + rm_state[rm + 1:]
        elif kind == "RmRcvAbortMsg":
            rm = action[1]
            rm_state = rm_state[:rm] + (ABORTED,) + rm_state[rm + 1:]
        else:
            raise ValueError(f"unknown action {action!r}")
        return (rm_state, tm_state, tm_prepared, msgs)

    def properties(self) -> List[Property]:
        return [
            Property.sometimes(
                "abort agreement",
                lambda _, s: all(r == ABORTED for r in s[0])),
            Property.sometimes(
                "commit agreement",
                lambda _, s: all(r == COMMITTED for r in s[0])),
            Property.always(
                "consistent",
                lambda _, s: not (any(r == ABORTED for r in s[0])
                                  and any(r == COMMITTED for r in s[0]))),
        ]

    def representative(self, state: State) -> State:
        """Canonical member under RM-permutation symmetry: the
        reference's sort-by-RM-state (2pc.rs:165-182), or the
        orbit-invariant complete-record sort under
        ``complete_symmetry``."""
        rm_state, tm_state, tm_prepared, msgs = state
        if self.complete_symmetry:
            keys = [(rm_state[i], tm_prepared[i],
                     1 if i in msgs else 0) for i in range(self.n)]
            plan = RewritePlan.from_values_to_sort(keys)
        else:
            plan = RewritePlan.from_values_to_sort(rm_state)
        return (
            tuple(plan.reindex(rm_state)),
            tm_state,
            tuple(plan.reindex(tm_prepared)),
            frozenset(plan.rewrite(m) if m < 16 else m for m in msgs),
        )

    def format_action(self, action) -> str:
        return action[0] + (f"({action[1]})" if len(action) > 1 else "")

    # ------------------------------------------------------------------
    # Packed side: words = [rm_fields, tm_state, prepared_bits, msg_bits]
    # ------------------------------------------------------------------
    def encode(self, state: State) -> np.ndarray:
        rm_state, tm_state, tm_prepared, msgs = state
        rmw = 0
        for i, r in enumerate(rm_state):
            rmw |= r << (2 * i)
        prep = 0
        for i, p in enumerate(tm_prepared):
            prep |= int(bool(p)) << i
        msgw = 0
        for m in msgs:
            msgw |= 1 << m
        return np.array([rmw, tm_state, prep, msgw], dtype=np.uint32)

    def decode(self, words) -> State:
        rmw, tm_state, prep, msgw = (int(w) for w in words)
        rm_state = tuple((rmw >> (2 * i)) & 3 for i in range(self.n))
        tm_prepared = tuple((prep >> i) & 1 for i in range(self.n))
        msgs = frozenset(m for m in range(18) if msgw & (1 << m))
        return (rm_state, tm_state, tm_prepared, msgs)

    def packed_representative(self, words):
        """Device canonicalization under RM permutation: stable sort of
        the per-RM (state, prepared, message) triples — by RM state
        (bit-exact with the reference-style :meth:`representative`,
        `2pc.rs:165-182`), or by the packed complete record
        ``state*4 + prepared*2 + msg`` (== the host's tuple
        lexicographic order) under ``complete_symmetry``."""
        import jax.numpy as jnp
        n = self.n
        rmw, tm, prep, msgs = words[0], words[1], words[2], words[3]
        idx = jnp.arange(n, dtype=jnp.uint32)
        r = (rmw >> (2 * idx)) & 3
        p = (prep >> idx) & 1
        m = (msgs >> idx) & 1  # message bit i = "RM i sent Prepared"
        sort_key = (r << 2) | (p << 1) | m if self.complete_symmetry \
            else r
        order = jnp.argsort(sort_key, stable=True)
        r, p, m = r[order], p[order], m[order]
        nrmw = (r << (2 * idx)).sum().astype(jnp.uint32)
        nprep = (p << idx).sum().astype(jnp.uint32)
        nmsgs = ((m << idx).sum()
                 | (msgs & ~jnp.uint32((1 << n) - 1))).astype(jnp.uint32)
        return jnp.stack([nrmw, tm, nprep, nmsgs]).astype(jnp.uint32)

    def packed_step(self, words):
        """Successor kernel, vectorized over the RM axis.

        The per-iteration cost of the device loop is dominated by the
        SEQUENTIAL op count of the traced graph (dependent-op latency —
        NOTES.md), not lane width, so the 5 per-RM action families are
        computed as (n,)-shaped array ops (~40 ops total) instead of a
        Python loop emitting ~8 ops per action lane (~300 ops for n=7).
        Action-lane ORDER differs from the host ``actions`` enumeration;
        engines treat lanes as an unordered nondeterminism axis, so only
        the successor multiset matters (pinned by the packed contract
        tests)."""
        import jax.numpy as jnp
        n = self.n
        rmw, tm, prep, msgs = words[0], words[1], words[2], words[3]
        all_mask = (1 << n) - 1
        tm_init = tm == TM_INIT
        commit_bit = jnp.uint32(1 << MSG_COMMIT)
        abort_bit = jnp.uint32(1 << MSG_ABORT)
        has_commit = (msgs & commit_bit) != 0
        has_abort = (msgs & abort_bit) != 0

        idx = jnp.arange(n, dtype=jnp.uint32)
        shift = 2 * idx
        fields = (rmw >> shift) & 3
        is_working = fields == WORKING
        cleared = rmw & ~(jnp.uint32(3) << shift)
        rm_bit = jnp.uint32(1) << idx
        rmw_v = jnp.broadcast_to(rmw, (n,))
        tm_v = jnp.broadcast_to(tm, (n,))
        prep_v = jnp.broadcast_to(prep, (n,))
        msgs_v = jnp.broadcast_to(msgs, (n,))

        def rows(w0, w1, w2, w3):
            return jnp.stack([w0, w1, w2, w3], axis=1).astype(jnp.uint32)

        # two TM lanes + five per-RM families, one block each
        tm_rows = jnp.stack([
            jnp.stack([rmw, jnp.uint32(TM_COMMITTED), prep,
                       msgs | commit_bit]),
            jnp.stack([rmw, jnp.uint32(TM_ABORTED), prep,
                       msgs | abort_bit]),
        ]).astype(jnp.uint32)
        tm_valid = jnp.stack([
            tm_init & ((prep & all_mask) == all_mask),   # TmCommit
            tm_init,                                     # TmAbort
        ])
        succs = jnp.concatenate([
            tm_rows,
            rows(rmw_v, tm_v, prep | rm_bit, msgs_v),    # TmRcvPrepared
            rows(cleared | (jnp.uint32(PREPARED) << shift), tm_v, prep_v,
                 msgs | rm_bit),                         # RmPrepare
            rows(cleared | (jnp.uint32(ABORTED) << shift), tm_v, prep_v,
                 msgs_v),                                # RmChooseToAbort
            rows(cleared | (jnp.uint32(COMMITTED) << shift), tm_v,
                 prep_v, msgs_v),                        # RmRcvCommitMsg
            rows(cleared | (jnp.uint32(ABORTED) << shift), tm_v, prep_v,
                 msgs_v),                                # RmRcvAbortMsg
        ])
        valids = jnp.concatenate([
            tm_valid,
            tm_init & ((msgs & rm_bit) != 0),
            is_working,
            is_working,
            jnp.broadcast_to(has_commit, (n,)),
            jnp.broadcast_to(has_abort, (n,)),
        ])
        return succs, valids

    def packed_properties(self, words):
        import jax.numpy as jnp
        n = self.n
        rmw = words[0]
        pat_aborted = 0
        pat_committed = 0
        for i in range(n):
            pat_aborted |= ABORTED << (2 * i)
            pat_committed |= COMMITTED << (2 * i)
        idx = jnp.arange(n, dtype=jnp.uint32)
        fields = (rmw >> (2 * idx)) & 3
        return jnp.stack([
            rmw == pat_aborted,
            rmw == pat_committed,
            ~((fields == ABORTED).any() & (fields == COMMITTED).any()),
        ])
