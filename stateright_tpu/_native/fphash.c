/* Native fingerprint core.
 *
 * C implementation of the column-parallel two-lane 64-bit fingerprint
 * defined in stateright_tpu/fingerprint.py (the host reference) and
 * mirrored by the device kernel in ops/hash_kernel.py. The reference's
 * stable hasher is native too (fixed-key aHash,
 * /root/reference/src/lib.rs:331-344); this is its host-side equivalent.
 * Built at import time by _native/__init__.py and loaded via ctypes; the
 * pure-Python implementation remains the fallback and the bit-exactness
 * oracle (differential-tested in tests).
 */

#include <stddef.h>
#include <stdint.h>
#include <stdlib.h>

#define C1_1 0xCC9E2D51u
#define C1_2 0x239B961Bu
#define GOLDEN 0x9E3779B9u
#define SEED1 0x9747B28Cu
#define SEED2 0x85EBCA6Bu

static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

/* Per-position whitening key P_i = fmix32((i + 1) * GOLDEN). */
static inline uint32_t col_key(size_t i) {
    return fmix32((uint32_t)(i + 1) * GOLDEN);
}

uint64_t fp64_words(const uint32_t *words, size_t n) {
    uint32_t h1 = 0, h2 = 0;
    for (size_t i = 0; i < n; i++) {
        uint32_t x = words[i] ^ col_key(i);
        h1 ^= fmix32(x * C1_1);
        h2 ^= fmix32(x * C1_2);
    }
    h1 = fmix32(h1 ^ SEED1 ^ (uint32_t)n);
    h2 = fmix32(h2 ^ SEED2 ^ ((uint32_t)n * C1_1));
    uint64_t fp = ((uint64_t)h1 << 32) | (uint64_t)h2;
    return fp ? fp : 1u;
}

/* Batch variant: fingerprint `count` rows of `width` words each (row-major),
 * writing one uint64 per row. Used for bulk host-side mirroring. The
 * whitening keys are computed once per call, not once per row. */
void fp64_rows(const uint32_t *rows, size_t count, size_t width,
               uint64_t *out) {
    uint32_t stack_keys[256];
    uint32_t *keys = stack_keys;
    if (width > 256) {
        keys = (uint32_t *)malloc(width * sizeof(uint32_t));
        if (!keys) { /* fall back to the scalar path */
            for (size_t r = 0; r < count; r++)
                out[r] = fp64_words(rows + r * width, width);
            return;
        }
    }
    for (size_t i = 0; i < width; i++)
        keys[i] = col_key(i);
    uint32_t fin2 = (uint32_t)width * C1_1;
    for (size_t r = 0; r < count; r++) {
        const uint32_t *row = rows + r * width;
        uint32_t h1 = 0, h2 = 0;
        for (size_t i = 0; i < width; i++) {
            uint32_t x = row[i] ^ keys[i];
            h1 ^= fmix32(x * C1_1);
            h2 ^= fmix32(x * C1_2);
        }
        h1 = fmix32(h1 ^ SEED1 ^ (uint32_t)width);
        h2 = fmix32(h2 ^ SEED2 ^ fin2);
        uint64_t fp = ((uint64_t)h1 << 32) | (uint64_t)h2;
        out[r] = fp ? fp : 1u;
    }
    if (keys != stack_keys)
        free(keys);
}
