/* Native fingerprint core.
 *
 * C implementation of the two-lane murmur3-style 64-bit fingerprint defined
 * in stateright_tpu/fingerprint.py (the host reference) and mirrored by the
 * device kernel in ops/hash_kernel.py. The reference's stable hasher is
 * native too (fixed-key aHash, /root/reference/src/lib.rs:331-344); this is
 * its host-side equivalent. Built at import time by _native/__init__.py and
 * loaded via ctypes; the pure-Python implementation remains the fallback
 * and the bit-exactness oracle (differential-tested in tests).
 */

#include <stddef.h>
#include <stdint.h>

#define C1_1 0xCC9E2D51u
#define C2_1 0x1B873593u
#define C1_2 0x239B961Bu
#define C2_2 0xAB0E9789u
#define SEED1 0x9747B28Cu
#define SEED2 0x85EBCA6Bu

static inline uint32_t rotl32(uint32_t x, int r) {
    return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

uint64_t fp64_words(const uint32_t *words, size_t n) {
    uint32_t h1 = SEED1, h2 = SEED2;
    for (size_t i = 0; i < n; i++) {
        uint32_t w = words[i];
        uint32_t k = w * C1_1;
        k = rotl32(k, 15);
        k *= C2_1;
        h1 ^= k;
        h1 = rotl32(h1, 13);
        h1 = h1 * 5u + 0xE6546B64u;

        k = w * C1_2;
        k = rotl32(k, 16);
        k *= C2_2;
        h2 ^= k;
        h2 = rotl32(h2, 13);
        h2 = h2 * 5u + 0x561CCD1Bu;
    }
    h1 = fmix32(h1 ^ (uint32_t)n);
    h2 = fmix32(h2 ^ (uint32_t)n);
    uint64_t fp = ((uint64_t)h1 << 32) | (uint64_t)h2;
    return fp ? fp : 1u;
}

/* Batch variant: fingerprint `count` rows of `width` words each (row-major),
 * writing one uint64 per row. Used for bulk host-side mirroring. */
void fp64_rows(const uint32_t *rows, size_t count, size_t width,
               uint64_t *out) {
    for (size_t r = 0; r < count; r++) {
        out[r] = fp64_words(rows + r * width, width);
    }
}
