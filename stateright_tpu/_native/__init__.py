"""Native (C) components, built lazily with the system toolchain.

The fingerprint core is the host engines' hottest function (profiling showed
~90% of `paxos check 2` in pure-Python hashing), and the reference's
equivalent is native as well (fixed-key aHash, `src/lib.rs:331-344`). The
shared library is compiled once into this package directory and loaded via
ctypes; every user keeps working (slower) if no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fphash.c")
_LIB = os.path.join(_DIR, "libfphash.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    for cc in ("cc", "gcc", "clang"):
        try:
            result = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC],
                capture_output=True, timeout=120)
            if result.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def load() -> Optional[ctypes.CDLL]:
    """The fphash library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB) \
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
                if not _build():
                    return None
            lib = ctypes.CDLL(_LIB)
            lib.fp64_words.argtypes = [
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t]
            lib.fp64_words.restype = ctypes.c_uint64
            lib.fp64_rows.argtypes = [
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
                ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)]
            lib.fp64_rows.restype = None
            _lib = lib
        except OSError:
            _lib = None
    return _lib
