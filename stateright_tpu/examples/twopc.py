"""CLI harness for the two-phase commit model
(:class:`stateright_tpu.models.twopc.TwoPhaseSys`).

Mirrors the reference example binary (`/root/reference/examples/2pc.rs:191-208`):
``check`` runs the host DFS engine, ``check-sym`` adds RM-permutation
symmetry reduction, and ``check-tpu`` runs the packed model on the device
engine. Oracles: 3 RMs = 288, 5 RMs = 8,832, 5 RMs + symmetry = 665.

Run: ``python -m stateright_tpu.examples.twopc check [RM_COUNT]``
"""

from __future__ import annotations

import sys

from ..models.twopc import TwoPhaseSys


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    rm_count = int(args[1]) if len(args) > 1 else 3
    if cmd == "check":
        print(f"Model checking two phase commit with {rm_count} resource "
              "managers.")
        TwoPhaseSys(rm_count).checker().spawn_dfs().report(sys.stdout)
    elif cmd == "check-sym":
        print(f"Model checking two phase commit with {rm_count} resource "
              "managers using symmetry reduction.")
        model = TwoPhaseSys(rm_count)
        (model.checker().symmetry_fn(model.representative)
         .spawn_dfs().report(sys.stdout))
    elif cmd == "check-tpu":
        print(f"Model checking two phase commit with {rm_count} resource "
              "managers on the TPU engine.")
        TwoPhaseSys(rm_count).checker().spawn_tpu().report(sys.stdout)
    elif cmd == "explore":
        address = args[2] if len(args) > 2 else "localhost:3000"
        print(f"Exploring state space for two phase commit with {rm_count} "
              f"resource managers on http://{address}.")
        TwoPhaseSys(rm_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.twopc check [RM_COUNT]")
        print("  python -m stateright_tpu.examples.twopc check-sym "
              "[RM_COUNT]")
        print("  python -m stateright_tpu.examples.twopc check-tpu "
              "[RM_COUNT]")
        print("  python -m stateright_tpu.examples.twopc explore "
              "[RM_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
