"""Example protocol workloads — the benchmark suite.

Each module mirrors one of the reference's examples
(`/root/reference/examples/`) and exposes ``main()`` with the same
subcommands (``check`` / ``check-sym`` / ``explore`` / ``spawn``) plus an
extra ``check-tpu`` strategy where a packed encoding exists.
"""
