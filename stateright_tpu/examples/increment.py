"""Shared-counter race: N threads read then write-increment without a lock.

Port of `/root/reference/examples/increment.rs`: each thread runs
``1: local = SHARED; 2: SHARED = local + 1; 3:`` with the two instructions
atomic but interleavable. The intended invariant "SHARED == number of
finished threads" (property ``fin``) is deliberately falsifiable. The doc
comment at `increment.rs:36-105` enumerates the full 2-thread state space:
13 unique states, 8 under symmetry reduction — both pinned in tests.

This is also a packed model, so the same workload runs under ``spawn_tpu``.

Run: ``python -m stateright_tpu.examples.increment check [THREAD_COUNT]``
"""

from __future__ import annotations

import sys
from typing import List, Tuple

import numpy as np

from ..checker.representative import RewritePlan
from ..core import Property
from ..models.packed import PackedModel

# state: (i, ((t, pc), ...)) — shared counter, per-thread (local, counter)
State = Tuple[int, Tuple[Tuple[int, int], ...]]


class Increment(PackedModel):
    """N racing increment threads (`increment.rs:147-204`)."""

    def __init__(self, n: int):
        assert 1 <= n <= 16
        self.n = n
        self.packed_width = 1 + n
        self.max_actions = n

    # --- host side -------------------------------------------------------
    def init_states(self) -> List[State]:
        return [(0, ((0, 1),) * self.n)]

    def actions(self, state: State, actions: List) -> None:
        _i, s = state
        for thread_id in range(self.n):
            pc = s[thread_id][1]
            if pc == 1:
                actions.append(("Read", thread_id))
            elif pc == 2:
                actions.append(("Write", thread_id))

    def next_state(self, state: State, action) -> State:
        i, s = state
        kind, tid = action
        if kind == "Read":
            s = s[:tid] + ((i, 2),) + s[tid + 1:]
            return (i, s)
        t = s[tid][0]
        s = s[:tid] + ((t, 3),) + s[tid + 1:]
        return ((t + 1) & 0xFF, s)

    def properties(self) -> List[Property]:
        return [Property.always(
            "fin",
            lambda _, state: sum(1 for t, pc in state[1] if pc == 3)
            == state[0])]

    def representative(self, state: State) -> State:
        """Sort the (identical) threads' states (`increment.rs:143-153`)."""
        i, s = state
        plan = RewritePlan.from_values_to_sort(s)
        return (i, tuple(plan.reindex(s)))

    def format_action(self, action) -> str:
        return f"{action[0]}({action[1]})"

    # --- packed side: [i, thread_0, ..., thread_n-1], thread = t<<4 | pc --
    def encode(self, state: State) -> np.ndarray:
        i, s = state
        return np.array([i] + [(t << 4) | pc for t, pc in s],
                        dtype=np.uint32)

    def decode(self, words) -> State:
        i = int(words[0])
        s = tuple((int(w) >> 4, int(w) & 0xF) for w in words[1:self.n + 1])
        return (i, s)

    def packed_representative(self, words):
        """Device canonicalization: sort the thread words — bit-exact with
        :meth:`representative` since a thread word is ``t<<4 | pc`` and
        the host's stable value sort over (t, pc) tuples equals integer
        sort of the packed words (pc < 16)."""
        import jax.numpy as jnp
        threads = jnp.sort(words[1:self.n + 1])
        return jnp.concatenate([words[:1], threads,
                                words[self.n + 1:]]).astype(jnp.uint32)

    def packed_step(self, words):
        import jax.numpy as jnp
        i = words[0]
        succs, valids = [], []
        for tid in range(self.n):
            w = words[1 + tid]
            t, pc = w >> 4, w & 0xF
            is_read = pc == 1
            # Read: (t, pc) <- (i, 2); Write: pc <- 3, i <- t + 1
            new_thread = jnp.where(is_read, (i << 4) | 2, (t << 4) | 3)
            new_i = jnp.where(is_read, i, (t + 1) & 0xFF)
            row = words.at[0].set(new_i).at[1 + tid].set(
                new_thread.astype(jnp.uint32))
            succs.append(row)
            valids.append((pc == 1) | (pc == 2))
        return jnp.stack(succs), jnp.stack(valids)

    def packed_properties(self, words):
        import jax.numpy as jnp
        i = words[0]
        fin_count = jnp.uint32(0)
        for tid in range(self.n):
            fin_count = fin_count + ((words[1 + tid] & 0xF) == 3)
        return jnp.stack([fin_count == i])


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    thread_count = int(args[1]) if len(args) > 1 else 3
    if cmd == "check":
        print(f"Model checking increment with {thread_count} threads.")
        Increment(thread_count).checker().spawn_dfs().report(sys.stdout)
    elif cmd == "check-sym":
        print(f"Model checking increment with {thread_count} threads "
              "using symmetry reduction.")
        model = Increment(thread_count)
        (model.checker().symmetry_fn(model.representative)
         .spawn_dfs().report(sys.stdout))
    elif cmd == "check-tpu":
        print(f"Model checking increment with {thread_count} threads "
              "on the TPU engine.")
        Increment(thread_count).checker().spawn_tpu().report(sys.stdout)
    elif cmd == "explore":
        address = args[2] if len(args) > 2 else "localhost:3000"
        print(f"Exploring state space for increment with {thread_count} "
              f"threads on http://{address}.")
        Increment(thread_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.increment "
              "check [THREAD_COUNT]")
        print("  python -m stateright_tpu.examples.increment "
              "check-sym [THREAD_COUNT]")
        print("  python -m stateright_tpu.examples.increment "
              "check-tpu [THREAD_COUNT]")
        print("  python -m stateright_tpu.examples.increment "
              "explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
