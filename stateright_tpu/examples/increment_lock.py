"""Shared counter with a lock: the fixed version of the increment race.

Port of `/root/reference/examples/increment_lock.rs`: each thread acquires a
global lock, reads, write-increments, and releases. Properties ``fin``
(counter equals finished threads) and ``mutex`` (at most one thread in the
critical section) both hold. A BASELINE.md bench config.

Also a packed model, so the workload runs under ``spawn_tpu``.

Run: ``python -m stateright_tpu.examples.increment_lock check [THREAD_COUNT]``
"""

from __future__ import annotations

import sys
from typing import List, Tuple

import numpy as np

from ..checker.representative import RewritePlan
from ..core import Property
from ..models.packed import PackedModel

# state: (i, lock, ((t, pc), ...))
State = Tuple[int, bool, Tuple[Tuple[int, int], ...]]


class IncrementLock(PackedModel):
    """N lock-protected increment threads (`increment_lock.rs:47-107`)."""

    def __init__(self, n: int):
        assert 1 <= n <= 16
        self.n = n
        self.packed_width = 2 + n
        self.max_actions = n

    # --- host side -------------------------------------------------------
    def init_states(self) -> List[State]:
        return [(0, False, ((0, 0),) * self.n)]

    def actions(self, state: State, actions: List) -> None:
        _i, lock, s = state
        for thread_id in range(self.n):
            pc = s[thread_id][1]
            if pc == 0 and not lock:
                actions.append(("Lock", thread_id))
            elif pc == 1:
                actions.append(("Read", thread_id))
            elif pc == 2:
                actions.append(("Write", thread_id))
            elif pc == 3 and lock:
                actions.append(("Release", thread_id))

    def next_state(self, state: State, action) -> State:
        i, lock, s = state
        kind, tid = action
        t, pc = s[tid]
        if kind == "Lock":
            return (i, True, s[:tid] + ((t, 1),) + s[tid + 1:])
        if kind == "Read":
            return (i, lock, s[:tid] + ((i, 2),) + s[tid + 1:])
        if kind == "Write":
            return ((t + 1) & 0xFF, lock, s[:tid] + ((t, 3),) + s[tid + 1:])
        assert kind == "Release"
        return (i, False, s[:tid] + ((t, 4),) + s[tid + 1:])

    def properties(self) -> List[Property]:
        return [
            Property.always(
                "fin",
                lambda _, state: sum(1 for t, pc in state[2] if pc >= 3)
                == state[0]),
            Property.always(
                "mutex",
                lambda _, state: sum(1 for t, pc in state[2]
                                     if 1 <= pc < 4) <= 1),
        ]

    def representative(self, state: State) -> State:
        i, lock, s = state
        plan = RewritePlan.from_values_to_sort(s)
        return (i, lock, tuple(plan.reindex(s)))

    def format_action(self, action) -> str:
        return f"{action[0]}({action[1]})"

    # --- packed side: [i, lock, thread_0, ...], thread = t<<4 | pc --------
    def encode(self, state: State) -> np.ndarray:
        i, lock, s = state
        return np.array([i, int(lock)] + [(t << 4) | pc for t, pc in s],
                        dtype=np.uint32)

    def decode(self, words) -> State:
        i = int(words[0])
        lock = bool(int(words[1]))
        s = tuple((int(w) >> 4, int(w) & 0xF) for w in words[2:self.n + 2])
        return (i, lock, s)

    def packed_step(self, words):
        import jax.numpy as jnp
        i, lock = words[0], words[1]
        succs, valids = [], []
        for tid in range(self.n):
            w = words[2 + tid]
            t, pc = w >> 4, w & 0xF
            can_lock = (pc == 0) & (lock == 0)
            is_read = pc == 1
            is_write = pc == 2
            can_release = (pc == 3) & (lock == 1)
            new_pc = jnp.where(can_lock, 1,
                               jnp.where(is_read, 2,
                                         jnp.where(is_write, 3, 4)))
            new_t = jnp.where(is_read, i, t)
            new_i = jnp.where(is_write, (t + 1) & 0xFF, i)
            new_lock = jnp.where(can_lock, 1,
                                 jnp.where(can_release, 0, lock))
            row = (words.at[0].set(new_i.astype(jnp.uint32))
                   .at[1].set(new_lock.astype(jnp.uint32))
                   .at[2 + tid].set(((new_t << 4) | new_pc)
                                    .astype(jnp.uint32)))
            succs.append(row)
            valids.append(can_lock | is_read | is_write | can_release)
        return jnp.stack(succs), jnp.stack(valids)

    def packed_properties(self, words):
        import jax.numpy as jnp
        i = words[0]
        fin = jnp.uint32(0)
        crit = jnp.uint32(0)
        for tid in range(self.n):
            pc = words[2 + tid] & 0xF
            fin = fin + (pc >= 3)
            crit = crit + ((pc >= 1) & (pc < 4))
        return jnp.stack([fin == i, crit <= 1])


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    thread_count = int(args[1]) if len(args) > 1 else 3
    if cmd == "check":
        print(f"Model checking increment_lock with {thread_count} threads.")
        IncrementLock(thread_count).checker().spawn_dfs().report(sys.stdout)
    elif cmd == "check-sym":
        print(f"Model checking increment_lock with {thread_count} threads "
              "using symmetry reduction.")
        model = IncrementLock(thread_count)
        (model.checker().symmetry_fn(model.representative)
         .spawn_dfs().report(sys.stdout))
    elif cmd == "check-tpu":
        print(f"Model checking increment_lock with {thread_count} threads "
              "on the TPU engine.")
        IncrementLock(thread_count).checker().spawn_tpu().report(sys.stdout)
    elif cmd == "explore":
        address = args[2] if len(args) > 2 else "localhost:3000"
        print(f"Exploring state space for increment_lock with "
              f"{thread_count} threads on http://{address}.")
        IncrementLock(thread_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.increment_lock "
              "check [THREAD_COUNT]")
        print("  python -m stateright_tpu.examples.increment_lock "
              "check-sym [THREAD_COUNT]")
        print("  python -m stateright_tpu.examples.increment_lock "
              "check-tpu [THREAD_COUNT]")


if __name__ == "__main__":
    main()
