"""Single Decree Paxos — the north-star benchmark workload.

Behavioral port of `/root/reference/examples/paxos.rs`: three servers run
single-decree Paxos under the register protocol; scripted clients put then
get; a :class:`LinearizabilityTester` rides in the model history and an
``always linearizable`` property queries it per state. Oracle: 2 clients +
3 servers = 16,668 unique states (`paxos.rs:291`, `:311`).

Run: ``python -m stateright_tpu.examples.paxos check [CLIENT_COUNT] [NETWORK]``
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Any, Optional, Tuple

from ..actor import ActorModel, Id, Network, Out, majority, model_peers
from ..actor.core import Actor
from ..actor.register import (Get, GetOk, Internal, Put, PutOk,
                              RegisterClient, RegisterServer,
                              record_invocations, record_returns)
from ..core import Expectation
from ..semantics import LinearizabilityTester, Register

# Ballot = (round, leader id); Proposal = (request id, requester, value).
Ballot = Tuple[int, int]
Proposal = Tuple[int, int, Any]


@dataclass(frozen=True)
class Prepare:
    ballot: Ballot


@dataclass(frozen=True)
class Prepared:
    ballot: Ballot
    last_accepted: Optional[Tuple[Ballot, Proposal]]


@dataclass(frozen=True)
class Accept:
    ballot: Ballot
    proposal: Proposal


@dataclass(frozen=True)
class Accepted:
    ballot: Ballot


@dataclass(frozen=True)
class Decided:
    ballot: Ballot
    proposal: Proposal


@dataclass(frozen=True)
class PaxosState:
    ballot: Ballot
    # leader state
    proposal: Optional[Proposal]
    prepares: tuple  # sorted ((id, last_accepted), ...)
    accepts: frozenset
    # acceptor state
    accepted: Optional[Tuple[Ballot, Proposal]]
    is_decided: bool


def _accepted_key(accepted):
    """Rust orders ``Option<(Ballot, Proposal)>`` with ``None`` least."""
    return (0,) if accepted is None else (1, accepted)


class PaxosActor(Actor):
    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def on_start(self, id: Id, o: Out) -> PaxosState:
        return PaxosState(ballot=(0, 0), proposal=None, prepares=(),
                          accepts=frozenset(), accepted=None,
                          is_decided=False)

    def on_msg(self, id: Id, state: PaxosState, src: Id, msg: Any,
               o: Out) -> Optional[PaxosState]:
        if state.is_decided:
            if isinstance(msg, Get):
                # Deliberately no reply when undecided (paxos.rs:119-126).
                assert state.accepted is not None, \
                    "decided but lacks accepted state"
                _b, (_req_id, _src, value) = state.accepted
                o.send(src, GetOk(msg.request_id, value))
            return None

        if isinstance(msg, Put) and state.proposal is None:
            ballot = (state.ballot[0] + 1, int(id))
            # Simulate `Prepare` + `Prepared` self-sends.
            prepares = ((int(id), state.accepted),)
            o.broadcast(self.peer_ids, Internal(Prepare(ballot)))
            return PaxosState(
                ballot=ballot, proposal=(msg.request_id, int(src), msg.value),
                prepares=prepares, accepts=frozenset(),
                accepted=state.accepted, is_decided=False)

        if isinstance(msg, Internal):
            inner = msg.msg
            if isinstance(inner, Prepare) and state.ballot < inner.ballot:
                o.send(src, Internal(Prepared(
                    ballot=inner.ballot, last_accepted=state.accepted)))
                return PaxosState(
                    ballot=inner.ballot, proposal=state.proposal,
                    prepares=state.prepares, accepts=state.accepts,
                    accepted=state.accepted, is_decided=False)

            if isinstance(inner, Prepared) and inner.ballot == state.ballot:
                prepares = dict(state.prepares)
                prepares[int(src)] = inner.last_accepted
                prepares_t = tuple(sorted(prepares.items()))
                if len(prepares) == majority(len(self.peer_ids) + 1):
                    # leadership handoff: favor the most recently accepted
                    # proposal from the prepare quorum (paxos.rs:157-180)
                    newest = max(prepares.values(), key=_accepted_key)
                    proposal = newest[1] if newest is not None \
                        else state.proposal
                    assert proposal is not None, "proposal expected"
                    o.broadcast(self.peer_ids, Internal(Accept(
                        ballot=inner.ballot, proposal=proposal)))
                    return PaxosState(
                        ballot=state.ballot, proposal=proposal,
                        prepares=prepares_t,
                        accepts=frozenset({int(id)}),
                        accepted=(inner.ballot, proposal),
                        is_decided=False)
                return PaxosState(
                    ballot=state.ballot, proposal=state.proposal,
                    prepares=prepares_t, accepts=state.accepts,
                    accepted=state.accepted, is_decided=False)

            if isinstance(inner, Accept) and state.ballot <= inner.ballot:
                o.send(src, Internal(Accepted(inner.ballot)))
                return PaxosState(
                    ballot=inner.ballot, proposal=state.proposal,
                    prepares=state.prepares, accepts=state.accepts,
                    accepted=(inner.ballot, inner.proposal),
                    is_decided=False)

            if isinstance(inner, Accepted) and inner.ballot == state.ballot:
                accepts = state.accepts | {int(src)}
                if len(accepts) == majority(len(self.peer_ids) + 1):
                    proposal = state.proposal
                    assert proposal is not None, "proposal expected"
                    o.broadcast(self.peer_ids, Internal(Decided(
                        ballot=inner.ballot, proposal=proposal)))
                    request_id, requester_id, _ = proposal
                    o.send(Id(requester_id), PutOk(request_id))
                    return PaxosState(
                        ballot=state.ballot, proposal=state.proposal,
                        prepares=state.prepares, accepts=accepts,
                        accepted=state.accepted, is_decided=True)
                return PaxosState(
                    ballot=state.ballot, proposal=state.proposal,
                    prepares=state.prepares, accepts=accepts,
                    accepted=state.accepted, is_decided=False)

            if isinstance(inner, Decided):
                return PaxosState(
                    ballot=inner.ballot, proposal=state.proposal,
                    prepares=state.prepares, accepts=state.accepts,
                    accepted=(inner.ballot, inner.proposal),
                    is_decided=True)
        return None


@dataclass
class PaxosModelCfg:
    client_count: int
    server_count: int
    network: Network

    def into_model(self) -> ActorModel:
        model = ActorModel(
            cfg=self,
            init_history=LinearizabilityTester(Register('\0')))
        for i in range(self.server_count):
            model.actor(RegisterServer(PaxosActor(
                model_peers(i, self.server_count))))
        for _ in range(self.client_count):
            model.actor(RegisterClient(
                put_count=1, server_count=self.server_count))

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != '\0':
                    return True
            return False

        return (model
                .init_network(self.network)
                .property(Expectation.ALWAYS, "linearizable",
                          lambda _, state:
                          state.history.serialized_history() is not None)
                .property(Expectation.SOMETIMES, "value chosen",
                          value_chosen)
                .record_msg_in(record_returns)
                .record_msg_out(record_invocations))


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    if cmd == "check":
        client_count = int(args[1]) if len(args) > 1 else 2
        network = Network.from_name(args[2]) if len(args) > 2 \
            else Network.new_unordered_nonduplicating()
        print(f"Model checking Single Decree Paxos with {client_count} "
              "clients.")
        (PaxosModelCfg(client_count=client_count, server_count=3,
                       network=network)
         .into_model().checker().spawn_bfs().report(sys.stdout))
    elif cmd == "explore":
        client_count = int(args[1]) if len(args) > 1 else 2
        address = args[2] if len(args) > 2 else "localhost:3000"
        network = Network.from_name(args[3]) if len(args) > 3 \
            else Network.new_unordered_nonduplicating()
        (PaxosModelCfg(client_count=client_count, server_count=3,
                       network=network)
         .into_model().checker().serve(address))
    elif cmd == "check-tpu":
        client_count = int(args[1]) if len(args) > 1 else 2
        from .paxos_packed import PackedPaxos
        print(f"Model checking Single Decree Paxos with {client_count} "
              "clients on the TPU engine.")
        (PackedPaxos(client_count).checker().spawn_tpu()
         .report(sys.stdout))
    elif cmd == "spawn":
        from .paxos_spawn import spawn_paxos_cluster
        spawn_paxos_cluster()
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.paxos check "
              "[CLIENT_COUNT] [NETWORK]")
        print("  python -m stateright_tpu.examples.paxos check-tpu "
              "[CLIENT_COUNT]")
        print("  python -m stateright_tpu.examples.paxos explore "
              "[CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  python -m stateright_tpu.examples.paxos spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
