"""Packed Single-Decree Paxos: the north-star workload on the TPU engine.

The same protocol as :mod:`stateright_tpu.examples.paxos` (a behavioral
port of `/root/reference/examples/paxos.rs`), expressed as a
:class:`~stateright_tpu.actor.packed.PackedActorModel` so ``spawn_tpu``
checks it on device. The host side IS the ActorModel semantics (servers,
register clients, linearizability history), so host BFS on this model and
the plain paxos model agree state-for-state (16,668 for 2 clients).

Packed layout decisions (all bit-level orderings chosen so that *integer*
comparison of packed words equals the host's tuple comparison):

* value code: ``'\\0'`` = 0, ``chr(ord('A')+k)`` = k+1 (monotonic in the
  char, so max-by-proposal picks the same winner);
* ballot ``(r, l)`` = ``r<<4 | l`` (12 bits);
* proposal ``(req, requester, value)`` = ``req<<8 | requester<<4 | value``
  (14 bits, flag bit 15 when wrapped in an Option);
* ``last_accepted`` Option[(Ballot, Proposal)] = ``flag<<26 | ballot<<14 |
  proposal`` (27 bits) — integer order equals the host's
  ``None-least, then (Ballot, Proposal)`` order (`paxos.rs:157-180`);
* server state = 3+S words: [ballot|accepts|decided, proposal,
  prepares×S, accepted];
* client state = 1 word: [flag<<31 | awaiting<<8 | op_count];
* message = 2 words: [type<<24 | a<<12 | b, c];
* history = 1 + 3·C words: [valid] + per client thread
  [entry0, entry1, in_flight] where an entry packs
  present | op kind | op value | ret value | last-completed codes
  (2 bits per peer: 0 = none, k = peer completed k ops at invocation) —
  a bounded, injective encoding of the LinearizabilityTester's
  ``_key()`` state for put_count=1 clients.
"""

from __future__ import annotations

import sys
from typing import Any, List, Optional, Tuple

from ..actor import Id, Network
from ..actor.packed import PackedActorModel
from ..actor.register import (Get, GetOk, Internal, Put, PutOk,
                              RegisterClient, RegisterServer,
                              record_invocations, record_returns)
from ..core import Expectation
from ..semantics import LinearizabilityTester, Register
from ..semantics.register import Read as ReadOp, ReadOk, Write as WriteOp, \
    WriteOk
from .paxos import (Accept, Accepted, Decided, PaxosActor, PaxosState,
                    Prepare, Prepared)

# message type tags
T_PUT, T_GET, T_PUTOK, T_GETOK = 1, 2, 3, 4
T_PREPARE, T_PREPARED, T_ACCEPT, T_ACCEPTED, T_DECIDED = 5, 6, 7, 8, 9


def _val_code(value: Any) -> int:
    if value == '\0':
        return 0
    code = ord(value) - ord('A') + 1
    assert 1 <= code <= 15, f"value out of packed range: {value!r}"
    return code


def _val_char(code: int) -> str:
    return '\0' if code == 0 else chr(ord('A') + code - 1)


def _ballot_word(ballot: Tuple[int, int]) -> int:
    r, l = ballot
    assert r <= 0xFF and l <= 0xF
    return (r << 4) | l


def _ballot_tuple(word: int) -> Tuple[int, int]:
    return (word >> 4, word & 0xF)


def _proposal_word(proposal) -> int:
    """14-bit packed (req, requester, value); 0x8000 flag when optional."""
    req, requester, value = proposal
    assert req <= 0x3F and requester <= 0xF
    return (req << 8) | (requester << 4) | _val_code(value)


def _proposal_tuple(word: int) -> Tuple[int, int, str]:
    return ((word >> 8) & 0x3F, (word >> 4) & 0xF, _val_char(word & 0xF))


def _la_word(last_accepted) -> int:
    """27-bit packed Option[(Ballot, Proposal)] (`paxos.rs:71-74`)."""
    if last_accepted is None:
        return 0
    ballot, proposal = last_accepted
    return (1 << 26) | (_ballot_word(ballot) << 14) \
        | _proposal_word(proposal)


def _la_tuple(word: int):
    if not (word >> 26) & 1:
        return None
    return (_ballot_tuple((word >> 14) & 0xFFF),
            _proposal_tuple(word & 0x3FFF))


class PackedPaxos(PackedActorModel):
    """Paxos with S servers + C put-once register clients, packed."""

    def __init__(self, client_count: int, server_count: int = 3,
                 net_capacity: int = 16):
        assert server_count <= 4, "accepts mask packs up to 4 servers"
        assert client_count <= 7, "last-completed codes pack up to 7 peers"
        super().__init__(cfg=self,
                         init_history=LinearizabilityTester(Register('\0')))
        self.client_count = client_count
        self.server_count = server_count
        self._server_w = 3 + server_count
        for i in range(server_count):
            peers = [Id(j) for j in range(server_count) if j != i]
            self.actor(RegisterServer(PaxosActor(peers)))
        for _ in range(client_count):
            self.actor(RegisterClient(put_count=1,
                                      server_count=server_count))
        self.init_network(Network.new_unordered_nonduplicating())

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != '\0':
                    return True
            return False

        self.property(Expectation.ALWAYS, "linearizable",
                      lambda _, state:
                      state.history.serialized_history() is not None)
        self.property(Expectation.SOMETIMES, "value chosen", value_chosen)
        self.record_msg_in(record_returns)
        self.record_msg_out(record_invocations)

        # --- packed schema ---------------------------------------------
        self.actor_widths = [self._server_w] * server_count \
            + [1] * client_count
        self.msg_width = 2
        self.net_capacity = net_capacity
        self.history_width = 1 + 3 * client_count
        self.max_sends = server_count  # Decided broadcast + PutOk
        self.host_property_indices = (0,)  # linearizable
        self.finalize_layout()

    def cache_key(self):
        return ("paxos", self.client_count, self.server_count,
                self.net_capacity)

    # ------------------------------------------------------------------
    # actor state packing
    # ------------------------------------------------------------------
    def encode_actor(self, index: int, state: Any) -> List[int]:
        s = self.server_count
        if index < s:
            p: PaxosState = state.state  # unwrap ServerState
            w0 = _ballot_word(p.ballot)
            for a in p.accepts:
                w0 |= 1 << (12 + a)
            w0 |= int(p.is_decided) << 16
            w1 = 0 if p.proposal is None \
                else (1 << 15) | _proposal_word(p.proposal)
            preps = [0] * s
            for sid, la in p.prepares:
                preps[sid] = (1 << 27) | _la_word(la)
            return [w0, w1] + preps + [_la_word(p.accepted)]
        c = state  # ClientState
        w = (c.op_count & 0xF)
        if c.awaiting is not None:
            w |= (1 << 31) | (c.awaiting << 8)
        return [w]

    def decode_actor(self, index: int, words: List[int]) -> Any:
        from .paxos import PaxosState
        from ..actor.register import ClientState, ServerState
        s = self.server_count
        if index < s:
            w0, w1 = words[0], words[1]
            preps = words[2:2 + s]
            ballot = _ballot_tuple(w0 & 0xFFF)
            accepts = frozenset(a for a in range(s)
                                if (w0 >> (12 + a)) & 1)
            decided = bool((w0 >> 16) & 1)
            proposal = _proposal_tuple(w1 & 0x3FFF) if (w1 >> 15) & 1 \
                else None
            prepares = tuple(sorted(
                (sid, _la_tuple(pw & 0x7FFFFFF))
                for sid, pw in enumerate(preps) if (pw >> 27) & 1))
            return ServerState(PaxosState(
                ballot=ballot, proposal=proposal, prepares=prepares,
                accepts=accepts, accepted=_la_tuple(words[2 + s]),
                is_decided=decided))
        w = words[0]
        awaiting = (w >> 8) & 0xFF if (w >> 31) & 1 else None
        return ClientState(awaiting=awaiting, op_count=w & 0xF)

    # ------------------------------------------------------------------
    # message packing: [type<<24 | a<<12 | b, c]
    # ------------------------------------------------------------------
    def encode_msg(self, msg: Any) -> List[int]:
        if isinstance(msg, Put):
            return [(T_PUT << 24) | (msg.request_id << 12)
                    | _val_code(msg.value), 0]
        if isinstance(msg, Get):
            return [(T_GET << 24) | (msg.request_id << 12), 0]
        if isinstance(msg, PutOk):
            return [(T_PUTOK << 24) | (msg.request_id << 12), 0]
        if isinstance(msg, GetOk):
            return [(T_GETOK << 24) | (msg.request_id << 12)
                    | _val_code(msg.value), 0]
        assert isinstance(msg, Internal)
        inner = msg.msg
        if isinstance(inner, Prepare):
            return [(T_PREPARE << 24) | _ballot_word(inner.ballot), 0]
        if isinstance(inner, Prepared):
            return [(T_PREPARED << 24) | _ballot_word(inner.ballot),
                    _la_word(inner.last_accepted)]
        if isinstance(inner, Accept):
            return [(T_ACCEPT << 24) | _ballot_word(inner.ballot),
                    _proposal_word(inner.proposal)]
        if isinstance(inner, Accepted):
            return [(T_ACCEPTED << 24) | _ballot_word(inner.ballot), 0]
        assert isinstance(inner, Decided)
        return [(T_DECIDED << 24) | _ballot_word(inner.ballot),
                _proposal_word(inner.proposal)]

    def decode_msg(self, words: List[int]) -> Any:
        w0, c = words
        mtype = w0 >> 24
        a = (w0 >> 12) & 0xFFF
        b = w0 & 0xFFF
        if mtype == T_PUT:
            return Put(a, _val_char(b & 0xF))
        if mtype == T_GET:
            return Get(a)
        if mtype == T_PUTOK:
            return PutOk(a)
        if mtype == T_GETOK:
            return GetOk(a, _val_char(b & 0xF))
        if mtype == T_PREPARE:
            return Internal(Prepare(_ballot_tuple(b)))
        if mtype == T_PREPARED:
            return Internal(Prepared(_ballot_tuple(b), _la_tuple(c)))
        if mtype == T_ACCEPT:
            return Internal(Accept(_ballot_tuple(b), _proposal_tuple(c)))
        if mtype == T_ACCEPTED:
            return Internal(Accepted(_ballot_tuple(b)))
        assert mtype == T_DECIDED
        return Internal(Decided(_ballot_tuple(b), _proposal_tuple(c)))

    # ------------------------------------------------------------------
    # history packing (LinearizabilityTester over Register)
    # ------------------------------------------------------------------
    def _lc_bits(self, thread: int, lc: dict) -> int:
        """2-bit completed-count codes for each peer of ``thread``."""
        bits = 0
        pos = 0
        s = self.server_count
        for peer in range(self.client_count):
            if peer == thread:
                continue
            idx = lc.get(Id(s + peer))
            code = 0 if idx is None else idx + 1
            bits |= code << (2 * pos)
            pos += 1
        return bits

    def _lc_dict(self, thread: int, bits: int) -> dict:
        lc = {}
        pos = 0
        s = self.server_count
        for peer in range(self.client_count):
            if peer == thread:
                continue
            code = (bits >> (2 * pos)) & 3
            if code:
                lc[Id(s + peer)] = code - 1
            pos += 1
        return lc

    @staticmethod
    def _entry_word(lc_bits: int, op, ret) -> int:
        kind = int(isinstance(op, ReadOp))
        opval = 0 if kind else _val_code(op.value)
        retval = _val_code(ret.value) if isinstance(ret, ReadOk) else 0
        return (1 << 31) | (kind << 30) | (opval << 26) | (retval << 22) \
            | lc_bits

    def encode_history(self, history: LinearizabilityTester) -> List[int]:
        words = [int(history._valid)]
        s = self.server_count
        for t in range(self.client_count):
            tid = Id(s + t)
            entries = history._history.get(tid, [])
            assert len(entries) <= 2, "put_count=1 clients do <=2 ops"
            e = [0, 0]
            for k, (lc, op, ret) in enumerate(entries):
                e[k] = self._entry_word(self._lc_bits(t, lc), op, ret)
            inflight = 0
            if tid in history._in_flight:
                lc, op = history._in_flight[tid]
                kind = int(isinstance(op, ReadOp))
                opval = 0 if kind else _val_code(op.value)
                inflight = (1 << 31) | (kind << 30) | (opval << 26) \
                    | self._lc_bits(t, lc)
            words.extend([e[0], e[1], inflight])
        return words

    def decode_history(self, words: List[int]) -> LinearizabilityTester:
        tester = LinearizabilityTester(Register('\0'))
        tester._valid = bool(words[0] & 1)
        s = self.server_count
        for t in range(self.client_count):
            tid = Id(s + t)
            e0, e1, inflight = words[1 + 3 * t: 4 + 3 * t]
            entries = []
            for w in (e0, e1):
                if not (w >> 31) & 1:
                    continue
                kind = (w >> 30) & 1
                opval = (w >> 26) & 0xF
                retval = (w >> 22) & 0xF
                op = ReadOp() if kind else WriteOp(_val_char(opval))
                ret = ReadOk(_val_char(retval)) if kind else WriteOk()
                entries.append((self._lc_dict(t, w & 0x3FFF), op, ret))
            if entries:
                tester._history[tid] = entries
            if (inflight >> 31) & 1:
                kind = (inflight >> 30) & 1
                opval = (inflight >> 26) & 0xF
                op = ReadOp() if kind else WriteOp(_val_char(opval))
                tester._in_flight[tid] = (
                    self._lc_dict(t, inflight & 0x3FFF), op)
                tester._history.setdefault(tid, [])
        return tester

    # ------------------------------------------------------------------
    # device kernels
    # ------------------------------------------------------------------
    def _peer_counts(self, hist, thread: int):
        """Packed last-completed codes for ``thread`` from current
        per-peer completed counts (mirrors ``on_invoke``,
        `linearizability.rs:102-125`)."""
        import jax.numpy as jnp
        bits = jnp.uint32(0)
        pos = 0
        for peer in range(self.client_count):
            if peer == thread:
                continue
            e0 = hist[1 + 3 * peer]
            e1 = hist[2 + 3 * peer]
            count = ((e0 >> 31) & 1) + ((e1 >> 31) & 1)
            bits = bits | (count.astype(jnp.uint32) << (2 * pos))
            pos += 1
        return bits

    def packed_record_out(self, hist, src, dst, msg):
        """``record_invocations``: Put -> Write invoke, Get -> Read."""
        import jax.numpy as jnp
        mtype = msg[0] >> 24
        is_put = mtype == T_PUT
        applies = is_put | (mtype == T_GET)
        valid = (hist[0] & 1).astype(bool)
        s = self.server_count
        new = hist
        for t in range(self.client_count):
            sel = applies & (src == (s + t))
            inflight = hist[3 + 3 * t]
            has_inflight = ((inflight >> 31) & 1).astype(bool)
            # double-invoke invalidates the history (on_invoke raising
            # after setting _valid=False; the record hook swallows it)
            invalidate = sel & valid & has_inflight
            kind = jnp.where(is_put, jnp.uint32(0), jnp.uint32(1))
            opval = jnp.where(is_put, msg[0] & 0xF, jnp.uint32(0))
            word = (jnp.uint32(1) << 31) | (kind << 30) | (opval << 26) \
                | self._peer_counts(hist, t)
            do_set = sel & valid & ~has_inflight
            new = jnp.where(do_set, new.at[3 + 3 * t].set(word), new)
            new = jnp.where(invalidate,
                            new.at[0].set(hist[0] & ~jnp.uint32(1)), new)
        return new

    def packed_record_in(self, hist, src, dst, msg):
        """``record_returns``: GetOk -> ReadOk, PutOk -> WriteOk."""
        import jax.numpy as jnp
        mtype = msg[0] >> 24
        is_getok = mtype == T_GETOK
        applies = is_getok | (mtype == T_PUTOK)
        valid = (hist[0] & 1).astype(bool)
        s = self.server_count
        new = hist
        for t in range(self.client_count):
            sel = applies & (dst == (s + t))
            inflight = hist[3 + 3 * t]
            has_inflight = ((inflight >> 31) & 1).astype(bool)
            invalidate = sel & valid & ~has_inflight
            retval = jnp.where(is_getok, msg[0] & 0xF, jnp.uint32(0))
            entry = inflight | (retval << 22)
            count0 = ~((hist[1 + 3 * t] >> 31) & 1).astype(bool)
            slot = jnp.where(count0, 1 + 3 * t, 2 + 3 * t)
            do_set = sel & valid & has_inflight
            completed = new.at[slot].set(entry).at[3 + 3 * t].set(
                jnp.uint32(0))  # entry appended, in-flight cleared
            new = jnp.where(do_set, completed, new)
            new = jnp.where(invalidate,
                            new.at[0].set(hist[0] & ~jnp.uint32(1)), new)
        return new

    def _server_step(self, sid, w, src, msg):
        """One server's ``on_msg`` (`paxos.rs:85-172`) as masked JAX.

        ``sid`` is a *traced* server index: one copy of this body serves
        every server (peer targets are modular arithmetic on ``sid``),
        keeping the compiled graph size independent of the cluster size.
        """
        import jax.numpy as jnp
        s = self.server_count
        quorum = s // 2 + 1
        sid = sid.astype(jnp.uint32)
        srv_src = jnp.minimum(src, s - 1)  # clip for safe indexing
        w0, w1 = w[0], w[1]
        preps = w[2:2 + s]
        accepted = w[2 + s]
        ballot = w0 & 0xFFF
        accepts_mask = (w0 >> 12) & 0xF
        decided = ((w0 >> 16) & 1).astype(bool)
        has_proposal = ((w1 >> 15) & 1).astype(bool)

        mtype = msg[0] >> 24
        a = (msg[0] >> 12) & 0xFFF
        b = msg[0] & 0xFFF
        c = msg[1]

        zmsg = jnp.zeros((2,), jnp.uint32)
        sends = [[jnp.uint32(0), zmsg, jnp.bool_(False)]
                 for _ in range(self.max_sends)]

        def set_send(k, cond, dst, m):
            sends[k][0] = jnp.where(cond, dst.astype(jnp.uint32),
                                    sends[k][0])
            sends[k][1] = jnp.where(cond, m, sends[k][1])
            sends[k][2] = sends[k][2] | cond

        def broadcast(cond, m):
            # peers of sid are (sid+1+k) mod s (paxos.rs:100, :127, :153)
            for k in range(s - 1):
                set_send(k, cond, (sid + 1 + k) % s, m)

        # --- decided: only Get is answered (paxos.rs:87-95) -------------
        dec_get = decided & (mtype == T_GET)
        getok = jnp.stack([(jnp.uint32(T_GETOK) << 24) | (a << 12)
                           | (accepted & 0xF), jnp.uint32(0)])
        set_send(0, dec_get, src, getok)

        nw0, nw1 = w0, w1
        npreps = preps
        naccepted = accepted
        live = ~decided

        # --- Put: become leader of a fresh ballot (paxos.rs:96-104) -----
        put_ok = live & (mtype == T_PUT) & ~has_proposal
        nb = ((((ballot >> 4) + 1) << 4) | sid).astype(jnp.uint32)
        put_proposal = (jnp.uint32(1) << 15) | (a << 8) | (src << 4) \
            | (msg[0] & 0xF)
        nw0 = jnp.where(put_ok, nb, nw0)  # accepts/decided bits cleared
        nw1 = jnp.where(put_ok, put_proposal, nw1)
        self_entry = (jnp.uint32(1) << 27) | (accepted & 0x7FFFFFF)
        put_preps = jnp.where(jnp.arange(s, dtype=jnp.uint32) == sid,
                              self_entry, jnp.uint32(0))
        npreps = jnp.where(put_ok, put_preps, npreps)
        prepare_msg = jnp.stack([(jnp.uint32(T_PREPARE) << 24) | nb,
                                 jnp.uint32(0)])
        broadcast(put_ok, prepare_msg)

        # --- Prepare (paxos.rs:108-114) ---------------------------------
        prep_ok = live & (mtype == T_PREPARE) & (ballot < b)
        prepared_msg = jnp.stack([(jnp.uint32(T_PREPARED) << 24) | b,
                                  accepted & 0x7FFFFFF])
        nw0 = jnp.where(prep_ok, (nw0 & ~jnp.uint32(0xFFF)) | b, nw0)
        set_send(0, prep_ok, src, prepared_msg)

        # --- Prepared (paxos.rs:116-138) --------------------------------
        prpd_ok = live & (mtype == T_PREPARED) & (b == ballot)
        entry = (jnp.uint32(1) << 27) | (c & 0x7FFFFFF)
        src_sel = jnp.arange(s, dtype=jnp.uint32) == srv_src
        npreps = jnp.where(prpd_ok & src_sel, entry, npreps)
        present = (npreps >> 27) & 1
        count = present.sum()
        la_all = jnp.where(present.astype(bool), npreps & 0x7FFFFFF,
                           jnp.uint32(0))
        la_max = la_all.max()
        prpd_q = prpd_ok & (count == quorum)
        # leadership handoff: favor the newest accepted proposal
        la_has = ((la_max >> 26) & 1).astype(bool)
        q_proposal = jnp.where(
            la_has, (jnp.uint32(1) << 15) | (la_max & 0x3FFF), nw1)
        accept_msg = jnp.stack([(jnp.uint32(T_ACCEPT) << 24) | b,
                                q_proposal & 0x3FFF])
        nw1 = jnp.where(prpd_q, q_proposal, nw1)
        nw0 = jnp.where(prpd_q,
                        (nw0 & ~jnp.uint32(0xF << 12))
                        | ((jnp.uint32(1) << sid) << 12), nw0)
        naccepted = jnp.where(
            prpd_q,
            (jnp.uint32(1) << 26) | (b << 14) | (q_proposal & 0x3FFF),
            naccepted)
        broadcast(prpd_q, accept_msg)

        # --- Accept (paxos.rs:140-146) ----------------------------------
        acc_ok = live & (mtype == T_ACCEPT) & (ballot <= b)
        accepted_msg = jnp.stack([(jnp.uint32(T_ACCEPTED) << 24) | b,
                                  jnp.uint32(0)])
        nw0 = jnp.where(acc_ok, (nw0 & ~jnp.uint32(0xFFF)) | b, nw0)
        naccepted = jnp.where(
            acc_ok, (jnp.uint32(1) << 26) | (b << 14) | (c & 0x3FFF),
            naccepted)
        set_send(0, acc_ok, src, accepted_msg)

        # --- Accepted (paxos.rs:148-164) --------------------------------
        acd_ok = live & (mtype == T_ACCEPTED) & (b == ballot)
        new_mask = (accepts_mask | (jnp.uint32(1) << srv_src)) & 0xF
        cnt = jnp.uint32(0)
        for j in range(s):
            cnt = cnt + ((new_mask >> j) & 1)
        acd_q = acd_ok & (cnt == quorum)
        nw0 = jnp.where(acd_ok,
                        (nw0 & ~jnp.uint32(0xF << 12)) | (new_mask << 12),
                        nw0)
        nw0 = jnp.where(acd_q, nw0 | (jnp.uint32(1) << 16), nw0)
        decided_msg = jnp.stack([(jnp.uint32(T_DECIDED) << 24) | b,
                                 nw1 & 0x3FFF])
        putok_msg = jnp.stack([(jnp.uint32(T_PUTOK) << 24)
                               | (((nw1 >> 8) & 0x3F) << 12),
                               jnp.uint32(0)])
        requester = (nw1 >> 4) & 0xF
        broadcast(acd_q, decided_msg)
        set_send(self.max_sends - 1, acd_q, requester, putok_msg)

        # --- Decided (paxos.rs:166-171) ---------------------------------
        dcd = live & (mtype == T_DECIDED)
        nw0 = jnp.where(dcd, ((nw0 & ~jnp.uint32(0xFFF)) | b)
                        | (jnp.uint32(1) << 16), nw0)
        naccepted = jnp.where(
            dcd, (jnp.uint32(1) << 26) | (b << 14) | (c & 0x3FFF),
            naccepted)

        changed = put_ok | prep_ok | prpd_ok | acc_ok | acd_ok | dcd
        new_w = jnp.concatenate(
            [jnp.stack([nw0, nw1]), npreps, jnp.stack([naccepted])]) \
            .astype(jnp.uint32)
        return new_w, changed, sends

    def _client_step(self, index, w, src, msg):
        """Register client ``on_msg`` (`register.rs:127-216`).

        ``index`` is a traced actor index (>= server_count)."""
        import jax.numpy as jnp
        s = self.server_count
        index = index.astype(jnp.uint32)
        word = w[0]
        has_awaiting = ((word >> 31) & 1).astype(bool)
        awaiting = (word >> 8) & 0xFF
        opc = word & 0xF
        mtype = msg[0] >> 24
        a = (msg[0] >> 12) & 0xFFF

        putok = (mtype == T_PUTOK) & has_awaiting & (a == awaiting)
        getok = (mtype == T_GETOK) & has_awaiting & (a == awaiting)
        new_req = ((opc + 1) * index).astype(jnp.uint32)
        get_dst = ((index + opc) % s).astype(jnp.uint32)
        get_msg = jnp.stack([(jnp.uint32(T_GET) << 24) | (new_req << 12),
                             jnp.uint32(0)])
        new_word = jnp.where(
            putok,
            (jnp.uint32(1) << 31) | (new_req << 8) | (opc + 1),
            jnp.where(getok, (opc + 1) & 0xF, word))
        zmsg = jnp.zeros((2,), jnp.uint32)
        sends = [[jnp.uint32(0), zmsg, jnp.bool_(False)]
                 for _ in range(self.max_sends)]
        sends[0][0] = jnp.where(putok, get_dst, sends[0][0])
        sends[0][1] = jnp.where(putok, get_msg, sends[0][1])
        sends[0][2] = putok
        return new_word[None].astype(jnp.uint32), putok | getok, sends

    def packed_deliver(self, actors, src, dst, msg):
        """Dynamic dispatch on the traced ``dst``: one server-handler and
        one client-handler instance in the graph, with the destination's
        state read and written via one-hot mask arithmetic (dynamic
        slices are the expensive primitive under vmap in the engine's
        device loop)."""
        import jax.numpy as jnp
        s = self.server_count
        sw = self._server_w
        dst = dst.astype(jnp.uint32)
        is_server = dst < s
        iota = jnp.arange(self._aw, dtype=jnp.int32)

        sidx = jnp.minimum(dst, s - 1)
        s_off = (sidx * sw).astype(jnp.int32)
        # one (aw, sw) one-hot encodes the server span mapping for both
        # the read (gather) and the write-back (scatter) below
        onehot = iota[:, None] == (s_off + jnp.arange(sw)[None, :])
        s_words = (jnp.where(onehot, actors[:, None], 0)
                   .sum(axis=0).astype(jnp.uint32))
        n_sw, s_ch, s_snds = self._server_step(sidx, s_words, src, msg)

        cidx = jnp.clip(dst.astype(jnp.int32) - s, 0,
                        self.client_count - 1)
        c_off = (s * sw + cidx).astype(jnp.int32)
        c_words = jnp.where(iota == c_off, actors, 0).sum()[None].astype(
            jnp.uint32)
        n_cw, c_ch, c_snds = self._client_step(cidx + s, c_words, src,
                                               msg)

        # write-back via the same one-hot: position i takes n_sw[i - s_off]
        # inside the server span (resp. n_cw at c_off), else keeps its word
        span = onehot.any(axis=1)
        scatter_sw = (jnp.where(onehot, n_sw[None, :], 0)).sum(axis=1)
        upd_server = jnp.where(span, scatter_sw, actors)
        upd_client = jnp.where(iota == c_off, n_cw[0], actors)
        new_actors = jnp.where(is_server, upd_server, upd_client)
        changed = jnp.where(is_server, s_ch, c_ch)
        sends = []
        for k in range(self.max_sends):
            sends.append((
                jnp.where(is_server, s_snds[k][0], c_snds[k][0]),
                jnp.where(is_server, s_snds[k][1], c_snds[k][1]),
                jnp.where(is_server, s_snds[k][2], c_snds[k][2])))
        return new_actors, changed, sends

    def host_property_key(self, row) -> bytes:
        """The linearizable property depends only on the history words."""
        import numpy as np
        return np.asarray(row[self._hist_off:], dtype=np.uint32).tobytes()

    def packed_properties(self, words):
        import jax.numpy as jnp
        # index 0 "linearizable" is host-evaluated: neutral True
        chosen = jnp.bool_(False)
        for e in range(self.net_capacity):
            off = self._net_off + e * self._sw
            hdr = words[off]
            m0 = words[off + 2]
            occupied = (hdr >> 16) & 1
            is_getok = (m0 >> 24) == T_GETOK
            has_value = (m0 & 0xF) != 0
            chosen = chosen | (occupied.astype(bool) & is_getok
                               & has_value)
        return jnp.stack([jnp.bool_(True), chosen])


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    client_count = int(args[1]) if len(args) > 1 else 2
    if cmd == "check-tpu":
        print(f"Model checking packed Paxos with {client_count} clients "
              "on the TPU engine.")
        PackedPaxos(client_count).checker().spawn_tpu().report(sys.stdout)
    elif cmd == "check":
        print(f"Model checking packed Paxos with {client_count} clients "
              "on the host engine.")
        PackedPaxos(client_count).checker().spawn_bfs().report(sys.stdout)
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.paxos_packed "
              "check-tpu [CLIENT_COUNT]")
        print("  python -m stateright_tpu.examples.paxos_packed "
              "check [CLIENT_COUNT]")


if __name__ == "__main__":
    main()
