"""Packed Single-Decree Paxos: the north-star workload on the TPU engine.

The same protocol as :mod:`stateright_tpu.examples.paxos` (a behavioral
port of `/root/reference/examples/paxos.rs`), expressed as a
:class:`~stateright_tpu.actor.packed.PackedActorModel` so ``spawn_tpu``
checks it on device. The host side IS the ActorModel semantics (servers,
register clients, linearizability history), so host BFS on this model and
the plain paxos model agree state-for-state (16,668 for 2 clients).

Packed layout decisions (all bit-level orderings chosen so that *integer*
comparison of packed words equals the host's tuple comparison):

* value code: ``'\\0'`` = 0, ``chr(ord('A')+k)`` = k+1 (monotonic in the
  char, so max-by-proposal picks the same winner);
* ballot ``(r, l)`` = ``r<<4 | l`` (12 bits);
* proposal ``(req, requester, value)`` = ``req<<8 | requester<<4 | value``
  (14 bits, flag bit 15 when wrapped in an Option);
* ``last_accepted`` Option[(Ballot, Proposal)] = ``flag<<26 | ballot<<14 |
  proposal`` (27 bits) — integer order equals the host's
  ``None-least, then (Ballot, Proposal)`` order (`paxos.rs:157-180`);
* server state = 3+S words: [ballot|accepts|decided, proposal,
  prepares×S, accepted];
* client state = 1 word: [flag<<31 | awaiting<<8 | op_count];
* message = 2 words: [type<<24 | a<<12 | b, c];
* history = 1 + 3·C words: [valid] + per client thread
  [entry0, entry1, in_flight] where an entry packs
  present | op kind | op value | ret value | last-completed codes
  (2 bits per peer: 0 = none, k = peer completed k ops at invocation) —
  a bounded, injective encoding of the LinearizabilityTester's
  ``_key()`` state for put_count=1 clients.
"""

from __future__ import annotations

import sys
from typing import Any, List, Optional, Tuple

from ..actor import Id
from ..actor.packed_register import (PackedRegisterModel, T_GET, T_GETOK,
                                     T_INTERNAL0, T_PUT, T_PUTOK,
                                     val_char as _val_char,
                                     val_code as _val_code)
from .paxos import (Accept, Accepted, Decided, PaxosActor, PaxosState,
                    Prepare, Prepared)

# protocol-internal message type tags
T_PREPARE, T_PREPARED, T_ACCEPT, T_ACCEPTED, T_DECIDED = range(
    T_INTERNAL0, T_INTERNAL0 + 5)


def _ballot_word(ballot: Tuple[int, int]) -> int:
    r, l = ballot
    assert r <= 0xFF and l <= 0xF
    return (r << 4) | l


def _ballot_tuple(word: int) -> Tuple[int, int]:
    return (word >> 4, word & 0xF)


def _proposal_word(proposal) -> int:
    """14-bit packed (req, requester, value); 0x8000 flag when optional."""
    req, requester, value = proposal
    assert req <= 0x3F and requester <= 0xF
    return (req << 8) | (requester << 4) | _val_code(value)


def _proposal_tuple(word: int) -> Tuple[int, int, str]:
    return ((word >> 8) & 0x3F, (word >> 4) & 0xF, _val_char(word & 0xF))


def _la_word(last_accepted) -> int:
    """27-bit packed Option[(Ballot, Proposal)] (`paxos.rs:71-74`)."""
    if last_accepted is None:
        return 0
    ballot, proposal = last_accepted
    return (1 << 26) | (_ballot_word(ballot) << 14) \
        | _proposal_word(proposal)


def _la_tuple(word: int):
    if not (word >> 26) & 1:
        return None
    return (_ballot_tuple((word >> 14) & 0xFFF),
            _proposal_tuple(word & 0x3FFF))


class PackedPaxos(PackedRegisterModel):
    """Paxos with S servers + C put-once register clients, packed.

    Client slots, register messages, the linearizability history, and the
    one-hot dispatch come from :class:`PackedRegisterModel`; this class
    supplies the paxos server packing and its masked step kernel."""

    def __init__(self, client_count: int, server_count: int = 3,
                 net_capacity: int = 16):
        self._init_register(
            client_count, server_count,
            server_actor=lambda i: PaxosActor(
                [Id(j) for j in range(server_count) if j != i]),
            server_width=3 + server_count,
            net_capacity=net_capacity,
            max_sends=server_count)  # Decided broadcast + PutOk
        # measured batch branching ~3.3 valid children per state on the
        # device engine (profile()['vmax'] / fmax); sizes the engine's
        # candidate buffer well below the max_actions axis
        self.branching_hint = 4

    def cache_key(self):
        return ("paxos", self.client_count, self.server_count,
                self.net_capacity)

    def durable_word_mask(self, index: int) -> List[int]:
        """Crash–restart support: a paxos server's entire state is on
        stable storage (the protocol is *defined* against crash–recovery
        with durable promises and accepted proposals), so a crash wipes
        nothing — the fault injected is the downtime itself (deliveries
        pause while down). Clients stay fail-stop (all-volatile)."""
        if index < self.server_count:
            return [1] * self.actor_widths[index]
        return [0] * self.actor_widths[index]

    # ------------------------------------------------------------------
    # server state packing
    # ------------------------------------------------------------------
    def encode_server(self, p: PaxosState) -> List[int]:
        s = self.server_count
        w0 = _ballot_word(p.ballot)
        for a in p.accepts:
            w0 |= 1 << (12 + a)
        w0 |= int(p.is_decided) << 16
        w1 = 0 if p.proposal is None \
            else (1 << 15) | _proposal_word(p.proposal)
        preps = [0] * s
        for sid, la in p.prepares:
            preps[sid] = (1 << 27) | _la_word(la)
        return [w0, w1] + preps + [_la_word(p.accepted)]

    def decode_server(self, words: List[int]) -> PaxosState:
        s = self.server_count
        w0, w1 = words[0], words[1]
        preps = words[2:2 + s]
        ballot = _ballot_tuple(w0 & 0xFFF)
        accepts = frozenset(a for a in range(s)
                            if (w0 >> (12 + a)) & 1)
        decided = bool((w0 >> 16) & 1)
        proposal = _proposal_tuple(w1 & 0x3FFF) if (w1 >> 15) & 1 \
            else None
        prepares = tuple(sorted(
            (sid, _la_tuple(pw & 0x7FFFFFF))
            for sid, pw in enumerate(preps) if (pw >> 27) & 1))
        return PaxosState(
            ballot=ballot, proposal=proposal, prepares=prepares,
            accepts=accepts, accepted=_la_tuple(words[2 + s]),
            is_decided=decided)

    # ------------------------------------------------------------------
    # message packing: [type<<24 | a<<12 | b, c]
    # ------------------------------------------------------------------
    def encode_internal(self, inner: Any) -> List[int]:
        if isinstance(inner, Prepare):
            return [(T_PREPARE << 24) | _ballot_word(inner.ballot), 0]
        if isinstance(inner, Prepared):
            return [(T_PREPARED << 24) | _ballot_word(inner.ballot),
                    _la_word(inner.last_accepted)]
        if isinstance(inner, Accept):
            return [(T_ACCEPT << 24) | _ballot_word(inner.ballot),
                    _proposal_word(inner.proposal)]
        if isinstance(inner, Accepted):
            return [(T_ACCEPTED << 24) | _ballot_word(inner.ballot), 0]
        assert isinstance(inner, Decided)
        return [(T_DECIDED << 24) | _ballot_word(inner.ballot),
                _proposal_word(inner.proposal)]

    def decode_internal(self, words: List[int]) -> Any:
        w0, c = words
        mtype = w0 >> 24
        b = w0 & 0xFFF
        if mtype == T_PREPARE:
            return Prepare(_ballot_tuple(b))
        if mtype == T_PREPARED:
            return Prepared(_ballot_tuple(b), _la_tuple(c))
        if mtype == T_ACCEPT:
            return Accept(_ballot_tuple(b), _proposal_tuple(c))
        if mtype == T_ACCEPTED:
            return Accepted(_ballot_tuple(b))
        assert mtype == T_DECIDED
        return Decided(_ballot_tuple(b), _proposal_tuple(c))

    # ------------------------------------------------------------------
    # the masked server kernel
    # ------------------------------------------------------------------
    def _server_step(self, sid, w, src, msg):
        """One server's ``on_msg`` (`paxos.rs:85-172`) as masked JAX.

        ``sid`` is a *traced* server index: one copy of this body serves
        every server (peer targets are modular arithmetic on ``sid``),
        keeping the compiled graph size independent of the cluster size.
        """
        import jax.numpy as jnp
        s = self.server_count
        quorum = s // 2 + 1
        sid = sid.astype(jnp.uint32)
        srv_src = jnp.minimum(src, s - 1)  # clip for safe indexing
        w0, w1 = w[0], w[1]
        preps = w[2:2 + s]
        accepted = w[2 + s]
        ballot = w0 & 0xFFF
        accepts_mask = (w0 >> 12) & 0xF
        decided = ((w0 >> 16) & 1).astype(bool)
        has_proposal = ((w1 >> 15) & 1).astype(bool)

        mtype = msg[0] >> 24
        a = (msg[0] >> 12) & 0xFFF
        b = msg[0] & 0xFFF
        c = msg[1]

        zmsg = jnp.zeros((2,), jnp.uint32)
        sends = [[jnp.uint32(0), zmsg, jnp.bool_(False)]
                 for _ in range(self.max_sends)]

        def set_send(k, cond, dst, m):
            sends[k][0] = jnp.where(cond, dst.astype(jnp.uint32),
                                    sends[k][0])
            sends[k][1] = jnp.where(cond, m, sends[k][1])
            sends[k][2] = sends[k][2] | cond

        def broadcast(cond, m):
            # peers of sid are (sid+1+k) mod s (paxos.rs:100, :127, :153)
            for k in range(s - 1):
                set_send(k, cond, (sid + 1 + k) % s, m)

        # --- decided: only Get is answered (paxos.rs:87-95) -------------
        dec_get = decided & (mtype == T_GET)
        getok = jnp.stack([(jnp.uint32(T_GETOK) << 24) | (a << 12)
                           | (accepted & 0xF), jnp.uint32(0)])
        set_send(0, dec_get, src, getok)

        nw0, nw1 = w0, w1
        npreps = preps
        naccepted = accepted
        live = ~decided

        # --- Put: become leader of a fresh ballot (paxos.rs:96-104) -----
        put_ok = live & (mtype == T_PUT) & ~has_proposal
        nb = ((((ballot >> 4) + 1) << 4) | sid).astype(jnp.uint32)
        put_proposal = (jnp.uint32(1) << 15) | (a << 8) | (src << 4) \
            | (msg[0] & 0xF)
        nw0 = jnp.where(put_ok, nb, nw0)  # accepts/decided bits cleared
        nw1 = jnp.where(put_ok, put_proposal, nw1)
        self_entry = (jnp.uint32(1) << 27) | (accepted & 0x7FFFFFF)
        put_preps = jnp.where(jnp.arange(s, dtype=jnp.uint32) == sid,
                              self_entry, jnp.uint32(0))
        npreps = jnp.where(put_ok, put_preps, npreps)
        prepare_msg = jnp.stack([(jnp.uint32(T_PREPARE) << 24) | nb,
                                 jnp.uint32(0)])
        broadcast(put_ok, prepare_msg)

        # --- Prepare (paxos.rs:108-114) ---------------------------------
        prep_ok = live & (mtype == T_PREPARE) & (ballot < b)
        prepared_msg = jnp.stack([(jnp.uint32(T_PREPARED) << 24) | b,
                                  accepted & 0x7FFFFFF])
        nw0 = jnp.where(prep_ok, (nw0 & ~jnp.uint32(0xFFF)) | b, nw0)
        set_send(0, prep_ok, src, prepared_msg)

        # --- Prepared (paxos.rs:116-138) --------------------------------
        prpd_ok = live & (mtype == T_PREPARED) & (b == ballot)
        entry = (jnp.uint32(1) << 27) | (c & 0x7FFFFFF)
        src_sel = jnp.arange(s, dtype=jnp.uint32) == srv_src
        npreps = jnp.where(prpd_ok & src_sel, entry, npreps)
        present = (npreps >> 27) & 1
        count = present.sum()
        la_all = jnp.where(present.astype(bool), npreps & 0x7FFFFFF,
                           jnp.uint32(0))
        la_max = la_all.max()
        prpd_q = prpd_ok & (count == quorum)
        # leadership handoff: favor the newest accepted proposal
        la_has = ((la_max >> 26) & 1).astype(bool)
        q_proposal = jnp.where(
            la_has, (jnp.uint32(1) << 15) | (la_max & 0x3FFF), nw1)
        accept_msg = jnp.stack([(jnp.uint32(T_ACCEPT) << 24) | b,
                                q_proposal & 0x3FFF])
        nw1 = jnp.where(prpd_q, q_proposal, nw1)
        nw0 = jnp.where(prpd_q,
                        (nw0 & ~jnp.uint32(0xF << 12))
                        | ((jnp.uint32(1) << sid) << 12), nw0)
        naccepted = jnp.where(
            prpd_q,
            (jnp.uint32(1) << 26) | (b << 14) | (q_proposal & 0x3FFF),
            naccepted)
        broadcast(prpd_q, accept_msg)

        # --- Accept (paxos.rs:140-146) ----------------------------------
        acc_ok = live & (mtype == T_ACCEPT) & (ballot <= b)
        accepted_msg = jnp.stack([(jnp.uint32(T_ACCEPTED) << 24) | b,
                                  jnp.uint32(0)])
        nw0 = jnp.where(acc_ok, (nw0 & ~jnp.uint32(0xFFF)) | b, nw0)
        naccepted = jnp.where(
            acc_ok, (jnp.uint32(1) << 26) | (b << 14) | (c & 0x3FFF),
            naccepted)
        set_send(0, acc_ok, src, accepted_msg)

        # --- Accepted (paxos.rs:148-164) --------------------------------
        acd_ok = live & (mtype == T_ACCEPTED) & (b == ballot)
        new_mask = (accepts_mask | (jnp.uint32(1) << srv_src)) & 0xF
        cnt = jnp.uint32(0)
        for j in range(s):
            cnt = cnt + ((new_mask >> j) & 1)
        acd_q = acd_ok & (cnt == quorum)
        nw0 = jnp.where(acd_ok,
                        (nw0 & ~jnp.uint32(0xF << 12)) | (new_mask << 12),
                        nw0)
        nw0 = jnp.where(acd_q, nw0 | (jnp.uint32(1) << 16), nw0)
        decided_msg = jnp.stack([(jnp.uint32(T_DECIDED) << 24) | b,
                                 nw1 & 0x3FFF])
        putok_msg = jnp.stack([(jnp.uint32(T_PUTOK) << 24)
                               | (((nw1 >> 8) & 0x3F) << 12),
                               jnp.uint32(0)])
        requester = (nw1 >> 4) & 0xF
        broadcast(acd_q, decided_msg)
        set_send(self.max_sends - 1, acd_q, requester, putok_msg)

        # --- Decided (paxos.rs:166-171) ---------------------------------
        dcd = live & (mtype == T_DECIDED)
        nw0 = jnp.where(dcd, ((nw0 & ~jnp.uint32(0xFFF)) | b)
                        | (jnp.uint32(1) << 16), nw0)
        naccepted = jnp.where(
            dcd, (jnp.uint32(1) << 26) | (b << 14) | (c & 0x3FFF),
            naccepted)

        changed = put_ok | prep_ok | prpd_ok | acc_ok | acd_ok | dcd
        new_w = jnp.concatenate(
            [jnp.stack([nw0, nw1]), npreps, jnp.stack([naccepted])]) \
            .astype(jnp.uint32)
        return new_w, changed, sends


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    client_count = int(args[1]) if len(args) > 1 else 2
    if cmd == "check-tpu":
        print(f"Model checking packed Paxos with {client_count} clients "
              "on the TPU engine.")
        PackedPaxos(client_count).checker().spawn_tpu().report(sys.stdout)
    elif cmd == "check":
        print(f"Model checking packed Paxos with {client_count} clients "
              "on the host engine.")
        PackedPaxos(client_count).checker().spawn_bfs().report(sys.stdout)
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.paxos_packed "
              "check-tpu [CLIENT_COUNT]")
        print("  python -m stateright_tpu.examples.paxos_packed "
              "check [CLIENT_COUNT]")


if __name__ == "__main__":
    main()
