"""Packed write-once register: the crash–restart demonstration pair.

An unreplicated write-once value server ('\\0' = unwritten; the first
``Put`` wins, a conflicting later ``Put`` gets ``PutFail``) checked by
put-once register clients with a linearizability history — the workload
proving ``crash_restart`` finds real bugs:

* ``PackedWriteOnce(c, durable=True)`` models a server whose register
  value is on stable storage (``durable_word_mask`` keeps the value
  word). Under ``crash_restart(1)`` it stays linearizable on both the
  host and the device engine.
* ``PackedWriteOnce(c, durable=False)`` models the buggy variant: the
  value lives only in volatile memory, so a crash silently loses an
  acknowledged write. Both engines must produce a linearizability
  counterexample whose path contains the ``Crash``/``Restart`` actions
  (client writes, gets ``PutOk``, server crashes and forgets, client
  reads '\\0').

The host side IS the ``ActorModel`` semantics — host BFS and ``spawn_tpu``
enumerate identical state counts and reach identical discoveries, the
crash–restart parity oracle next to paxos (`tests/test_crash_restart.py`).
"""

from __future__ import annotations

import sys
from typing import Any, List, Optional

from ..actor.core import Actor, Id, Out
from ..actor.packed_register import (PackedRegisterModel, T_GET, T_GETOK,
                                     T_INTERNAL0, T_PUT, T_PUTOK,
                                     val_char as _val_char,
                                     val_code as _val_code)
from ..actor.register import Get, GetOk, Put, PutOk

# write-once failure reply; reuses the first protocol-internal tag slot
# (this model has no internal messages)
T_PUTFAIL = T_INTERNAL0

from ..actor.write_once_register import PutFail


class WriteOnceActor(Actor):
    """Unreplicated write-once value server: first ``Put`` wins; a
    conflicting later ``Put`` fails; re-putting the same value succeeds
    (mirroring the ``WORegister`` spec semantics). '\\0' = unwritten."""

    def on_start(self, id: Id, o: Out) -> str:
        return '\0'

    def on_msg(self, id: Id, state: str, src: Id, msg: Any,
               o: Out) -> Optional[str]:
        if isinstance(msg, Put):
            if state == '\0':
                o.send(src, PutOk(msg.request_id))
                return msg.value
            if state == msg.value:
                o.send(src, PutOk(msg.request_id))
                return None
            o.send(src, PutFail(msg.request_id))
            return None
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state))
            return None
        return None


class PackedWriteOnce(PackedRegisterModel):
    """Write-once value server(s) + C put-once register clients.

    ``durable`` selects whether the server's register value survives a
    crash (``durable_word_mask``); it is part of the model identity.
    Enable fault injection with ``.crash_restart(k, actors=[0])``.
    """

    def __init__(self, client_count: int, server_count: int = 1,
                 durable: bool = True, net_capacity: int = 8):
        self.durable_server = bool(durable)
        self._init_register(
            client_count, server_count,
            server_actor=lambda i: WriteOnceActor(),
            server_width=1,
            net_capacity=net_capacity,
            max_sends=1)

    def cache_key(self):
        return ("write_once", self.client_count, self.server_count,
                self.net_capacity, self.durable_server)

    def durable_word_mask(self, index: int) -> List[int]:
        if index < self.server_count and self.durable_server:
            return [1] * self.actor_widths[index]
        return [0] * self.actor_widths[index]

    # --- server packing: one word, the stored value ----------------------
    def encode_server(self, val: str) -> List[int]:
        return [_val_code(val)]

    def decode_server(self, words: List[int]) -> str:
        return _val_char(words[0])

    def encode_internal(self, msg: Any) -> List[int]:
        raise AssertionError("write-once register has no internal msgs")

    def decode_internal(self, words: List[int]) -> Any:
        raise AssertionError("write-once register has no internal msgs")

    # PutFail rides the register vocabulary (tag T_PUTFAIL)
    def encode_msg(self, msg: Any) -> List[int]:
        if isinstance(msg, PutFail):
            return [(T_PUTFAIL << 24) | (msg.request_id << 12), 0]
        return super().encode_msg(msg)

    def decode_msg(self, words: List[int]) -> Any:
        if (words[0] >> 24) == T_PUTFAIL:
            return PutFail((words[0] >> 12) & 0xFFF)
        return super().decode_msg(words)

    # --- the masked server kernel ---------------------------------------
    def _server_step(self, sid, w, src, msg):
        import jax.numpy as jnp

        val = w[0]
        mtype = msg[0] >> 24
        m_rid = (msg[0] >> 12) & 0xFFF
        m_val = msg[0] & 0xF
        is_put = mtype == T_PUT
        is_get = mtype == T_GET
        unwritten = val == 0

        ok = is_put & (unwritten | (val == m_val))
        fail = is_put & ~unwritten & (val != m_val)
        new_val = jnp.where(is_put & unwritten, m_val, val)
        putok = jnp.stack([(jnp.uint32(T_PUTOK) << 24) | (m_rid << 12),
                           jnp.uint32(0)])
        putfail = jnp.stack([(jnp.uint32(T_PUTFAIL) << 24)
                             | (m_rid << 12), jnp.uint32(0)])
        getok = jnp.stack([(jnp.uint32(T_GETOK) << 24) | (m_rid << 12)
                           | val, jnp.uint32(0)])
        zmsg = jnp.zeros((2,), jnp.uint32)
        sends = [[jnp.uint32(0), zmsg, jnp.bool_(False)]
                 for _ in range(self.max_sends)]
        reply = is_put | is_get
        sends[0][0] = jnp.where(reply, src.astype(jnp.uint32),
                                sends[0][0])
        sends[0][1] = jnp.where(is_get, getok,
                                jnp.where(ok, putok,
                                          jnp.where(fail, putfail, zmsg)))
        sends[0][2] = reply
        changed = is_put & unwritten
        return new_val[None].astype(jnp.uint32), changed, sends


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    client_count = int(args[1]) if len(args) > 1 else 1
    volatile = "volatile" in args
    crashes = 0 if "no-crash" in args else 1
    if cmd in ("check", "check-tpu"):
        kind = "volatile" if volatile else "durable"
        print(f"Model checking a packed write-once register "
              f"({kind} server, {client_count} clients, "
              f"max_crashes={crashes}) on the "
              f"{'TPU' if cmd == 'check-tpu' else 'host'} engine.")
        model = PackedWriteOnce(client_count, durable=not volatile)
        if crashes:
            model.crash_restart(crashes, actors=[0])
        checker = model.checker()
        (checker.spawn_tpu() if cmd == "check-tpu"
         else checker.spawn_bfs()).report(sys.stdout)
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.write_once_packed "
              "check [CLIENT_COUNT] [volatile] [no-crash]")
        print("  python -m stateright_tpu.examples.write_once_packed "
              "check-tpu [CLIENT_COUNT] [volatile] [no-crash]")


if __name__ == "__main__":
    main()
