"""Run real Paxos servers over localhost UDP (`paxos spawn`).

Port of the reference's spawn subcommand (`/root/reference/examples/paxos.rs:358-381`):
the *same* :class:`~stateright_tpu.examples.paxos.PaxosActor` objects that
the checker exhaustively verified are executed by the UDP runtime, speaking
a JSON protocol simple enough to drive with netcat:

    $ nc -u localhost 3000
    {"Put": [1, "X"]}
    {"Get": [2]}

The serde functions use externally-tagged JSON (the shape serde_json gives
the reference's enums), shared with the other register-protocol examples.
"""

from __future__ import annotations

import json
from typing import Any

from ..actor import Id, peer_ids
from ..actor.register import (Get, GetOk, Internal, Put, PutOk,
                              register_msg_from_json, register_msg_to_json)
from ..actor.runtime import SpawnHandle, spawn
from .paxos import Accept, Accepted, Decided, PaxosActor, Prepare, Prepared


# --- JSON serde for the register + paxos protocol ---------------------------

def _ballot_json(ballot):
    return [ballot[0], ballot[1]]


def _proposal_json(proposal):
    return [proposal[0], proposal[1], proposal[2]]


def _la_json(la):
    if la is None:
        return None
    return [_ballot_json(la[0]), _proposal_json(la[1])]


def _encode_internal(inner: Any) -> dict:
    if isinstance(inner, Prepare):
        return {"Prepare": [_ballot_json(inner.ballot)]}
    if isinstance(inner, Prepared):
        return {"Prepared": [_ballot_json(inner.ballot),
                             _la_json(inner.last_accepted)]}
    if isinstance(inner, Accept):
        return {"Accept": [_ballot_json(inner.ballot),
                           _proposal_json(inner.proposal)]}
    if isinstance(inner, Accepted):
        return {"Accepted": [_ballot_json(inner.ballot)]}
    assert isinstance(inner, Decided), inner
    return {"Decided": [_ballot_json(inner.ballot),
                        _proposal_json(inner.proposal)]}


def msg_to_json(msg: Any) -> bytes:
    """Externally-tagged JSON encoding of a register/paxos message."""
    return register_msg_to_json(msg, _encode_internal)


def _ballot_from(v):
    return (v[0], v[1])


def _proposal_from(v):
    return (v[0], v[1], v[2])


def _la_from(v):
    if v is None:
        return None
    return (_ballot_from(v[0]), _proposal_from(v[1]))


def _decode_internal(tag: str, value) -> Any:
    if tag == "Prepare":
        return Prepare(_ballot_from(value[0]))
    if tag == "Prepared":
        return Prepared(_ballot_from(value[0]), _la_from(value[1]))
    if tag == "Accept":
        return Accept(_ballot_from(value[0]), _proposal_from(value[1]))
    if tag == "Accepted":
        return Accepted(_ballot_from(value[0]))
    assert tag == "Decided", tag
    return Decided(_ballot_from(value[0]), _proposal_from(value[1]))


def msg_from_json(data: bytes) -> Any:
    return register_msg_from_json(data, _decode_internal)


def spawn_paxos_cluster(port: int = 3000,
                        background: bool = False) -> SpawnHandle:
    """Spawn 3 Paxos servers on localhost UDP ports ``port..port+2``."""
    print("  A set of servers that implement Single Decree Paxos.")
    print("  You can monitor and interact using tcpdump and netcat. "
          "Examples:")
    print("$ sudo tcpdump -i lo -s 0 -nnX")
    print(f"$ nc -u localhost {port}")
    print(msg_to_json(Put(1, 'X')).decode())
    print(msg_to_json(Get(2)).decode())
    print()
    # WARNING (as in the reference): omits ordered_reliable_link to keep
    # the message protocol simple for nc.
    localhost = (127, 0, 0, 1)
    ids = [Id.from_socket_addr(localhost, port + i) for i in range(3)]
    actors = [(i, PaxosActor(peer_ids(i, ids))) for i in ids]
    return spawn(msg_to_json, msg_from_json, actors, background=background)
