"""Run real Paxos servers over localhost UDP (`paxos spawn`).

Port of the reference's spawn subcommand (`/root/reference/examples/paxos.rs:358-381`):
the *same* :class:`~stateright_tpu.examples.paxos.PaxosActor` objects that
the checker exhaustively verified are executed by the UDP runtime, speaking
a JSON protocol simple enough to drive with netcat:

    $ nc -u localhost 3000
    {"Put": [1, "X"]}
    {"Get": [2]}

The serde functions use externally-tagged JSON (the shape serde_json gives
the reference's enums), shared with the other register-protocol examples.
"""

from __future__ import annotations

import json
from typing import Any

from ..actor import Id
from ..actor.register import Get, GetOk, Internal, Put, PutOk
from ..actor.runtime import SpawnHandle, spawn
from .paxos import Accept, Accepted, Decided, PaxosActor, Prepare, Prepared


# --- JSON serde for the register + paxos protocol ---------------------------

def _ballot_json(ballot):
    return [ballot[0], ballot[1]]


def _proposal_json(proposal):
    return [proposal[0], proposal[1], proposal[2]]


def _la_json(la):
    if la is None:
        return None
    return [_ballot_json(la[0]), _proposal_json(la[1])]


def msg_to_json(msg: Any) -> bytes:
    """Externally-tagged JSON encoding of a register/paxos message."""
    if isinstance(msg, Put):
        obj = {"Put": [msg.request_id, msg.value]}
    elif isinstance(msg, Get):
        obj = {"Get": [msg.request_id]}
    elif isinstance(msg, PutOk):
        obj = {"PutOk": [msg.request_id]}
    elif isinstance(msg, GetOk):
        obj = {"GetOk": [msg.request_id, msg.value]}
    elif isinstance(msg, Internal):
        inner = msg.msg
        if isinstance(inner, Prepare):
            iobj = {"Prepare": [_ballot_json(inner.ballot)]}
        elif isinstance(inner, Prepared):
            iobj = {"Prepared": [_ballot_json(inner.ballot),
                                 _la_json(inner.last_accepted)]}
        elif isinstance(inner, Accept):
            iobj = {"Accept": [_ballot_json(inner.ballot),
                               _proposal_json(inner.proposal)]}
        elif isinstance(inner, Accepted):
            iobj = {"Accepted": [_ballot_json(inner.ballot)]}
        elif isinstance(inner, Decided):
            iobj = {"Decided": [_ballot_json(inner.ballot),
                                _proposal_json(inner.proposal)]}
        else:
            raise TypeError(f"unknown internal message {inner!r}")
        obj = {"Internal": iobj}
    else:
        raise TypeError(f"unknown message {msg!r}")
    return json.dumps(obj).encode()


def _ballot_from(v):
    return (v[0], v[1])


def _proposal_from(v):
    return (v[0], v[1], v[2])


def _la_from(v):
    if v is None:
        return None
    return (_ballot_from(v[0]), _proposal_from(v[1]))


def msg_from_json(data: bytes) -> Any:
    obj = json.loads(data)
    (tag, value), = obj.items()
    if tag == "Put":
        return Put(value[0], value[1])
    if tag == "Get":
        return Get(value[0])
    if tag == "PutOk":
        return PutOk(value[0])
    if tag == "GetOk":
        return GetOk(value[0], value[1])
    if tag == "Internal":
        (itag, ivalue), = value.items()
        if itag == "Prepare":
            return Internal(Prepare(_ballot_from(ivalue[0])))
        if itag == "Prepared":
            return Internal(Prepared(_ballot_from(ivalue[0]),
                                     _la_from(ivalue[1])))
        if itag == "Accept":
            return Internal(Accept(_ballot_from(ivalue[0]),
                                   _proposal_from(ivalue[1])))
        if itag == "Accepted":
            return Internal(Accepted(_ballot_from(ivalue[0])))
        if itag == "Decided":
            return Internal(Decided(_ballot_from(ivalue[0]),
                                    _proposal_from(ivalue[1])))
    raise ValueError(f"unknown message tag in {obj!r}")


def spawn_paxos_cluster(port: int = 3000,
                        background: bool = False) -> SpawnHandle:
    """Spawn 3 Paxos servers on localhost UDP ports ``port..port+2``."""
    print("  A set of servers that implement Single Decree Paxos.")
    print("  You can monitor and interact using tcpdump and netcat. "
          "Examples:")
    print("$ sudo tcpdump -i lo -s 0 -nnX")
    print(f"$ nc -u localhost {port}")
    print(msg_to_json(Put(1, 'X')).decode())
    print(msg_to_json(Get(2)).decode())
    print()
    # WARNING (as in the reference): omits ordered_reliable_link to keep
    # the message protocol simple for nc.
    localhost = (127, 0, 0, 1)
    ids = [Id.from_socket_addr(localhost, port + i) for i in range(3)]
    actors = [
        (ids[i], PaxosActor([ids[j] for j in range(3) if j != i]))
        for i in range(3)
    ]
    return spawn(msg_to_json, msg_from_json, actors, background=background)
