"""Packed single-copy register: the linearizability-counterexample
workload on the TPU engine.

The same system as :mod:`stateright_tpu.examples.single_copy_register`
(a behavioral port of `/root/reference/examples/single-copy-register.rs`):
unreplicated value servers. One server is linearizable (93 states for 2
clients, `single-copy-register.rs:100`); two servers are NOT — the checker
must produce a linearizability counterexample (the reference stops after
20 states, `:121`; the device engine, which evaluates the host property
post-hoc per chunk, may explore more before reporting — any valid
counterexample is accepted, as with the reference's multithreaded runs).

This is the workload proving the device engine can *catch* a
linearizability bug, not just confirm absence. Server state = 1 word
(the value code)."""

from __future__ import annotations

import sys
from typing import Any, List

from ..actor.packed_register import (PackedRegisterModel,
                                     T_GET, T_GETOK, T_PUT, T_PUTOK,
                                     val_char as _val_char,
                                     val_code as _val_code)
from .single_copy_register import SingleCopyActor


class PackedSingleCopy(PackedRegisterModel):
    """Unreplicated value server(s) + C put-once register clients."""

    def __init__(self, client_count: int, server_count: int = 1,
                 net_capacity: int = 16):
        self._init_register(
            client_count, server_count,
            server_actor=lambda i: SingleCopyActor(),
            server_width=1,
            net_capacity=net_capacity,
            max_sends=1)

    def cache_key(self):
        return ("single_copy", self.client_count, self.server_count,
                self.net_capacity)

    # --- server packing: one word, the stored value ----------------------
    def encode_server(self, val: str) -> List[int]:
        return [_val_code(val)]

    def decode_server(self, words: List[int]) -> str:
        return _val_char(words[0])

    def encode_internal(self, msg: Any) -> List[int]:
        raise AssertionError("single-copy register has no internal msgs")

    def decode_internal(self, words: List[int]) -> Any:
        raise AssertionError("single-copy register has no internal msgs")

    # --- the masked server kernel (`single-copy-register.rs:18-37`) ------
    def _server_step(self, sid, w, src, msg):
        import jax.numpy as jnp

        val = w[0]
        mtype = msg[0] >> 24
        m_rid = (msg[0] >> 12) & 0xFFF
        is_put = mtype == T_PUT
        is_get = mtype == T_GET

        new_val = jnp.where(is_put, msg[0] & 0xF, val)
        putok = jnp.stack([(jnp.uint32(T_PUTOK) << 24) | (m_rid << 12),
                           jnp.uint32(0)])
        getok = jnp.stack([(jnp.uint32(T_GETOK) << 24) | (m_rid << 12)
                           | val, jnp.uint32(0)])
        zmsg = jnp.zeros((2,), jnp.uint32)
        sends = [[jnp.uint32(0), zmsg, jnp.bool_(False)]
                 for _ in range(self.max_sends)]
        reply = is_put | is_get
        sends[0][0] = jnp.where(reply, src.astype(jnp.uint32),
                                sends[0][0])
        sends[0][1] = jnp.where(is_put, putok,
                                jnp.where(is_get, getok, zmsg))
        sends[0][2] = reply
        changed = is_put & (new_val != val)
        return new_val[None].astype(jnp.uint32), changed, sends


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    client_count = int(args[1]) if len(args) > 1 else 2
    server_count = int(args[2]) if len(args) > 2 else 1
    if cmd == "check-tpu":
        print(f"Model checking a packed single-copy register with "
              f"{client_count} clients, {server_count} servers on the "
              "TPU engine.")
        (PackedSingleCopy(client_count, server_count).checker()
         .spawn_tpu().report(sys.stdout))
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.single_copy_packed "
              "check-tpu [CLIENT_COUNT] [SERVER_COUNT]")


if __name__ == "__main__":
    main()
