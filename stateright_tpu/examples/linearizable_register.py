"""ABD quorum-replicated linearizable register.

Port of `/root/reference/examples/linearizable-register.rs`: the
Attiya/Bar-Noy/Dolev algorithm ("Sharing Memory Robustly in Message-Passing
Systems") — a two-phase (query-quorum then record-quorum) read/write
register that stays linearizable as long as a majority of servers is
reachable. Oracle: 2 clients + 2 servers = 544 unique states
(`linearizable-register.rs:258`, `:281`), pinned in tests. The ``check``
CLI accepts the ``ordered`` network argument (a BASELINE.md bench config).

Run: ``python -m stateright_tpu.examples.linearizable_register check [N] [NETWORK]``
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Any, FrozenSet, Optional, Tuple

from ..actor import ActorModel, Id, Network, Out, majority, model_peers
from ..actor.core import Actor
from ..actor.register import (Get, GetOk, Internal, Put, PutOk,
                              RegisterClient, RegisterServer,
                              record_invocations, record_returns)
from ..core import Expectation
from ..semantics import LinearizabilityTester, Register

# Seq = (logical clock, server id); higher wins, ids break ties.
Seq = Tuple[int, int]


# --- protocol messages (`linearizable-register.rs:29-36`) -------------------

@dataclass(frozen=True)
class Query:
    request_id: int


@dataclass(frozen=True)
class AckQuery:
    request_id: int
    seq: Seq
    value: Any


@dataclass(frozen=True)
class Record:
    request_id: int
    seq: Seq
    value: Any


@dataclass(frozen=True)
class AckRecord:
    request_id: int


# --- server state (`linearizable-register.rs:38-50`) ------------------------

@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: int
    write: Optional[Any]  # None = this is a read
    responses: FrozenSet[Tuple[int, Tuple[Seq, Any]]]


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: int
    read: Optional[Any]  # None = this is a write
    acks: FrozenSet[int]


@dataclass(frozen=True)
class AbdState:
    seq: Seq
    val: Any
    phase: Any  # None | Phase1 | Phase2


class AbdActor(Actor):
    """One ABD replica (`linearizable-register.rs:57-188`)."""

    def __init__(self, peers):
        self.peers = list(peers)

    def _quorum(self) -> int:
        return majority(len(self.peers) + 1)

    def on_start(self, id: Id, o: Out) -> AbdState:
        return AbdState(seq=(0, int(id)), val='\0', phase=None)

    def on_msg(self, id: Id, state: AbdState, src: Id, msg: Any,
               o: Out) -> Optional[AbdState]:
        if isinstance(msg, (Put, Get)) and state.phase is None:
            # Phase 1: query a quorum for the latest (seq, value)
            write = msg.value if isinstance(msg, Put) else None
            o.broadcast(self.peers, Internal(Query(msg.request_id)))
            responses = frozenset({(int(id), (state.seq, state.val))})
            return AbdState(
                seq=state.seq, val=state.val,
                phase=Phase1(request_id=msg.request_id,
                             requester_id=int(src), write=write,
                             responses=responses))

        if isinstance(msg, Internal):
            inner = msg.msg
            if isinstance(inner, Query):
                o.send(src, Internal(AckQuery(
                    inner.request_id, state.seq, state.val)))
                return None

            if isinstance(inner, AckQuery) \
                    and isinstance(state.phase, Phase1) \
                    and state.phase.request_id == inner.request_id:
                ph = state.phase
                responses = dict(ph.responses)
                responses[int(src)] = (inner.seq, inner.value)
                if len(responses) == self._quorum():
                    # Quorum reached: pick the newest (seq, value) — the
                    # seq's id component makes ties impossible — then move
                    # to phase 2 recording it (or its increment on writes)
                    seq, val = max(responses.values())
                    read = None
                    if ph.write is not None:
                        seq = (seq[0] + 1, int(id))
                        val = ph.write
                    else:
                        read = val
                    o.broadcast(self.peers, Internal(Record(
                        ph.request_id, seq, val)))
                    # self-deliver Record and AckRecord
                    new_seq, new_val = (seq, val) if seq > state.seq \
                        else (state.seq, state.val)
                    return AbdState(
                        seq=new_seq, val=new_val,
                        phase=Phase2(request_id=ph.request_id,
                                     requester_id=ph.requester_id,
                                     read=read,
                                     acks=frozenset({int(id)})))
                return AbdState(
                    seq=state.seq, val=state.val,
                    phase=Phase1(request_id=ph.request_id,
                                 requester_id=ph.requester_id,
                                 write=ph.write,
                                 responses=frozenset(responses.items())))

            if isinstance(inner, Record):
                o.send(src, Internal(AckRecord(inner.request_id)))
                if inner.seq > state.seq:
                    return AbdState(seq=inner.seq, val=inner.value,
                                    phase=state.phase)
                return None

            if isinstance(inner, AckRecord) \
                    and isinstance(state.phase, Phase2) \
                    and state.phase.request_id == inner.request_id \
                    and int(src) not in state.phase.acks:
                ph = state.phase
                acks = ph.acks | {int(src)}
                if len(acks) == self._quorum():
                    if ph.read is not None:
                        o.send(Id(ph.requester_id),
                               GetOk(ph.request_id, ph.read))
                    else:
                        o.send(Id(ph.requester_id), PutOk(ph.request_id))
                    return AbdState(seq=state.seq, val=state.val,
                                    phase=None)
                return AbdState(
                    seq=state.seq, val=state.val,
                    phase=Phase2(request_id=ph.request_id,
                                 requester_id=ph.requester_id,
                                 read=ph.read, acks=acks))
        return None


@dataclass
class AbdModelCfg:
    client_count: int
    server_count: int
    network: Network

    def into_model(self) -> ActorModel:
        model = ActorModel(
            cfg=self, init_history=LinearizabilityTester(Register('\0')))
        for i in range(self.server_count):
            model.actor(RegisterServer(AbdActor(
                model_peers(i, self.server_count))))
        for _ in range(self.client_count):
            model.actor(RegisterClient(
                put_count=1, server_count=self.server_count))

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != '\0':
                    return True
            return False

        return (model
                .init_network(self.network)
                .property(Expectation.ALWAYS, "linearizable",
                          lambda _, state:
                          state.history.serialized_history() is not None)
                .property(Expectation.SOMETIMES, "value chosen",
                          value_chosen)
                .record_msg_in(record_returns)
                .record_msg_out(record_invocations))


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    if cmd == "check":
        client_count = int(args[1]) if len(args) > 1 else 2
        network = Network.from_name(args[2]) if len(args) > 2 \
            else Network.new_unordered_nonduplicating()
        print(f"Model checking a linearizable register with {client_count} "
              "clients.")
        (AbdModelCfg(client_count=client_count, server_count=3,
                     network=network)
         .into_model().checker().spawn_dfs().report(sys.stdout))
    elif cmd == "check-tpu":
        client_count = int(args[1]) if len(args) > 1 else 2
        print(f"Model checking a linearizable register with {client_count} "
              "clients on the TPU engine.")
        from .abd_packed import PackedAbd
        (PackedAbd(client_count, server_count=3).checker()
         .spawn_tpu().report(sys.stdout))
    elif cmd == "explore":
        client_count = int(args[1]) if len(args) > 1 else 2
        address = args[2] if len(args) > 2 else "localhost:3000"
        print(f"Exploring state space for a linearizable register with "
              f"{client_count} clients on http://{address}.")
        (AbdModelCfg(client_count=client_count, server_count=3,
                     network=Network.new_unordered_nonduplicating())
         .into_model().checker().serve(address))
    elif cmd == "spawn":
        from .register_spawn import spawn_abd_cluster
        spawn_abd_cluster()
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.linearizable_register "
              "check [CLIENT_COUNT] [NETWORK]")
        print("  python -m stateright_tpu.examples.linearizable_register "
              "check-tpu [CLIENT_COUNT]")
        print("  python -m stateright_tpu.examples.linearizable_register "
              "explore [CLIENT_COUNT] [ADDRESS]")
        print("  python -m stateright_tpu.examples.linearizable_register "
              "spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
