"""Unreplicated single-copy register servers — no consensus.

Port of `/root/reference/examples/single-copy-register.rs`: each server
exposes a rewritable register; with one server the system is linearizable
(93 unique states), with two it is not — the checker finds a 20-state
linearizability *counterexample*, the reference's only workload proving the
checker catches a consistency bug. Both oracles are pinned in tests.

Run: ``python -m stateright_tpu.examples.single_copy_register check [N]``
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Any, Optional

from ..actor import ActorModel, Id, Network, Out
from ..actor.core import Actor
from ..actor.register import (Get, GetOk, Put, PutOk, RegisterClient,
                              RegisterServer, record_invocations,
                              record_returns)
from ..core import Expectation
from ..semantics import LinearizabilityTester, Register


class SingleCopyActor(Actor):
    """A server holding one mutable value (`single-copy-register.rs:18-37`)."""

    def on_start(self, id: Id, o: Out) -> str:
        return '\0'

    def on_msg(self, id: Id, state: str, src: Id, msg: Any,
               o: Out) -> Optional[str]:
        if isinstance(msg, Put):
            o.send(src, PutOk(msg.request_id))
            return msg.value
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state))
        return None


@dataclass
class SingleCopyModelCfg:
    client_count: int
    server_count: int
    network: Network

    def into_model(self) -> ActorModel:
        model = ActorModel(
            cfg=self, init_history=LinearizabilityTester(Register('\0')))
        for _ in range(self.server_count):
            model.actor(RegisterServer(SingleCopyActor()))
        for _ in range(self.client_count):
            model.actor(RegisterClient(
                put_count=1, server_count=self.server_count))

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != '\0':
                    return True
            return False

        return (model
                .init_network(self.network)
                .property(Expectation.ALWAYS, "linearizable",
                          lambda _, state:
                          state.history.serialized_history() is not None)
                .property(Expectation.SOMETIMES, "value chosen",
                          value_chosen)
                .record_msg_in(record_returns)
                .record_msg_out(record_invocations))


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    if cmd == "check":
        client_count = int(args[1]) if len(args) > 1 else 2
        network = Network.from_name(args[2]) if len(args) > 2 \
            else Network.new_unordered_nonduplicating()
        print(f"Model checking a single-copy register with {client_count} "
              "clients.")
        (SingleCopyModelCfg(client_count=client_count, server_count=1,
                            network=network)
         .into_model().checker().spawn_dfs().report(sys.stdout))
    elif cmd == "explore":
        client_count = int(args[1]) if len(args) > 1 else 2
        address = args[2] if len(args) > 2 else "localhost:3000"
        print(f"Exploring state space for a single-copy register with "
              f"{client_count} clients on http://{address}.")
        (SingleCopyModelCfg(client_count=client_count, server_count=1,
                            network=Network.new_unordered_nonduplicating())
         .into_model().checker().serve(address))
    elif cmd == "spawn":
        from .register_spawn import spawn_single_copy
        spawn_single_copy()
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.single_copy_register "
              "check [CLIENT_COUNT] [NETWORK]")
        print("  python -m stateright_tpu.examples.single_copy_register "
              "explore [CLIENT_COUNT] [ADDRESS]")
        print("  python -m stateright_tpu.examples.single_copy_register "
              "spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
