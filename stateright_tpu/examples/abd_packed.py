"""Packed ABD linearizable register: quorum replication on the TPU engine.

The same protocol as :mod:`stateright_tpu.examples.linearizable_register`
(a behavioral port of `/root/reference/examples/linearizable-register.rs`),
expressed through :class:`~stateright_tpu.actor.packed_register.PackedRegisterModel`
so ``spawn_tpu`` checks it on device — the second consistency-tested actor
family on the device engine after paxos. Host BFS on this model agrees
state-for-state with the plain model (544 for 2 clients + 2 servers,
`linearizable-register.rs:258`).

Packed layout (integer comparison of a packed seq equals the host's tuple
comparison, since the server id is the low component and ids are unique):

* seq ``(clock, sid)`` = ``clock<<4 | sid`` (12 bits);
* server state = 2+S words:
  - w0: ``seq | val<<12 | phase_tag<<16 | request_id<<18 | requester<<26``
    (tag: 0 = idle, 1 = phase 1 query, 2 = phase 2 record);
  - w1: ``write_present | write_val<<1 | read_present<<5 | read_val<<6 |
    acks_mask<<10`` (phase payload);
  - resp[k]: ``present<<16 | seq<<4 | val`` (phase-1 responses, by server);
* internal message = 2 words:
  ``[type<<24 | request_id<<12 | seq, value]``.
"""

from __future__ import annotations

import sys
from typing import Any, List

from ..actor import Id
from ..actor.packed_register import (PackedRegisterModel, T_INTERNAL0,
                                     val_char as _val_char,
                                     val_code as _val_code)
from .linearizable_register import (AbdActor, AbdState, AckQuery,
                                    AckRecord, Phase1, Phase2, Query,
                                    Record)

T_QUERY, T_ACKQUERY, T_RECORD, T_ACKRECORD = range(
    T_INTERNAL0, T_INTERNAL0 + 4)


def _seq_word(seq) -> int:
    clock, sid = seq
    assert clock <= 0xFF and sid <= 0xF
    return (clock << 4) | sid


def _seq_tuple(word: int):
    return (word >> 4, word & 0xF)


class PackedAbd(PackedRegisterModel):
    """ABD with S replicas + C put-once register clients, packed."""

    def __init__(self, client_count: int, server_count: int = 2,
                 net_capacity: int = 16, ordered: bool = False,
                 channel_depth: int = 4):
        self._init_register(
            client_count, server_count, ordered=ordered,
            channel_depth=channel_depth,
            server_actor=lambda i: AbdActor(
                [Id(j) for j in range(server_count) if j != i]),
            server_width=2 + server_count,
            net_capacity=net_capacity,
            max_sends=max(server_count - 1, 1))  # broadcasts to peers

    def cache_key(self):
        return ("abd", self.client_count, self.server_count,
                self.net_capacity, self._net_ordered, self.channel_depth)

    # ------------------------------------------------------------------
    # server state packing
    # ------------------------------------------------------------------
    def encode_server(self, p: AbdState) -> List[int]:
        s = self.server_count
        w0 = _seq_word(p.seq) | (_val_code(p.val) << 12)
        w1 = 0
        resp = [0] * s
        if isinstance(p.phase, Phase1):
            w0 |= (1 << 16) | (p.phase.request_id << 18) \
                | (p.phase.requester_id << 26)
            if p.phase.write is not None:
                w1 |= 1 | (_val_code(p.phase.write) << 1)
            for sid, (seq, val) in p.phase.responses:
                resp[sid] = (1 << 16) | (_seq_word(seq) << 4) \
                    | _val_code(val)
        elif isinstance(p.phase, Phase2):
            w0 |= (2 << 16) | (p.phase.request_id << 18) \
                | (p.phase.requester_id << 26)
            if p.phase.read is not None:
                w1 |= (1 << 5) | (_val_code(p.phase.read) << 6)
            for a in p.phase.acks:
                w1 |= 1 << (10 + a)
        return [w0, w1] + resp

    def decode_server(self, words: List[int]) -> AbdState:
        s = self.server_count
        w0, w1 = words[0], words[1]
        seq = _seq_tuple(w0 & 0xFFF)
        val = _val_char((w0 >> 12) & 0xF)
        tag = (w0 >> 16) & 3
        rid = (w0 >> 18) & 0xFF
        requester = (w0 >> 26) & 0xF
        phase = None
        if tag == 1:
            write = _val_char((w1 >> 1) & 0xF) if w1 & 1 else None
            responses = frozenset(
                (sid, (_seq_tuple((rw >> 4) & 0xFFF),
                       _val_char(rw & 0xF)))
                for sid, rw in enumerate(words[2:2 + s])
                if (rw >> 16) & 1)
            phase = Phase1(request_id=rid, requester_id=requester,
                           write=write, responses=responses)
        elif tag == 2:
            read = _val_char((w1 >> 6) & 0xF) if (w1 >> 5) & 1 else None
            acks = frozenset(a for a in range(s) if (w1 >> (10 + a)) & 1)
            phase = Phase2(request_id=rid, requester_id=requester,
                           read=read, acks=acks)
        return AbdState(seq=seq, val=val, phase=phase)

    # ------------------------------------------------------------------
    # internal message packing
    # ------------------------------------------------------------------
    def encode_internal(self, inner: Any) -> List[int]:
        if isinstance(inner, Query):
            return [(T_QUERY << 24) | (inner.request_id << 12), 0]
        if isinstance(inner, AckQuery):
            return [(T_ACKQUERY << 24) | (inner.request_id << 12)
                    | _seq_word(inner.seq), _val_code(inner.value)]
        if isinstance(inner, Record):
            return [(T_RECORD << 24) | (inner.request_id << 12)
                    | _seq_word(inner.seq), _val_code(inner.value)]
        assert isinstance(inner, AckRecord)
        return [(T_ACKRECORD << 24) | (inner.request_id << 12), 0]

    def decode_internal(self, words: List[int]) -> Any:
        w0, w1 = words
        mtype = w0 >> 24
        rid = (w0 >> 12) & 0xFFF
        seq = _seq_tuple(w0 & 0xFFF)
        if mtype == T_QUERY:
            return Query(rid)
        if mtype == T_ACKQUERY:
            return AckQuery(rid, seq, _val_char(w1 & 0xF))
        if mtype == T_RECORD:
            return Record(rid, seq, _val_char(w1 & 0xF))
        assert mtype == T_ACKRECORD
        return AckRecord(rid)

    # ------------------------------------------------------------------
    # the masked server kernel (`linearizable-register.rs:57-188`)
    # ------------------------------------------------------------------
    def _server_step(self, sid, w, src, msg):
        import jax.numpy as jnp

        from ..actor.packed_register import (T_GET, T_GETOK, T_PUT,
                                             T_PUTOK)
        s = self.server_count
        quorum = s // 2 + 1
        sid = sid.astype(jnp.uint32)
        srv_src = jnp.minimum(src, s - 1)
        src_sel = jnp.arange(s, dtype=jnp.uint32) == srv_src

        w0, w1 = w[0], w[1]
        resp = w[2:2 + s]
        seq = w0 & 0xFFF
        val = (w0 >> 12) & 0xF
        tag = (w0 >> 16) & 3
        rid = (w0 >> 18) & 0xFF
        requester = (w0 >> 26) & 0xF
        wr_p = (w1 & 1).astype(bool)
        wr_v = (w1 >> 1) & 0xF
        rd_p = ((w1 >> 5) & 1).astype(bool)
        rd_v = (w1 >> 6) & 0xF
        acks = (w1 >> 10) & 0xF

        mtype = msg[0] >> 24
        m_rid = (msg[0] >> 12) & 0xFFF
        m_seq = msg[0] & 0xFFF
        m_val = msg[1] & 0xF

        zmsg = jnp.zeros((2,), jnp.uint32)
        sends = [[jnp.uint32(0), zmsg, jnp.bool_(False)]
                 for _ in range(self.max_sends)]

        def set_send(k, cond, dst, m):
            sends[k][0] = jnp.where(cond, dst.astype(jnp.uint32),
                                    sends[k][0])
            sends[k][1] = jnp.where(cond, m, sends[k][1])
            sends[k][2] = sends[k][2] | cond

        def broadcast(cond, m):
            for k in range(s - 1):
                set_send(k, cond, (sid + 1 + k) % s, m)

        nw0, nw1, nresp = w0, w1, resp

        # --- Put/Get while idle: phase 1 query (`:96-115` in the py port)
        start = ((mtype == T_PUT) | (mtype == T_GET)) & (tag == 0)
        is_put = mtype == T_PUT
        put_val = msg[0] & 0xF  # register msgs carry the value in word 0
        query_msg = jnp.stack([(jnp.uint32(T_QUERY) << 24)
                               | (m_rid << 12), jnp.uint32(0)])
        broadcast(start, query_msg)
        start_w0 = seq | (val << 12) | (jnp.uint32(1) << 16) \
            | (m_rid << 18) | (src.astype(jnp.uint32) << 26)
        start_w1 = jnp.where(is_put, jnp.uint32(1) | (put_val << 1),
                             jnp.uint32(0))
        own_sel = jnp.arange(s, dtype=jnp.uint32) == sid
        start_resp = jnp.where(
            own_sel, (jnp.uint32(1) << 16) | (seq << 4) | val,
            jnp.uint32(0))
        nw0 = jnp.where(start, start_w0, nw0)
        nw1 = jnp.where(start, start_w1, nw1)
        nresp = jnp.where(start, start_resp, nresp)

        # --- Query: answer with our (seq, val) ---------------------------
        is_query = mtype == T_QUERY
        ackq_msg = jnp.stack([(jnp.uint32(T_ACKQUERY) << 24)
                              | (m_rid << 12) | seq, val])
        set_send(0, is_query, src, ackq_msg)

        # --- AckQuery in phase 1: collect, act at quorum -----------------
        ackq = (mtype == T_ACKQUERY) & (tag == 1) & (m_rid == rid)
        entry = (jnp.uint32(1) << 16) | (m_seq << 4) | m_val
        resp2 = jnp.where(ackq & src_sel, entry, nresp)
        cnt = ((resp2 >> 16) & 1).sum()
        q_hit = ackq & (cnt == quorum)
        # newest (seq, value): integer max over packed (seq<<4 | val)
        keys = jnp.where(((resp2 >> 16) & 1).astype(bool),
                         resp2 & 0xFFFF, jnp.uint32(0))
        best = keys.max()
        b_seq, b_val = best >> 4, best & 0xF
        n_seq = jnp.where(wr_p, (((b_seq >> 4) + 1) << 4) | sid, b_seq)
        n_val = jnp.where(wr_p, wr_v, b_val)
        record_msg = jnp.stack([(jnp.uint32(T_RECORD) << 24)
                                | (rid << 12) | n_seq, n_val])
        broadcast(q_hit, record_msg)
        # move to phase 2 (self-ack); adopt the recorded value if newer
        newer = n_seq > seq
        ph2_w0 = jnp.where(newer, n_seq | (n_val << 12),
                           seq | (val << 12)) \
            | (jnp.uint32(2) << 16) | (rid << 18) | (requester << 26)
        ph2_w1 = jnp.where(wr_p, jnp.uint32(0),
                           (jnp.uint32(1) << 5) | (b_val << 6)) \
            | ((jnp.uint32(1) << sid) << 10)
        nw0 = jnp.where(q_hit, ph2_w0, nw0)
        nw1 = jnp.where(q_hit, ph2_w1, nw1)
        nresp = jnp.where(q_hit, jnp.uint32(0),
                          jnp.where(ackq, resp2, nresp))

        # --- Record: ack; adopt if newer ---------------------------------
        is_rec = mtype == T_RECORD
        ackr_msg = jnp.stack([(jnp.uint32(T_ACKRECORD) << 24)
                              | (m_rid << 12), jnp.uint32(0)])
        set_send(0, is_rec, src, ackr_msg)
        adopt = is_rec & (m_seq > (nw0 & 0xFFF))
        nw0 = jnp.where(adopt,
                        (nw0 & ~jnp.uint32(0xFFFF)) | m_seq | (m_val << 12),
                        nw0)

        # --- AckRecord in phase 2: count, respond at quorum --------------
        already = ((acks >> srv_src) & 1).astype(bool)
        ackr = (mtype == T_ACKRECORD) & (tag == 2) & (m_rid == rid) \
            & ~already
        acks2 = acks | (jnp.uint32(1) << srv_src)
        cnt2 = jnp.uint32(0)
        for j in range(s):
            cnt2 = cnt2 + ((acks2 >> j) & 1)
        r_hit = ackr & (cnt2 == quorum)
        done_msg = jnp.where(
            rd_p,
            jnp.stack([(jnp.uint32(T_GETOK) << 24) | (rid << 12) | rd_v,
                       jnp.uint32(0)]),
            jnp.stack([(jnp.uint32(T_PUTOK) << 24) | (rid << 12),
                       jnp.uint32(0)]))
        set_send(0, r_hit, requester, done_msg)
        nw1 = jnp.where(ackr & ~r_hit,
                        (nw1 & ~jnp.uint32(0xF << 10)) | (acks2 << 10),
                        nw1)
        # back to idle: clear phase bits entirely
        idle_w0 = (nw0 & 0xFFFF)
        nw0 = jnp.where(r_hit, idle_w0, nw0)
        nw1 = jnp.where(r_hit, jnp.uint32(0), nw1)

        changed = start | ackq | adopt | ackr
        new_w = jnp.concatenate(
            [jnp.stack([nw0, nw1]), nresp]).astype(jnp.uint32)
        return new_w, changed, sends


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else None
    client_count = int(args[1]) if len(args) > 1 else 2
    ordered = len(args) > 2 and args[2] == "ordered"
    kw = dict(ordered=True, channel_depth=8) if ordered else {}
    net = "ordered" if ordered else "unordered"
    if cmd == "check-tpu":
        print(f"Model checking packed ABD with {client_count} clients "
              f"({net} network) on the TPU engine.")
        PackedAbd(client_count, **kw).checker().spawn_tpu() \
            .report(sys.stdout)
    elif cmd == "check":
        print(f"Model checking packed ABD with {client_count} clients "
              f"({net} network) on the host engine.")
        PackedAbd(client_count, **kw).checker().spawn_bfs() \
            .report(sys.stdout)
    else:
        print("USAGE:")
        print("  python -m stateright_tpu.examples.abd_packed "
              "check[-tpu] [CLIENT_COUNT] [ordered]")


if __name__ == "__main__":
    main()
