"""Run real register servers over localhost UDP (the ``spawn``
subcommands of `single-copy-register` and `linearizable-register`).

Ports of the reference's spawn branches
(`/root/reference/examples/single-copy-register.rs:168-186`,
`linearizable-register.rs:328-349`): the *same* actor objects the checker
verified, executed by the UDP runtime with netcat-friendly JSON:

    $ nc -u localhost 3000
    {"Put": [1, "X"]}
    {"Get": [2]}
"""

from __future__ import annotations

from typing import Any

from ..actor import Id, peer_ids
from ..actor.register import (Get, Put, register_msg_from_json,
                              register_msg_to_json)
from ..actor.runtime import SpawnHandle, spawn
from .linearizable_register import (AbdActor, AckQuery, AckRecord, Query,
                                    Record)
from .single_copy_register import SingleCopyActor


def _encode_internal(inner: Any) -> dict:
    if isinstance(inner, Query):
        return {"Query": [inner.request_id]}
    if isinstance(inner, AckQuery):
        return {"AckQuery": [inner.request_id, list(inner.seq),
                             inner.value]}
    if isinstance(inner, Record):
        return {"Record": [inner.request_id, list(inner.seq), inner.value]}
    assert isinstance(inner, AckRecord), inner
    return {"AckRecord": [inner.request_id]}


def _decode_internal(tag: str, value) -> Any:
    if tag == "Query":
        return Query(value[0])
    if tag == "AckQuery":
        return AckQuery(value[0], tuple(value[1]), value[2])
    if tag == "Record":
        return Record(value[0], tuple(value[1]), value[2])
    assert tag == "AckRecord", tag
    return AckRecord(value[0])


def msg_to_json(msg: Any) -> bytes:
    return register_msg_to_json(msg, _encode_internal)


def msg_from_json(data: bytes) -> Any:
    return register_msg_from_json(data, _decode_internal)


def _banner(kind: str, port: int) -> None:
    print(f"  A server that implements a {kind}.")
    print("  You can interact with the server using netcat. Example:")
    print(f"$ nc -u localhost {port}")
    print(msg_to_json(Put(1, 'X')).decode())
    print(msg_to_json(Get(2)).decode())
    print()


def spawn_single_copy(port: int = 3000,
                      background: bool = False) -> SpawnHandle:
    """One unreplicated register server
    (`single-copy-register.rs:168-186`)."""
    _banner("single-copy register", port)
    localhost = (127, 0, 0, 1)
    actors = [(Id.from_socket_addr(localhost, port), SingleCopyActor())]
    return spawn(msg_to_json, msg_from_json, actors, background=background)


def spawn_abd_cluster(port: int = 3000,
                      background: bool = False) -> SpawnHandle:
    """Three ABD replicas (`linearizable-register.rs:328-349`). As in the
    reference, omits the ordered reliable link to keep the protocol
    netcat-friendly."""
    _banner("linearizable register", port)
    localhost = (127, 0, 0, 1)
    ids = [Id.from_socket_addr(localhost, port + i) for i in range(3)]
    actors = [(i, AbdActor(peer_ids(i, ids))) for i in ids]
    return spawn(msg_to_json, msg_from_json, actors, background=background)
