"""Run real register servers over localhost UDP (the ``spawn``
subcommands of `single-copy-register` and `linearizable-register`).

Ports of the reference's spawn branches
(`/root/reference/examples/single-copy-register.rs:168-186`,
`linearizable-register.rs:328-349`): the *same* actor objects the checker
verified, executed by the UDP runtime with netcat-friendly JSON:

    $ nc -u localhost 3000
    {"Put": [1, "X"]}
    {"Get": [2]}
"""

from __future__ import annotations

import json
from typing import Any

from ..actor import Id, peer_ids
from ..actor.register import Get, GetOk, Internal, Put, PutOk
from ..actor.runtime import SpawnHandle, spawn
from .linearizable_register import (AbdActor, AckQuery, AckRecord, Query,
                                    Record)
from .single_copy_register import SingleCopyActor


def msg_to_json(msg: Any) -> bytes:
    """Externally-tagged JSON (the shape serde_json gives the reference's
    enums)."""
    if isinstance(msg, Put):
        obj = {"Put": [msg.request_id, msg.value]}
    elif isinstance(msg, Get):
        obj = {"Get": [msg.request_id]}
    elif isinstance(msg, PutOk):
        obj = {"PutOk": [msg.request_id]}
    elif isinstance(msg, GetOk):
        obj = {"GetOk": [msg.request_id, msg.value]}
    elif isinstance(msg, Internal):
        inner = msg.msg
        if isinstance(inner, Query):
            iobj = {"Query": [inner.request_id]}
        elif isinstance(inner, AckQuery):
            iobj = {"AckQuery": [inner.request_id, list(inner.seq),
                                 inner.value]}
        elif isinstance(inner, Record):
            iobj = {"Record": [inner.request_id, list(inner.seq),
                               inner.value]}
        elif isinstance(inner, AckRecord):
            iobj = {"AckRecord": [inner.request_id]}
        else:
            raise TypeError(f"unknown internal message {inner!r}")
        obj = {"Internal": iobj}
    else:
        raise TypeError(f"unknown message {msg!r}")
    return json.dumps(obj).encode()


def msg_from_json(data: bytes) -> Any:
    obj = json.loads(data)
    (tag, value), = obj.items()
    if tag == "Put":
        return Put(value[0], value[1])
    if tag == "Get":
        return Get(value[0])
    if tag == "PutOk":
        return PutOk(value[0])
    if tag == "GetOk":
        return GetOk(value[0], value[1])
    if tag == "Internal":
        (itag, ivalue), = value.items()
        if itag == "Query":
            return Internal(Query(ivalue[0]))
        if itag == "AckQuery":
            return Internal(AckQuery(ivalue[0], tuple(ivalue[1]),
                                     ivalue[2]))
        if itag == "Record":
            return Internal(Record(ivalue[0], tuple(ivalue[1]),
                                   ivalue[2]))
        if itag == "AckRecord":
            return Internal(AckRecord(ivalue[0]))
    raise ValueError(f"unknown message tag in {obj!r}")


def _banner(kind: str, port: int) -> None:
    print(f"  A server that implements a {kind}.")
    print("  You can interact with the server using netcat. Example:")
    print(f"$ nc -u localhost {port}")
    print(msg_to_json(Put(1, 'X')).decode())
    print(msg_to_json(Get(2)).decode())
    print()


def spawn_single_copy(port: int = 3000,
                      background: bool = False) -> SpawnHandle:
    """One unreplicated register server
    (`single-copy-register.rs:168-186`)."""
    _banner("single-copy register", port)
    localhost = (127, 0, 0, 1)
    actors = [(Id.from_socket_addr(localhost, port), SingleCopyActor())]
    return spawn(msg_to_json, msg_from_json, actors, background=background)


def spawn_abd_cluster(port: int = 3000,
                      background: bool = False) -> SpawnHandle:
    """Three ABD replicas (`linearizable-register.rs:328-349`). As in the
    reference, omits the ordered reliable link to keep the protocol
    netcat-friendly."""
    _banner("linearizable register", port)
    localhost = (127, 0, 0, 1)
    ids = [Id.from_socket_addr(localhost, port + i) for i in range(3)]
    actors = [(i, AbdActor(peer_ids(i, ids))) for i in ids]
    return spawn(msg_to_json, msg_from_json, actors, background=background)
