/* Explorer SPA: a state is addressed by the fingerprint path from an init
 * state (e.g. "#/123/456"); every view is fetched lazily from the
 * server's replay endpoints. */
"use strict";

function currentPath() {
  const h = location.hash.replace(/^#\/?/, "");
  return h ? h.split("/").filter(Boolean) : [];
}

function link(fps) { return "#/" + fps.join("/"); }

function esc(s) {
  const d = document.createElement("span");
  d.textContent = s;
  return d.innerHTML;
}

/* Verdict wording per (expectation, discovered, done, bounded) — a
 * discovery is the GOAL for sometimes-properties and a VIOLATION for
 * always/eventually ones. A bounded (target_state_count) run that
 * finishes without a discovery has not established a "holds" claim,
 * only absence so far. */
function verdict(expectation, discovered, done, bounded, sound) {
  if (discovered) {
    return expectation === "sometimes"
      ? "✅ example found" : "⚠️ counterexample found";
  }
  if (!done) return "🔎 searching";
  if (bounded) {
    return expectation === "sometimes"
      ? "⚠️ example not found (bounded run)"
      : "✅ no violation found (bounded run)";
  }
  switch (expectation) {
    case "always": return "✅ safety holds";
    case "eventually":
      /* without sound_eventually() exhaustion can miss cycle
       * counterexamples (the reference's documented caveat) */
      return sound ? "✅ liveness holds" : "✅ no counterexample found";
    default: return "⚠️ example not found";
  }
}

async function renderStatus() {
  try {
    const r = await fetch("/.status");
    const s = await r.json();
    let html = `${s.model} &mdash; ${s.done ? "done" : "checking"}, ` +
      `states=${s.state_count}, unique=${s.unique_state_count}`;
    if (s.chunks) html += `, device chunks=${s.chunks}`;
    for (const [expectation, name, discovery] of s.properties) {
      const cls = discovery ? "discovered" : "";
      const label = `${expectation} ${esc(name)}: ` +
        verdict(expectation, !!discovery, s.done, !!s.bounded,
                !!s.sound);
      html += `<span class="prop ${cls}">` +
        (discovery ? `<a href="#/${discovery}">${label} &#9733;</a>`
                   : label) + `</span>`;
    }
    document.getElementById("status").innerHTML = html;
  } catch (e) {
    document.getElementById("status").textContent = "status unavailable";
  }
}

function renderCrumbs(fps) {
  let html = `<a href="#/">init</a>`;
  for (let i = 0; i < fps.length; i++) {
    html += `&rsaquo; <a href="${link(fps.slice(0, i + 1))}">` +
      `${fps[i].slice(0, 8)}&hellip;</a>`;
  }
  document.getElementById("crumbs").innerHTML = html;
}

async function renderStates() {
  const fps = currentPath();
  renderCrumbs(fps);
  const main = document.getElementById("states");
  const r = await fetch("/.states/" + fps.join("/"));
  if (!r.ok) {
    main.innerHTML = `<p>${esc(await r.text())}</p>`;
    return;
  }
  const views = await r.json();
  main.innerHTML = "";
  for (const v of views) {
    const div = document.createElement("div");
    const ignored = !("state" in v);
    div.className = "state" + (ignored ? " ignored" : " clickable");
    let html = "";
    if (v.action) html += `<div class="action">${esc(v.action)}</div>`;
    if (v.outcome) html += `<div class="outcome">${esc(v.outcome)}</div>`;
    if (ignored) {
      html += `<div class="outcome">action ignored (no-op)</div>`;
    } else {
      html += `<pre>${esc(v.state)}</pre>` +
        `<div class="fp">fingerprint ${esc(v.fingerprint)}</div>`;
      if (v.svg) html += v.svg;
    }
    div.innerHTML = html;
    if (!ignored) {
      div.addEventListener("click", () => {
        location.hash = link(fps.concat([v.fingerprint]));
      });
    }
    main.appendChild(div);
  }
}

window.addEventListener("hashchange", renderStates);
renderStatus();
setInterval(renderStatus, 5000);
renderStates();
