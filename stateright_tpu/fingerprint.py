"""Stable 64-bit state fingerprints.

The reference derives fingerprints by feeding Rust's ``Hash`` into a
fixed-key aHash (``/root/reference/src/lib.rs:303-344``). Build-stable
fingerprints are load-bearing: path reconstruction replays the model and
matches fingerprints (``src/checker/path.rs:20-86``) and the Explorer
addresses states by fingerprint paths.

We need the additional property that the *same* hash is computable both on
host (Python) and on device (JAX/TPU, see ``stateright_tpu.ops.hash_kernel``)
over a canonical ``uint32``-word encoding of a state. aHash is not
TPU-friendly (it leans on AES rounds / 128-bit folded multiplies), and a
murmur-style sequential accumulator is not either: its mixing chain is one
dependent op per word, so hashing a W-word state costs O(W) *vector-op
latency* on the VPU no matter how many states are batched. We instead use a
**column-parallel** construction: every word is whitened independently
(position-keyed), the whitened words are XOR-reduced, and only the final
avalanche is sequential — O(1) dependent ops per state regardless of width,
which benchmarked ~9 ms/iteration faster inside the engine's device loop.

Layout contract (shared with the C core and the device kernel), all
arithmetic mod 2^32:

  P_i  = fmix32((i + 1) * GOLDEN)          # per-position whitening key
  x_i  = w_i ^ P_i
  h1   = XOR_i fmix32(x_i * C1_1)          # two independent 32-bit lanes
  h2   = XOR_i fmix32(x_i * C1_2)
  fp64 = (fmix32(h1 ^ SEED1 ^ n) << 32) | fmix32(h2 ^ SEED2 ^ n * C1_1)

where n = len(words). Each lane XOR-combines a bijective whitening of each
(word, position) pair, so single-word differences always change both lanes
and multi-word collisions require a simultaneous 64-bit match across two
independently-mixed lanes. A zero digest is mapped to 1 (fingerprints are
non-zero, mirroring ``NonZeroU64`` in the reference).
"""

from __future__ import annotations

import ctypes as _ctypes
import dataclasses
import enum
import struct
import threading as _threading
from array import array as _array
from typing import Any, Iterable, List, Optional

M32 = 0xFFFFFFFF

_NATIVE = None
_NATIVE_TRIED = False


def _native_lib():
    """The C fingerprint core, or None (pure-Python fallback)."""
    global _NATIVE, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE_TRIED = True
        from . import _native
        _NATIVE = _native.load()
    return _NATIVE

# Lane multipliers: murmur3_x86_32's first constant and murmur3_x86_128's
# first constant. GOLDEN = 2^32 / golden ratio keys the per-position
# whitening. The seeds separate the two lanes' finalizers.
C1_1 = 0xCC9E2D51
C1_2 = 0x239B961B
GOLDEN = 0x9E3779B9
SEED1 = 0x9747B28C
SEED2 = 0x85EBCA6B


def _fmix32(h: int) -> int:
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & M32
    h ^= h >> 16
    return h


# Version of the fingerprint layout. Bump whenever the algorithm changes:
# checkpoints embed it so a resume against differently-hashed history is
# rejected instead of silently corrupting the search.
FP_VERSION = 2

_COL_KEYS: List[int] = []
_COL_KEYS_LOCK = _threading.Lock()


def col_keys(n: int) -> List[int]:
    """The first ``n`` per-position whitening keys ``P_i`` (host cache;
    the device kernel materializes the same values as a constant)."""
    if len(_COL_KEYS) < n:
        with _COL_KEYS_LOCK:
            # re-check under the lock; compute each key from its target
            # index so concurrent extenders can never shift positions
            for i in range(len(_COL_KEYS), n):
                _COL_KEYS.append(_fmix32((i + 1) * GOLDEN & M32))
    return _COL_KEYS[:n]


def fp64_words(words: Iterable[int]) -> int:
    """Hash a sequence of uint32 words into a non-zero 64-bit fingerprint.

    Dispatches to the native C core (`_native/fphash.c`) when available;
    the pure-Python body below is the reference implementation and
    fallback. The device implementation in ``ops/hash_kernel.py`` must
    match both bit-for-bit (differential-tested).
    """
    lib = _native_lib()
    if lib is not None:
        if not isinstance(words, (list, tuple)):
            # materialize: the masked retry below must see every word
            words = list(words)
        try:
            buf = _array("I", words)
        except (OverflowError, TypeError):
            buf = _array("I", [w & M32 for w in words])
        n = len(buf)
        if n == 0:
            return lib.fp64_words(None, 0)
        addr, _ = buf.buffer_info()
        return lib.fp64_words(
            _ctypes.cast(addr, _ctypes.POINTER(_ctypes.c_uint32)), n)
    return _fp64_words_py(words)


def fp64_node(fp: int, ebits_mask: int) -> int:
    """Dedup identity of a search NODE under sound-eventually checking:
    the state fingerprint combined with the pending eventually-bits.

    The reference deliberately leaves ebits out of the state identity and
    documents the resulting missed counterexamples
    (`/root/reference/src/checker/bfs.rs:239-244`);
    ``CheckerBuilder.sound_eventually()`` opts into including them. The
    word order ``[lo, hi, ebits]`` is mirrored bit-for-bit by
    ``ops.hash_kernel.fp64_node_device``."""
    return fp64_words([fp & M32, (fp >> 32) & M32, ebits_mask & M32])


def fp64_rows(rows) -> "list":
    """Fingerprint a batch of packed states on the host.

    ``rows`` is a uint32[N, W] numpy array (C-contiguous); returns a list of
    N non-zero 64-bit fingerprints, equal row-for-row to ``fp64_words``.
    This is the bulk path the host mirror uses when pulling packed states
    back from the device.
    """
    import numpy as np
    rows = np.ascontiguousarray(rows, dtype=np.uint32)
    count, width = rows.shape
    lib = _native_lib()
    if lib is None:
        return [_fp64_words_py(row.tolist()) for row in rows]
    out = np.empty((count,), dtype=np.uint64)
    if count:
        lib.fp64_rows(
            rows.ctypes.data_as(_ctypes.POINTER(_ctypes.c_uint32)),
            count, width,
            out.ctypes.data_as(_ctypes.POINTER(_ctypes.c_uint64)))
    return out.tolist()


def _fp64_words_py(words: Iterable[int]) -> int:
    """Pure-Python reference implementation of :func:`fp64_words`."""
    h1 = 0
    h2 = 0
    n = 0
    keys = _COL_KEYS
    for w in words:
        if n >= len(keys):
            col_keys(n + 1)  # extend the shared cache in place
        x = (w & M32) ^ keys[n]
        h1 ^= _fmix32((x * C1_1) & M32)
        h2 ^= _fmix32((x * C1_2) & M32)
        n += 1

    h1 = _fmix32(h1 ^ SEED1 ^ n)
    h2 = _fmix32(h2 ^ SEED2 ^ ((n * C1_1) & M32))
    fp = (h1 << 32) | h2
    return fp if fp != 0 else 1


# ---------------------------------------------------------------------------
# Canonical word encoding of Python state values.
#
# Mirrors the reference's reliance on Rust ``#[derive(Hash)]`` plus the
# order-insensitive containers in ``src/util.rs`` (``HashableHashSet`` hashes
# each element, sorts the 64-bit hashes, and feeds them to the outer hasher —
# ``util.rs:124-145``; ``HashableHashMap`` does the same per (k, v) entry —
# ``util.rs:321-343``).
# ---------------------------------------------------------------------------

_TAG_NONE = 0
_TAG_BOOL = 1
_TAG_INT = 2
_TAG_STR = 3
_TAG_BYTES = 4
_TAG_SEQ = 5
_TAG_SET = 6
_TAG_MAP = 7
_TAG_OBJ = 8
_TAG_ENUM = 9
_TAG_FLOAT = 10


def _emit_packed_bytes(data: bytes, out: List[int]) -> None:
    out.append(len(data))
    for i in range(0, len(data), 4):
        out.append(int.from_bytes(data[i:i + 4], "little"))


_CLASS_FP_CACHE: dict = {}
_FIELD_NAMES_CACHE: dict = {}


def _field_names(cls: type) -> tuple:
    names = _FIELD_NAMES_CACHE.get(cls)
    if names is None:
        names = tuple(f.name for f in dataclasses.fields(cls))
        _FIELD_NAMES_CACHE[cls] = names
    return names


def _class_fp(cls: type) -> int:
    fp = _CLASS_FP_CACHE.get(cls)
    if fp is None:
        words: List[int] = []
        _emit_packed_bytes(cls.__qualname__.encode(), words)
        fp = fp64_words(words)
        _CLASS_FP_CACHE[cls] = fp
    return fp


def stable_words(value: Any, out: List[int]) -> None:
    """Append the canonical uint32-word encoding of ``value`` to ``out``."""
    if value is None:
        out.append(_TAG_NONE)
    elif value is True or value is False:
        out.append(_TAG_BOOL)
        out.append(1 if value else 0)
    elif isinstance(value, int) and not isinstance(value, enum.Enum):
        out.append(_TAG_INT)
        sign = 1 if value < 0 else 0
        mag = -value if sign else value
        mag_words: List[int] = []
        while mag:
            mag_words.append(mag & M32)
            mag >>= 32
        out.append(sign)
        out.append(len(mag_words))
        out.extend(mag_words)
    elif isinstance(value, str):
        out.append(_TAG_STR)
        _emit_packed_bytes(value.encode(), out)
    elif isinstance(value, (bytes, bytearray)):
        out.append(_TAG_BYTES)
        _emit_packed_bytes(bytes(value), out)
    elif isinstance(value, enum.Enum):
        out.append(_TAG_ENUM)
        cfp = _class_fp(type(value))
        out.append(cfp & M32)
        out.append((cfp >> 32) & M32)
        _emit_packed_bytes(value.name.encode(), out)
    elif isinstance(value, (tuple, list)):
        out.append(_TAG_SEQ)
        out.append(len(value))
        for item in value:
            stable_words(item, out)
    elif isinstance(value, (set, frozenset)):
        # Order-insensitive: sorted element fingerprints (util.rs:124-145).
        out.append(_TAG_SET)
        out.append(len(value))
        for fp in sorted(stable_fingerprint(item) for item in value):
            out.append(fp & M32)
            out.append((fp >> 32) & M32)
    elif isinstance(value, dict):
        # Order-insensitive: sorted entry fingerprints (util.rs:321-343).
        out.append(_TAG_MAP)
        out.append(len(value))
        for fp in sorted(stable_fingerprint((k, v)) for k, v in value.items()):
            out.append(fp & M32)
            out.append((fp >> 32) & M32)
    elif isinstance(value, float):
        out.append(_TAG_FLOAT)
        bits = struct.unpack("<Q", struct.pack("<d", value))[0]
        out.append(bits & M32)
        out.append((bits >> 32) & M32)
    elif hasattr(value, "__stable_words__"):
        value.__stable_words__(out)
    elif dataclasses.is_dataclass(value) and not isinstance(value, type):
        out.append(_TAG_OBJ)
        cfp = _class_fp(type(value))
        out.append(cfp & M32)
        out.append((cfp >> 32) & M32)
        for name in _field_names(type(value)):
            stable_words(getattr(value, name), out)
    else:
        raise TypeError(
            f"cannot stably fingerprint value of type {type(value)!r}; "
            f"implement __stable_words__(out) or use a supported type")


def stable_fingerprint(value: Any) -> int:
    """Non-zero 64-bit stable fingerprint of an arbitrary state value."""
    words: List[int] = []
    stable_words(value, words)
    return fp64_words(words)
