"""``CheckerBuilder.sound_eventually()``: node-keyed dedup that goes
beyond the reference.

The reference accepts missed ``eventually`` counterexamples when a state
is revisited with different pending bits — the documented FIXME at
`/root/reference/src/checker/bfs.rs:239-244`, pinned by its
``fixme_can_miss_counterexample_when_revisiting_a_state`` test
(`src/checker.rs:402-414`). Sound mode dedups on (state, pending-ebits)
nodes, so the DAG-rejoin miss disappears on every supporting engine, and
the DFS engine is lasso-COMPLETE: on-path rejoins report immediately,
and a post-exhaustion SCC sweep over the explored node graph reports
cycles entered via cross edges into already-explored branches (pinned
below; under symmetry reduction only the on-path check runs — a
cross-branch witness cannot be replayed through concrete orbit
members).
"""

import pytest

from stateright_tpu.core import Property
from stateright_tpu.models.fixtures import DGraph


def eventually_odd():
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def rejoin_graph():
    """DAG rejoin: 0->2->4 and 1->4->6; 4's even continuation to 6 is
    masked by the visit via odd 1 in default mode."""
    return (DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4])
            .with_path([1, 4, 6]))


def cycle_graph():
    """Lasso: 0->2->4->2, all even — an infinite run on which "odd"
    never holds."""
    return DGraph.with_property(eventually_odd()).with_path([0, 2, 4, 2])


class TestHostSound:
    def test_bfs_finds_rejoin_counterexample(self):
        # default mode misses it (the pinned reference behavior) ...
        assert (rejoin_graph().checker().spawn_bfs().join()
                .discovery("odd")) is None
        # ... sound mode finds it, and the witness replays
        c = rejoin_graph().checker().sound_eventually().spawn_bfs().join()
        path = c.assert_any_discovery("odd")
        states = path.into_states()
        assert states[-1] == 6
        assert not any(s % 2 == 1 for s in states)

    def test_dfs_finds_rejoin_counterexample(self):
        assert (rejoin_graph().checker().spawn_dfs().join()
                .discovery("odd")) is None
        c = rejoin_graph().checker().sound_eventually().spawn_dfs().join()
        states = c.assert_any_discovery("odd").into_states()
        assert states[-1] == 6
        assert not any(s % 2 == 1 for s in states)

    def test_bfs_still_misses_pure_cycle(self):
        # BFS has no path context: lassos remain undetected (documented)
        c = cycle_graph().checker().sound_eventually().spawn_bfs().join()
        assert c.discovery("odd") is None

    def test_dfs_reports_lasso(self):
        # default mode misses the cycle; sound DFS reports the lasso
        assert (cycle_graph().checker().spawn_dfs().join()
                .discovery("odd")) is None
        c = cycle_graph().checker().sound_eventually().spawn_dfs().join()
        path = c.assert_any_discovery("odd")
        states = path.into_states()
        # the trace ends by re-entering the cycle (state 2 repeats)
        assert states[-1] == 2 and states.count(2) == 2
        assert not any(s % 2 == 1 for s in states)

    def test_dfs_cross_edge_cycle_found(self):
        # a cycle entered via a cross edge into an already-explored
        # sibling branch (2->4->2 below, discovered from 0's two
        # children) dedups at push time so the on-path check never sees
        # it; the post-exhaustion SCC sweep (round 4) reports it — this
        # used to be the pinned limitation
        g = (DGraph.with_property(eventually_odd())
             .with_path([0, 2, 4, 2])
             .with_path([0, 4]))
        c = g.checker().sound_eventually().spawn_dfs().join()
        path = c.assert_any_discovery("odd")
        states = path.into_states()
        assert not any(s % 2 == 1 for s in states)
        # the witness ends with one full lap of the cycle
        assert states[-1] in (2, 4) and states.count(states[-1]) >= 2

    def test_dfs_disjoint_branch_cycle_found(self):
        # cycle spanning two sibling branches: 0->2, 0->4, 2->4, 4->2 —
        # NO single DFS path contains both cycle edges, so only the SCC
        # sweep can see it
        g = (DGraph.with_property(eventually_odd())
             .with_path([0, 2, 4])
             .with_path([0, 4, 2]))
        c = g.checker().sound_eventually().spawn_dfs().join()
        path = c.assert_any_discovery("odd")
        states = path.into_states()
        assert not any(s % 2 == 1 for s in states)

    def test_no_false_positives(self):
        # graphs whose eventually-property holds stay clean in sound mode
        g = (DGraph.with_property(eventually_odd())
             .with_path([1])
             .with_path([2, 3])
             .with_path([2, 6, 7])
             .with_path([4, 9, 10]))
        g.checker().sound_eventually().spawn_bfs().join() \
            .assert_properties()
        g.checker().sound_eventually().spawn_dfs().join() \
            .assert_properties()
        # a satisfied cycle is not a lasso: 0->1(odd)->2->0
        g = DGraph.with_property(eventually_odd()).with_path([0, 1, 2, 0])
        g.checker().sound_eventually().spawn_dfs().join() \
            .assert_properties()

    def test_node_space_counts(self):
        # 4 and 3 are each explored once per distinct pending set (via
        # odd init 1 with the bit cleared, via even init 0 with it
        # pending): 4 states, 6 nodes; no counterexample exists, so the
        # space is fully explored and the property holds
        g = (DGraph.with_property(eventually_odd())
             .with_path([1, 4, 3])
             .with_path([0, 4, 3]))
        c = g.checker().sound_eventually().spawn_bfs().join()
        c.assert_properties()
        assert len(c.generated_fingerprints()) == 4
        assert c.unique_state_count() == 6


class TestDeviceSound:
    @pytest.fixture(autouse=True)
    def _jax(self):
        pytest.importorskip("jax")

    def check_tpu(self, graph):
        return (graph.checker().sound_eventually()
                .tpu_options(capacity=1 << 10, fmax=16)
                .spawn_tpu().join())

    def test_device_finds_rejoin_counterexample(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2, 4])
             .with_path([1, 4, 6]))
        c = self.check_tpu(g)
        states = c.assert_any_discovery("odd").into_states()
        assert states[-1] == 6
        assert not any(s % 2 == 1 for s in states)

    def test_device_reports_pure_cycle(self):
        # round 5: the device engine logs cross edges (dedup hits with
        # pending bits) and runs the shared lasso sweep at exhaustion —
        # this used to be the pinned device limitation
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2, 4, 2]))
        c = self.check_tpu(g)
        states = c.assert_any_discovery("odd").into_states()
        # the witness ends with one full lap of the 2->4->2 cycle; the
        # entry point depends on visitation order
        assert states[-1] in (2, 4) and states.count(states[-1]) >= 2
        assert not any(s % 2 == 1 for s in states)

    def test_device_cross_edge_cycle_found(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2, 4, 2])
             .with_path([0, 4]))
        c = self.check_tpu(g)
        states = c.assert_any_discovery("odd").into_states()
        assert not any(s % 2 == 1 for s in states)
        assert states[-1] in (2, 4) and states.count(states[-1]) >= 2

    def test_device_disjoint_branch_cycle_found(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2, 4])
             .with_path([0, 4, 2]))
        c = self.check_tpu(g)
        states = c.assert_any_discovery("odd").into_states()
        assert not any(s % 2 == 1 for s in states)

    def test_device_satisfied_cycle_not_reported(self):
        # a cycle whose path already satisfied the property is NOT a
        # lasso: the node mask is 0 around it
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 1, 2, 0]))
        self.check_tpu(g).assert_properties()

    def test_device_no_false_positives_and_host_parity(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([1])
             .with_path([2, 3])
             .with_path([2, 6, 7])
             .with_path([4, 9, 10]))
        c = self.check_tpu(g)
        c.assert_properties()
        host = (g.checker().sound_eventually().spawn_bfs().join())
        assert c.generated_fingerprints() == host.generated_fingerprints()

    def test_level_mode_rejected(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 1]))
        with pytest.raises(NotImplementedError):
            (g.checker().sound_eventually()
             .tpu_options(capacity=1 << 10, mode="level")
             .spawn_tpu().join())


def _sym_sound_increment(n):
    """Increment threads with eventually-properties layered on: the
    value-complete representative (engine-independent symmetry counts)
    makes this the fixture for sound x symmetry on the device engines."""
    import jax.numpy as jnp

    from stateright_tpu.examples.increment import Increment

    class SymSoundIncrement(Increment):
        def properties(self):
            return super().properties() + [
                # holds: terminal <=> every thread finished
                Property.eventually(
                    "all fin",
                    lambda _, s: all(pc == 3 for _t, pc in s[1])),
                # falsifiable: lost updates leave i < n at termination
                Property.eventually(
                    "full count", lambda _, s: s[0] == self.n),
            ]

        def packed_properties(self, words):
            base = super().packed_properties(words)
            allfin = jnp.bool_(True)
            for tid in range(self.n):
                allfin = allfin & ((words[1 + tid] & 0xF) == 3)
            return jnp.concatenate(
                [base, jnp.stack([allfin, words[0] == self.n])])

        def cache_key(self):
            return ("sym_sound_increment", self.n)

    return SymSoundIncrement(n)


class TestSoundSymmetry:
    """sound_eventually x symmetry reduction on the device engines: node
    keys over CANONICAL fingerprints, replay through original states."""

    @pytest.fixture(autouse=True)
    def _jax(self):
        pytest.importorskip("jax")

    def _host(self, n):
        m = _sym_sound_increment(n)
        return (m.checker().symmetry_fn(m.representative)
                .sound_eventually().spawn_dfs().join())

    def test_device_matches_host_dfs(self):
        m = _sym_sound_increment(3)
        dev = (m.checker().symmetry_fn(m.representative)
               .sound_eventually()
               .tpu_options(capacity=1 << 12, fmax=32)
               .spawn_tpu().join())
        host = self._host(3)
        # node-space reachability is engine-independent for a
        # value-complete representative; the generated-fingerprint SETS
        # are not comparable (the recorded original orbit member per
        # canonical node depends on exploration order)
        assert dev.unique_state_count() == host.unique_state_count()
        assert set(dev.discoveries()) == set(host.discoveries())
        # witnesses replay through concrete original states
        path = dev.assert_any_discovery("full count")
        assert path.last_state()[0] < 3

    def test_clean_property_stays_clean(self):
        m = _sym_sound_increment(2)
        dev = (m.checker().symmetry_fn(m.representative)
               .sound_eventually()
               .tpu_options(capacity=1 << 12, fmax=32)
               .spawn_tpu().join())
        assert dev.discovery("all fin") is None


class TestShardedSound:
    """sound_eventually on the SPMD sharded engine: node-keyed dedup,
    ownership routing and logs over node keys."""

    @pytest.fixture(autouse=True)
    def _jax(self):
        pytest.importorskip("jax")

    def _mesh(self, n):
        import jax
        from jax.sharding import Mesh

        return Mesh(jax.devices("cpu")[:n], ("shards",))

    def check_sharded(self, graph, n_shards=2):
        return (graph.checker().sound_eventually()
                .tpu_options(capacity=1 << 12, fmax=16,
                             mesh=self._mesh(n_shards))
                .spawn_tpu().join())

    def test_sharded_finds_rejoin_counterexample(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2, 4])
             .with_path([1, 4, 6]))
        c = self.check_sharded(g)
        states = c.assert_any_discovery("odd").into_states()
        assert states[-1] == 6
        assert not any(s % 2 == 1 for s in states)

    def test_sharded_host_parity_4shards(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([1])
             .with_path([2, 3])
             .with_path([2, 6, 7])
             .with_path([4, 9, 10]))
        c = self.check_sharded(g, n_shards=4)
        c.assert_properties()
        host = g.checker().sound_eventually().spawn_bfs().join()
        assert c.generated_fingerprints() == host.generated_fingerprints()
        assert c.unique_state_count() == host.unique_state_count()


class TestShardedLasso:
    """Sharded twins of the device lasso tests (virtual CPU mesh)."""

    @pytest.fixture(autouse=True)
    def _jax(self):
        pytest.importorskip("jax")

    def check_sharded(self, graph, n=2):
        import numpy as np
        import jax
        from jax.sharding import Mesh

        if len(jax.devices()) < n:
            pytest.skip(f"need {n} devices")
        mesh = Mesh(np.array(jax.devices()[:n]), ("shards",))
        return (graph.checker().sound_eventually()
                .tpu_options(capacity=1 << 10, fmax=16, mesh=mesh)
                .spawn_tpu().join())

    def test_sharded_reports_pure_cycle(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2, 4, 2]))
        c = self.check_sharded(g)
        states = c.assert_any_discovery("odd").into_states()
        assert states[-1] in (2, 4) and states.count(states[-1]) >= 2
        assert not any(s % 2 == 1 for s in states)

    def test_sharded_cross_edge_cycle_found(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2, 4, 2])
             .with_path([0, 4]))
        c = self.check_sharded(g)
        states = c.assert_any_discovery("odd").into_states()
        assert not any(s % 2 == 1 for s in states)

    def test_sharded_no_false_positives(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 1, 2, 0]))
        self.check_sharded(g).assert_properties()


class TestGuardedCombinations:
    """The deliberately-unsupported feature combinations raise actionable
    errors (pinned so the capability matrix in README.md stays honest)."""

    @pytest.fixture(autouse=True)
    def _jax(self):
        pytest.importorskip("jax")

    def test_sound_with_host_props_raises(self):
        # sound dedup identity is (state, ebits) nodes; the host-property
        # history dedup keys on state columns — the two identities cannot
        # share one table. Sound mode only engages when an EVENTUALLY
        # property exists, so the fixture layers one on.
        from test_tpu_engine import _HostPropEquation

        class _SoundHostProp(_HostPropEquation):
            def properties(self):
                return super().properties() + [
                    Property.eventually("never", lambda _m, _s: False)]

        with pytest.raises(NotImplementedError, match="host-evaluated"):
            (_SoundHostProp(2, 0, 10**9).checker().sound_eventually()
             .tpu_options(capacity=1 << 10).spawn_tpu())

    def test_sound_level_mode_raises(self):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 2]))
        with pytest.raises(NotImplementedError, match="device engine"):
            (g.checker().sound_eventually()
             .tpu_options(capacity=1 << 10, mode="level")
             .spawn_tpu().join())
