"""Semantics-layer tests: spec tables + tester accept/reject tables.

Ports of the reference's co-located tests
(`/root/reference/src/semantics/{register,vec,write_once_register}.rs` and
`linearizability.rs:268-453`, `sequential_consistency.rs:240-344`).
"""

import pytest

from stateright_tpu.semantics import (
    Len, LenOk, LinearizabilityTester, Pop, PopOk, Push, PushOk, Read,
    ReadOk, Register, SequentialConsistencyTester, VecSpec, WORegister,
    Write, WriteFail, WriteOk)


# --- reference objects ------------------------------------------------------

def test_register_semantics():
    r = Register('A')
    assert r.invoke(Read()) == ReadOk('A')
    assert r.invoke(Write('B')) == WriteOk()
    assert r.invoke(Read()) == ReadOk('B')


def test_register_histories():
    assert Register('A').is_valid_history([])
    assert Register('A').is_valid_history([
        (Read(), ReadOk('A')),
        (Write('B'), WriteOk()),
        (Read(), ReadOk('B')),
        (Write('C'), WriteOk()),
        (Read(), ReadOk('C')),
    ])
    assert not Register('A').is_valid_history([
        (Read(), ReadOk('B')),
        (Write('B'), WriteOk()),
    ])
    assert not Register('A').is_valid_history([
        (Write('B'), WriteOk()),
        (Read(), ReadOk('A')),
    ])


def test_wo_register_semantics():
    # duplicate write of same value succeeds (`write_once_register.rs:32-39`)
    r = WORegister()
    assert r.invoke(Read()) == ReadOk(None)
    assert r.invoke(Write('B')) == WriteOk()
    assert r.invoke(Write('B')) == WriteOk()
    assert r.invoke(Write('C')) == WriteFail()
    assert r.invoke(Read()) == ReadOk('B')
    assert WORegister().is_valid_history([
        (Write('B'), WriteOk()),
        (Write('C'), WriteFail()),
        (Read(), ReadOk('B')),
    ])
    assert not WORegister().is_valid_history([
        (Write('B'), WriteOk()),
        (Write('C'), WriteOk()),
    ])


def test_vec_semantics():
    v = VecSpec()
    assert v.invoke(Pop()) == PopOk(None)
    assert v.invoke(Push(10)) == PushOk()
    assert v.invoke(Len()) == LenOk(1)
    assert v.invoke(Pop()) == PopOk(10)
    assert v.invoke(Len()) == LenOk(0)


# --- linearizability (`linearizability.rs:268-453`) -------------------------

def test_linearizability_rejects_invalid_history():
    t = LinearizabilityTester(Register('A'))
    t.on_invoke(99, Write('B'))
    with pytest.raises(ValueError, match="already has an operation"):
        t.on_invoke(99, Write('C'))

    t = LinearizabilityTester(Register('A'))
    t.on_invret(99, Write('B'), WriteOk())
    t.on_invret(99, Write('C'), WriteOk())
    with pytest.raises(ValueError, match="no in-flight invocation"):
        t.on_return(99, WriteOk())


def test_linearizable_register_history():
    t = LinearizabilityTester(Register('A'))
    t.on_invoke(0, Write('B'))
    t.on_invret(1, Read(), ReadOk('A'))
    assert t.serialized_history() == [(Read(), ReadOk('A'))]

    t = LinearizabilityTester(Register('A'))
    t.on_invoke(0, Read())
    t.on_invoke(1, Write('B'))
    t.on_return(0, ReadOk('B'))
    assert t.serialized_history() == [
        (Write('B'), WriteOk()),
        (Read(), ReadOk('B')),
    ]


def test_unlinearizable_register_history():
    t = LinearizabilityTester(Register('A'))
    t.on_invret(0, Read(), ReadOk('B'))
    assert t.serialized_history() is None

    t = LinearizabilityTester(Register('A'))
    t.on_invret(0, Read(), ReadOk('B'))
    t.on_invoke(1, Write('B'))
    assert t.serialized_history() is None  # SC but not linearizable


def test_linearizable_vec_history():
    t = LinearizabilityTester(VecSpec())
    t.on_invoke(0, Push(10))
    assert t.serialized_history() == []

    t = LinearizabilityTester(VecSpec())
    t.on_invoke(0, Push(10))
    t.on_invret(1, Pop(), PopOk(None))
    assert t.serialized_history() == [(Pop(), PopOk(None))]

    t = LinearizabilityTester(VecSpec())
    t.on_invoke(0, Push(10))
    t.on_invret(1, Pop(), PopOk(10))
    assert t.serialized_history() == [
        (Push(10), PushOk()), (Pop(), PopOk(10))]

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(0, Push(20))
    t.on_invret(1, Len(), LenOk(1))
    t.on_invret(1, Pop(), PopOk(20))
    t.on_invret(1, Pop(), PopOk(10))
    assert t.serialized_history() == [
        (Push(10), PushOk()), (Len(), LenOk(1)), (Push(20), PushOk()),
        (Pop(), PopOk(20)), (Pop(), PopOk(10))]

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(1, Len())
    t.on_invoke(0, Push(20))
    t.on_return(1, LenOk(2))
    assert t.serialized_history() == [
        (Push(10), PushOk()), (Push(20), PushOk()), (Len(), LenOk(2))]


def test_unlinearizable_vec_history():
    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invret(1, Pop(), PopOk(None))
    assert t.serialized_history() is None  # SC but not linearizable

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(1, Len())
    t.on_invoke(0, Push(20))
    t.on_return(1, LenOk(0))
    assert t.serialized_history() is None

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(0, Push(20))
    t.on_invret(1, Len(), LenOk(2))
    t.on_invret(1, Pop(), PopOk(10))
    t.on_invret(1, Pop(), PopOk(20))
    assert t.serialized_history() is None


# --- sequential consistency -------------------------------------------------

def test_sc_accepts_what_linearizability_rejects():
    # real-time order is not an SC constraint
    t = SequentialConsistencyTester(Register('A'))
    t.on_invret(0, Read(), ReadOk('B'))
    t.on_invoke(1, Write('B'))
    assert t.serialized_history() == [
        (Write('B'), WriteOk()), (Read(), ReadOk('B'))]

    t = SequentialConsistencyTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invret(1, Pop(), PopOk(None))
    assert t.serialized_history() is not None


def test_sc_rejects_spec_violations():
    t = SequentialConsistencyTester(Register('A'))
    t.on_invret(0, Read(), ReadOk('B'))
    assert t.serialized_history() is None


def test_testers_are_values():
    # clone + hash/eq over canonical contents (they ride in model state)
    t = LinearizabilityTester(Register('A'))
    t.on_invoke(0, Write('B'))
    dup = t.clone()
    assert dup == t and hash(dup) == hash(t)
    dup.on_return(0, WriteOk())
    assert dup != t

    from stateright_tpu.fingerprint import stable_fingerprint
    assert stable_fingerprint(t) != stable_fingerprint(dup)
    assert stable_fingerprint(t) == stable_fingerprint(t.clone())
