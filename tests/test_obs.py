"""Unified observability (stateright_tpu/obs/): metrics registry,
run-trace schema, trace-on/off parity, and overhead smoke.

The load-bearing guarantee is PARITY: enabling ``tpu_options(trace=...)``
must not change a single observable result — state counts, unique
counts, discoveries, reached fingerprints — on the single-chip device
engine, the sharded engine, and the host engines. Everything else
(schema, consumers) builds on that.
"""

import io
import json
import time

import pytest

from stateright_tpu.obs import (EVENT_SCHEMA, GLOSSARY, NULL_TRACE,
                                Metrics, RunTrace, make_trace,
                                validate_event)

pytestmark = pytest.mark.obs


# --- Metrics registry ------------------------------------------------------

class TestMetrics:
    def test_counters_timers_maxima(self):
        m = Metrics()
        m.inc("chunks")
        m.inc("chunks", 2)
        m.add_time("grow", 0.5)
        m.add_time("grow", 0.25)
        m.observe_max("vmax", 10)
        m.observe_max("vmax", 7)  # lower: ignored
        with m.timed("seed"):
            pass
        snap = m.snapshot()
        assert snap["chunks"] == 3
        assert snap["grow"] == 0.75
        assert snap["vmax"] == 10
        assert snap["seed"] >= 0.0
        # snapshot is a copy
        snap["chunks"] = 99
        assert m.get("chunks") == 3

    def test_merge_semantics(self):
        a, b = Metrics(), Metrics()
        a.inc("chunks", 2)
        a.observe_max("vmax", 5)
        b.inc("chunks", 3)
        b.observe_max("vmax", 9)
        a.merge(b)
        assert a.get("chunks") == 5  # counters add
        assert a.get("vmax") == 9  # maxima take max

    def test_merge_gauges_last_writer_wins(self):
        # gauges were SUMMED on merge: a raced-run fold could report
        # fused=2 or a mesh width no mesh ever had — pinned here
        from stateright_tpu.obs import GAUGES, GLOSSARY
        assert GAUGES <= set(GLOSSARY)
        a, b = Metrics(), Metrics()
        a.set("fused", 1)
        a.set("mesh_shards", 4)
        a.set("shard_balance", 0.9)
        a.set("fault_device", 3)
        b.set("fused", 1)
        b.set("mesh_shards", 2)
        b.set("history_ok", 1)
        a.merge(b)
        assert a.get("fused") == 1  # NOT 2
        assert a.get("mesh_shards") == 2  # the incoming width, not 6
        assert a.get("shard_balance") == 0.9  # absent in b: untouched
        assert a.get("fault_device") == 3
        assert a.get("history_ok") == 1
        # non-gauges still accumulate alongside
        b2 = Metrics()
        b2.inc("retries", 2)
        a.merge(b2)
        assert a.get("retries") == 2

    def test_glossary_covers_engine_keys(self):
        # the canonical keys every engine emits must stay documented
        for key in ("dispatch", "sync_stall", "host_overlap", "grow",
                    "hgrow", "chunks", "grows", "compiles", "vmax",
                    "dmax", "rmax", "levels", "jobs", "search",
                    "shard_balance"):
            assert key in GLOSSARY, key


# --- RunTrace sinks and schema ---------------------------------------------

class TestRunTrace:
    def test_disabled_is_falsy_noop(self):
        assert not NULL_TRACE
        NULL_TRACE.emit("chunk", anything=1)  # no-op, no error
        assert make_trace(None, engine="X") is NULL_TRACE
        with pytest.raises(RuntimeError, match="disabled trace"):
            NULL_TRACE.subscribe(lambda e: None)

    def test_list_sink_and_base_fields(self):
        events = []
        tr = RunTrace(events, engine="E")
        assert tr
        tr.emit("compile", reason="initial")
        assert events == [{"t": events[0]["t"], "ev": "compile",
                           "engine": "E", "reason": "initial"}]
        validate_event(events[0])

    def test_callable_and_file_sinks(self, tmp_path):
        got = []
        RunTrace(got.append, engine="E").emit("grow", capacity=4)
        assert got[0]["capacity"] == 4

        path = tmp_path / "t.jsonl"
        tr = RunTrace(str(path), engine="E")
        tr.emit("grow", capacity=8)
        tr.close()
        lines = path.read_text().splitlines()
        assert json.loads(lines[0])["capacity"] == 8

        buf = io.StringIO()
        RunTrace(buf, engine="E").emit("egrow", ecap=2)
        assert json.loads(buf.getvalue())["ev"] == "egrow"

    def test_bad_sink_rejected(self):
        with pytest.raises(TypeError, match="trace"):
            RunTrace(42, engine="E")

    def test_subscribers_receive_events(self):
        events = []
        tr = RunTrace(None, engine="E")
        assert not tr  # no sink, no subscribers: still off
        tr.subscribe(events.append)
        assert tr  # a subscriber enables it
        tr.emit("compile", reason="x")
        assert events[0]["reason"] == "x"

    def test_subscriber_runs_outside_lock(self):
        """Callbacks fire OUTSIDE the sink lock: a subscriber that
        itself emits (the SSE relay shape) must not deadlock on the
        non-reentrant lock, and a slow subscriber must not block
        another thread's writer. The old code held the lock across
        callbacks — this test hung under it."""
        import threading

        events = []
        tr = RunTrace(events, engine="E")

        def reentrant(ev):
            if ev["ev"] == "compile":
                tr.emit("grow", capacity=1)  # deadlocks if lock is held

        tr.subscribe(reentrant)
        t = threading.Thread(target=lambda: tr.emit("compile",
                                                    reason="x"),
                             daemon=True)
        t.start()
        t.join(5.0)
        assert not t.is_alive(), "emit deadlocked on its own subscriber"
        assert [e["ev"] for e in events] == ["compile", "grow"]

    def test_subscribe_during_live_emit_is_safe(self):
        """Subscribing to a live raced run raced the un-locked list
        append against emit's iteration; now appends happen under the
        lock onto a fresh list (copy-on-write) while emits iterate a
        snapshot — hammer both sides concurrently."""
        import threading

        tr = RunTrace([], engine="E")
        tr.subscribe(lambda ev: None)  # keep it truthy throughout
        stop = threading.Event()
        errors = []

        def emitter():
            try:
                while not stop.is_set():
                    tr.emit("compile", reason="x")
            except BaseException as exc:  # pragma: no cover
                errors.append(exc)

        t = threading.Thread(target=emitter, daemon=True)
        t.start()
        got = []
        for _ in range(200):
            fn = got.append
            tr.subscribe(fn)
            tr.unsubscribe(fn)
        stop.set()
        t.join(5.0)
        assert not errors
        assert not t.is_alive()

    def test_unsubscribe(self):
        got = []
        tr = RunTrace([], engine="E")
        tr.subscribe(got.append)
        tr.emit("compile", reason="a")
        tr.unsubscribe(got.append)  # different bound object: no-op...
        assert len(got) == 1
        fn = got.append
        tr.subscribe(fn)
        tr.unsubscribe(fn)
        tr.emit("compile", reason="b")
        assert len(got) == 2  # only the still-subscribed first append

    def test_validate_rejects_bad_events(self):
        with pytest.raises(ValueError, match="unknown trace event"):
            validate_event({"t": 0, "ev": "nope", "engine": "E"})
        with pytest.raises(ValueError, match="missing fields"):
            validate_event({"t": 0, "ev": "chunk", "engine": "E"})
        with pytest.raises(ValueError, match="base fields"):
            validate_event({"ev": "compile", "reason": "x"})


# --- emitted-stream schema validation --------------------------------------

def _twopc(n=3, **opts):
    from stateright_tpu.models.twopc import TwoPhaseSys
    return TwoPhaseSys(n).checker().tpu_options(
        capacity=1 << 12, race=False, **opts)


class TestEmittedSchema:
    def test_device_jsonl_schema(self, tmp_path):
        path = tmp_path / "run.jsonl"
        ck = _twopc(trace=str(path)).spawn_tpu().join()
        events = [json.loads(line)
                  for line in path.read_text().splitlines()]
        assert events, "no events emitted"
        for ev in events:
            validate_event(ev)
        kinds = {e["ev"] for e in events}
        assert {"run_start", "chunk", "done"} <= kinds
        # fingerprints must be JSON-safe strings (uint64 > 2^53)
        for ev in events:
            if ev["ev"] == "discovery":
                fp = ev["fp"]
                assert isinstance(fp, (str, list))
        done = [e for e in events if e["ev"] == "done"][-1]
        assert done["unique"] == ck.unique_state_count()
        assert done["gen"] == ck.state_count()

    def test_every_emitted_kind_is_in_schema(self):
        events = []
        _twopc(trace=events).spawn_tpu().join()
        assert {e["ev"] for e in events} <= set(EVENT_SCHEMA)

    def test_host_engines_emit(self):
        from stateright_tpu.models.fixtures import LinearEquation
        events = []
        (LinearEquation(2, 10, 14).checker()
         .tpu_options(trace=events).spawn_bfs().join())
        kinds = [e["ev"] for e in events]
        assert kinds[0] == "run_start"
        assert "discovery" in kinds and kinds[-1] == "done"
        for ev in events:
            validate_event(ev)

        events_dfs = []
        (LinearEquation(2, 10, 14).checker()
         .tpu_options(trace=events_dfs).spawn_dfs().join())
        assert any(e["ev"] == "discovery" for e in events_dfs)
        for ev in events_dfs:
            validate_event(ev)

    def test_fault_injection_event(self):
        from stateright_tpu.examples.write_once_packed import (
            PackedWriteOnce)
        events = []
        model = PackedWriteOnce(1, durable=True).crash_restart(
            1, actors=[0])
        (model.checker().tpu_options(capacity=1 << 12, race=False,
                                     trace=events)
         .spawn_tpu().join())
        fi = [e for e in events if e["ev"] == "fault_injection"]
        assert fi and fi[0]["max_crashes"] == 1
        assert fi[0]["actors"] == [0]


# --- parity: trace on/off must be bit-identical ----------------------------

class TestTraceParity:
    def _assert_parity(self, ck_off, ck_on):
        assert ck_on.unique_state_count() == ck_off.unique_state_count()
        assert ck_on.state_count() == ck_off.state_count()
        assert (sorted(ck_on.discoveries()) ==
                sorted(ck_off.discoveries()))
        assert (ck_on.generated_fingerprints() ==
                ck_off.generated_fingerprints())

    def test_twopc_single_chip(self):
        ck_off = _twopc().spawn_tpu().join()
        ck_on = _twopc(trace=[]).spawn_tpu().join()
        assert ck_on.unique_state_count() == 288
        self._assert_parity(ck_off, ck_on)

    def test_paxos_capped(self):
        from stateright_tpu.examples.paxos_packed import PackedPaxos

        def mk(**opts):
            return (PackedPaxos(2).checker()
                    .tpu_options(capacity=1 << 14, race=False, **opts)
                    .target_state_count(2000).spawn_tpu().join())

        mk()  # warm: pin the observed-size memo for both runs
        self._assert_parity(mk(), mk(trace=[]))

    def test_sharded(self):
        import jax
        import numpy as np
        from jax.sharding import Mesh

        def mk(**opts):
            mesh = Mesh(np.array(jax.devices()[:2]), ("shards",))
            return _twopc(mesh=mesh, **opts).spawn_tpu().join()

        events = []
        ck_off, ck_on = mk(), mk(trace=events)
        self._assert_parity(ck_off, ck_on)
        chunk = [e for e in events if e["ev"] == "chunk"][-1]
        assert len(chunk["shard_new"]) == 2  # per-shard volumes ride

    def test_host_bfs_parity(self):
        from stateright_tpu.models.twopc import TwoPhaseSys
        ck_off = TwoPhaseSys(3).checker().spawn_bfs().join()
        ck_on = (TwoPhaseSys(3).checker().tpu_options(trace=[])
                 .spawn_bfs().join())
        self._assert_parity(ck_off, ck_on)


# --- overhead smoke --------------------------------------------------------

class TestOverhead:
    def test_disabled_emit_is_trivial(self):
        t0 = time.perf_counter()
        for _ in range(100_000):
            if NULL_TRACE:
                NULL_TRACE.emit("chunk", gen=1)
        assert time.perf_counter() - t0 < 0.5

    def test_traced_run_overhead_smoke(self):
        """Loose CI bound (CPU timing is noisy); the <2% contract is
        measured on the bench workload via bench.py's metrics lines."""
        def mk(**opts):
            return _twopc(4, **opts).spawn_tpu().join()

        mk()  # warm compile
        off = min(self._clock(mk), self._clock(mk))
        on = min(self._clock(lambda: mk(trace=[])),
                 self._clock(lambda: mk(trace=[])))
        assert on < off * 1.5 + 0.25, (on, off)

    @staticmethod
    def _clock(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0


# --- schema drift lint -----------------------------------------------------

class TestSchemaDriftLint:
    """New instrumentation cannot silently bypass the canonical
    registries: every literal ``trace.emit("<ev>", ...)`` event name in
    the source tree must be in EVENT_SCHEMA, and every literal metrics
    key (``inc``/``set``/``observe_max``/``add_time``/``timed``) must
    be in GLOSSARY. This is the check that kept PR 3's unification from
    rotting — a drive-by `self._metrics.inc("my_counter")` fails here,
    not in a code review six rounds later."""

    def _sources(self):
        import glob
        import os
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        files = glob.glob(os.path.join(root, "stateright_tpu", "**",
                                       "*.py"), recursive=True)
        files += glob.glob(os.path.join(root, "tools", "*.py"))
        files.append(os.path.join(root, "bench.py"))
        assert len(files) > 40, "source scan found too few files"
        for path in files:
            with open(path) as f:
                yield path, f.read()

    def test_emitted_event_names_are_in_schema(self):
        import re
        emit_re = re.compile(r'\.emit\(\s*[\'"]([a-z_0-9]+)[\'"]')
        bad = [(path, name)
               for path, src in self._sources()
               for name in emit_re.findall(src)
               if name not in EVENT_SCHEMA]
        assert not bad, f"trace events missing from EVENT_SCHEMA: {bad}"

    def test_metrics_keys_are_in_glossary(self):
        import re
        key_res = (
            re.compile(r'(?:metrics|_metrics)\.'
                       r'(?:inc|set|observe_max|add_time|timed)'
                       r'\(\s*[\'"]([a-z_0-9]+)[\'"]'),
            re.compile(r'self\._timed\(\s*[\'"]([a-z_0-9]+)[\'"]'),
        )
        bad = [(path, key)
               for path, src in self._sources()
               for rx in key_res
               for key in rx.findall(src)
               if key not in GLOSSARY]
        assert not bad, f"metrics keys missing from GLOSSARY: {bad}"

    def test_schema_events_all_have_emit_sites(self):
        """The reverse lint (PR 14): every EVENT_SCHEMA entry must be
        emitted somewhere in the source tree — schema entries nothing
        emits are dead weight that silently bless typo'd names. This
        is what guarantees the lint actually COVERS the service
        (job_*), fleet (host_join/mesh_init), and lifecycle/
        aggregation emit sites rather than merely not rejecting
        them."""
        import re
        emit_re = re.compile(r'\.emit\(\s*[\'"]([a-z_0-9]+)[\'"]')
        emitted = set()
        for _path, src in self._sources():
            emitted.update(emit_re.findall(src))
        dead = set(EVENT_SCHEMA) - emitted
        assert not dead, f"EVENT_SCHEMA events nothing emits: {dead}"

    def test_lifecycle_and_fleet_families_are_pinned(self):
        """The service/fleet/observability-plane event families and
        the PR-14 glossary keys must stay registered — a drive-by
        rename breaks every recorded artifact's consumers."""
        for ev in ("trace_header",
                   "job_submit", "job_grant", "job_start",
                   "job_first_chunk", "job_pause", "job_resume",
                   "job_done", "pool_util",
                   "mesh_init", "host_join", "host_drop",
                   "bucket_flush", "batch_form", "lane_retire"):
            assert ev in EVENT_SCHEMA, ev
        for key in ("queue_wait_s", "first_chunk_s", "pool_busy_frac",
                    "jobs_per_min", "sse_dropped", "queue_depth",
                    "jobs_submitted", "jobs_done", "hosts", "procs"):
            assert key in GLOSSARY, key
        # the exposition typing derives from GAUGES: the new gauges
        # must be registered there or /metrics would type them counter
        from stateright_tpu.obs import GAUGES
        assert {"pool_busy_frac", "jobs_per_min"} <= GAUGES


# --- consumers -------------------------------------------------------------

class TestConsumers:
    def test_race_profile_tags_winner(self):
        # satellite fix: a host-won race used to report {}
        from stateright_tpu.examples.increment_lock import IncrementLock
        ck = IncrementLock(2).checker().spawn_tpu().join()
        prof = ck.profile()
        assert prof["engine"] in ("host", "device")
        assert "search" in prof  # the winner's real metrics rode along

    def test_profile_keys_stay_in_glossary(self):
        ck = _twopc().spawn_tpu().join()
        unknown = set(ck.profile()) - set(GLOSSARY)
        assert not unknown, f"undocumented profile keys: {unknown}"

    def test_report_metrics_line(self):
        w = io.StringIO()
        _twopc().spawn_tpu().report(w)
        out = w.getvalue()
        assert "\n# " in out and "chunks=" in out, out

    def test_subscribe_live_progress(self):
        seen = []
        ck = _twopc(trace=[]).spawn_tpu()
        ck.subscribe(seen.append)
        ck.join()
        assert any(e["ev"] == "chunk" for e in seen)

    def test_explorer_metrics_endpoint(self):
        import urllib.request

        from stateright_tpu.checker.explorer import serve
        from stateright_tpu.models.twopc import TwoPhaseSys
        checker, server = serve(TwoPhaseSys(2).checker(),
                                ("127.0.0.1", 0), block=False)
        host, port = server.server_address
        try:
            checker.join()
            with urllib.request.urlopen(
                    f"http://{host}:{port}/.metrics") as r:
                payload = json.loads(r.read())
            assert payload["done"] is True
            assert payload["unique_state_count"] > 0
            assert "search" in payload["profile"]
        finally:
            server.shutdown()
            server.server_close()

    def test_trace_report_tool(self, tmp_path, capsys):
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        try:
            import trace_report
        finally:
            sys.path.pop(0)
        path = tmp_path / "run.jsonl"
        _twopc(trace=str(path)).spawn_tpu().join()
        assert trace_report.main([str(path), "--validate"]) == 0
        out = capsys.readouterr().out
        assert "=== engine: TpuChecker" in out
        assert "done:" in out and "timeline:" in out
