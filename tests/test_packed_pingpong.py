"""Lossy + duplicating network semantics on the TPU engine, pinned by the
ping_pong oracles (`/root/reference/src/actor/model.rs:603-646`): lossy
duplicating max 5 -> 4,094 unique states; lossless non-duplicating
max 5 -> 11. Drop actions are part of the packed action axis, so
message-loss interleavings are explored exhaustively on device."""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.actor.core import Envelope, Id  # noqa: E402
from stateright_tpu.actor.model import Deliver, Drop  # noqa: E402
from stateright_tpu.actor.test_util import PackedPingPong, Ping  # noqa: E402
from stateright_tpu.models.packed import validate_packed_model  # noqa: E402


class TestPackedPingPong:
    def test_contract_lossy_duplicating_full(self):
        # host/device step agreement over the whole 4,094-state space,
        # including every Drop successor
        assert validate_packed_model(
            PackedPingPong(5, lossy=True, duplicating=True),
            max_states=5000) == 4_094

    def test_contract_lossless_nonduplicating(self):
        assert validate_packed_model(
            PackedPingPong(5, lossy=False, duplicating=False),
            max_states=100) == 11

    def test_device_lossy_duplicating_4094(self):
        ck = (PackedPingPong(5, lossy=True, duplicating=True).checker()
              .tpu_options(capacity=1 << 14).spawn_tpu().join())
        assert ck.unique_state_count() == 4_094
        assert ck.discovery("delta within 1") is None  # safety holds
        assert ck.discovery("can reach max") is not None
        # dropping messages can stall the protocol: the liveness
        # counterexample surfaces at a terminal, and its witness replays
        # through the host model (Drop actions included)
        path = ck.assert_any_discovery("must reach max")
        assert max(path.last_state().actor_states) < 5

    def test_device_matches_host_reached_set(self):
        model = PackedPingPong(5, lossy=True, duplicating=True)
        host = model.checker().spawn_bfs().join()
        dev = (PackedPingPong(5, lossy=True, duplicating=True).checker()
               .tpu_options(capacity=1 << 14).spawn_tpu().join())
        assert host.unique_state_count() == 4_094
        assert (dev.generated_fingerprints()
                == host.generated_fingerprints())

    def test_device_lossless_nonduplicating_11(self):
        ck = (PackedPingPong(5, lossy=False, duplicating=False).checker()
              .tpu_options(capacity=1 << 10, fmax=16).spawn_tpu().join())
        assert ck.unique_state_count() == 11
        assert ck.discovery("delta within 1") is None
        assert ck.discovery("can reach max") is not None
        assert ck.discovery("must reach max") is None  # liveness holds

    def test_drop_witness_replays_on_host(self):
        # `model.rs:616-631`: dropping the first Ping gets stuck — the
        # canonical witness must also be accepted by assert_discovery
        ck = (PackedPingPong(5, lossy=True, duplicating=True).checker()
              .tpu_options(capacity=1 << 14).spawn_tpu().join())
        ck.assert_discovery("must reach max", [
            Drop(Envelope(src=Id(0), dst=Id(1), msg=Ping(0))),
        ])


def test_remaining_network_quadrants_contract():
    # lossless+duplicating (delivery leaves the envelope, no Drop lanes)
    # and lossy+non-duplicating (Drop decrements a count) are distinct
    # code paths from the two pinned configs
    assert validate_packed_model(
        PackedPingPong(5, lossy=False, duplicating=True),
        max_states=100) == 11
    assert validate_packed_model(
        PackedPingPong(5, lossy=True, duplicating=False),
        max_states=100) == 22
