"""Packed ABD (linearizable register) on the device engine.

Differential oracles: the packed model must agree with the plain
ActorModel state-for-state (544 for 2 clients + 2 servers —
`/root/reference/examples/linearizable-register.rs:258`), and the packed
transition relation must match the host semantics on every reachable
state."""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.examples.abd_packed import PackedAbd  # noqa: E402
from stateright_tpu.models.packed import validate_packed_model  # noqa: E402


class TestPackedAbdContract:
    def test_validate_full_2x2(self):
        # full reachable-space contract check: encode/decode round-trips,
        # device fingerprints, packed successors vs host successors
        assert validate_packed_model(
            PackedAbd(2, server_count=2), max_states=600) == 544


class TestPackedAbdOnDevice:
    def test_device_544(self):
        ck = (PackedAbd(2, server_count=2).checker()
              .tpu_options(capacity=1 << 12).spawn_tpu().join())
        assert ck.unique_state_count() == 544
        ck.assert_properties()
        path = ck.discoveries()["value chosen"]
        assert len(path.into_actions()) >= 1  # witness replays

    def test_matches_host_set(self):
        host = (PackedAbd(2, server_count=2).checker()
                .spawn_bfs().join())
        dev = (PackedAbd(2, server_count=2).checker()
               .tpu_options(capacity=1 << 12).spawn_tpu().join())
        assert host.unique_state_count() == 544
        assert dev.generated_fingerprints() == host.generated_fingerprints()

    def test_agrees_with_plain_model(self):
        # the packed model and the plain linearizable_register model are
        # the same system: identical unique counts
        from stateright_tpu.actor.network import Network
        from stateright_tpu.examples.linearizable_register import (
            AbdModelCfg)
        plain = (AbdModelCfg(client_count=2, server_count=2,
                             network=Network.new_unordered_nonduplicating())
                 .into_model().checker().spawn_bfs().join())
        packed = PackedAbd(2, server_count=2).checker().spawn_bfs().join()
        assert (plain.unique_state_count()
                == packed.unique_state_count() == 544)

    def test_three_servers(self):
        # quorum-of-2 behavior with 3 replicas: host/device agreement
        host = (PackedAbd(1, server_count=3).checker()
                .spawn_bfs().join())
        dev = (PackedAbd(1, server_count=3).checker()
               .tpu_options(capacity=1 << 13).spawn_tpu().join())
        assert dev.unique_state_count() == host.unique_state_count()
        dev.assert_properties()


class TestOrderedOnDevice:
    """The ordered network semantics (per-(src, dst) FIFO channels) on the
    TPU engine — the reference's `check N ordered` CLI configuration
    (`linearizable-register.rs`, `network.rs:157-170`: ordered networks
    expose only channel heads)."""

    def test_contract_full_space(self):
        from stateright_tpu.models.packed import validate_packed_model

        assert validate_packed_model(
            PackedAbd(2, server_count=2, ordered=True),
            max_states=600) == 564

    def test_device_matches_host(self):
        host = (PackedAbd(2, server_count=2, ordered=True).checker()
                .spawn_bfs().join())
        dev = (PackedAbd(2, server_count=2, ordered=True).checker()
               .tpu_options(capacity=1 << 12).spawn_tpu().join())
        assert host.unique_state_count() == 564
        assert dev.unique_state_count() == 564
        assert (dev.generated_fingerprints()
                == host.generated_fingerprints())
        dev.assert_properties()

    def test_channel_overflow_is_loud(self):
        import pytest

        # 2+3 ordered overflows depth-4 channels within 100k states; the
        # engine must hard-error, never silently under-explore
        with pytest.raises(RuntimeError, match="capacity overflow"):
            (PackedAbd(2, server_count=3, ordered=True, channel_depth=4)
             .checker().tpu_options(capacity=1 << 18)
             .target_state_count(100_000).spawn_tpu().join())
