"""Packed ABD (linearizable register) on the device engine.

Differential oracles: the packed model must agree with the plain
ActorModel state-for-state (544 for 2 clients + 2 servers —
`/root/reference/examples/linearizable-register.rs:258`), and the packed
transition relation must match the host semantics on every reachable
state."""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.examples.abd_packed import PackedAbd  # noqa: E402
from stateright_tpu.models.packed import validate_packed_model  # noqa: E402


class TestPackedAbdContract:
    def test_validate_full_2x2(self):
        # full reachable-space contract check: encode/decode round-trips,
        # device fingerprints, packed successors vs host successors
        assert validate_packed_model(
            PackedAbd(2, server_count=2), max_states=600) == 544


class TestPackedAbdOnDevice:
    def test_device_544(self):
        ck = (PackedAbd(2, server_count=2).checker()
              .tpu_options(capacity=1 << 12).spawn_tpu().join())
        assert ck.unique_state_count() == 544
        ck.assert_properties()
        path = ck.discoveries()["value chosen"]
        assert len(path.into_actions()) >= 1  # witness replays

    def test_matches_host_set(self):
        host = (PackedAbd(2, server_count=2).checker()
                .spawn_bfs().join())
        dev = (PackedAbd(2, server_count=2).checker()
               .tpu_options(capacity=1 << 12).spawn_tpu().join())
        assert host.unique_state_count() == 544
        assert dev.generated_fingerprints() == host.generated_fingerprints()

    def test_agrees_with_plain_model(self):
        # the packed model and the plain linearizable_register model are
        # the same system: identical unique counts
        from stateright_tpu.actor.network import Network
        from stateright_tpu.examples.linearizable_register import (
            AbdModelCfg)
        plain = (AbdModelCfg(client_count=2, server_count=2,
                             network=Network.new_unordered_nonduplicating())
                 .into_model().checker().spawn_bfs().join())
        packed = PackedAbd(2, server_count=2).checker().spawn_bfs().join()
        assert (plain.unique_state_count()
                == packed.unique_state_count() == 544)

    @pytest.mark.slow  # ~28s warm: 3-replica host + device enumerations
    def test_three_servers(self):
        # quorum-of-2 behavior with 3 replicas: host/device agreement
        host = (PackedAbd(1, server_count=3).checker()
                .spawn_bfs().join())
        dev = (PackedAbd(1, server_count=3).checker()
               .tpu_options(capacity=1 << 13).spawn_tpu().join())
        assert dev.unique_state_count() == host.unique_state_count()
        dev.assert_properties()


class TestOrderedOnDevice:
    """The ordered network semantics (per-(src, dst) FIFO channels) on the
    TPU engine — the reference's `check N ordered` CLI configuration
    (`linearizable-register.rs`, `network.rs:157-170`: ordered networks
    expose only channel heads)."""

    def test_contract_full_space(self):
        from stateright_tpu.models.packed import validate_packed_model

        assert validate_packed_model(
            PackedAbd(2, server_count=2, ordered=True),
            max_states=600) == 564

    def test_device_matches_host(self):
        host = (PackedAbd(2, server_count=2, ordered=True).checker()
                .spawn_bfs().join())
        dev = (PackedAbd(2, server_count=2, ordered=True).checker()
               .tpu_options(capacity=1 << 12).spawn_tpu().join())
        assert host.unique_state_count() == 564
        assert dev.unique_state_count() == 564
        assert (dev.generated_fingerprints()
                == host.generated_fingerprints())
        dev.assert_properties()

    @pytest.mark.slow  # ~24s warm: 100k-state run to the overflow
    def test_channel_overflow_is_loud(self):
        import pytest

        # 2+3 ordered overflows depth-4 channels within 100k states; the
        # engine must hard-error, never silently under-explore
        with pytest.raises(RuntimeError, match="capacity overflow"):
            (PackedAbd(2, server_count=3, ordered=True, channel_depth=4)
             .checker().tpu_options(capacity=1 << 18)
             .target_state_count(100_000).spawn_tpu().join())

    def test_out_of_range_recipient_is_loud(self):
        # Regression: a send to sdst >= n_actors from a non-last sender
        # has a flat index cd = sender*A + sdst < n_chan, which used to
        # alias into a real channel (e.g. A=3, sender=0, sdst=4 lands in
        # channel (1,1)) and silently corrupt exploration. It must be
        # reported as encoding overflow like any other unencodable send.
        import pytest

        from stateright_tpu.actor.core import Actor, Id, Out
        from stateright_tpu.actor.network import Network
        from stateright_tpu.actor.packed import PackedActorModel
        from stateright_tpu.core import Expectation

        class Misaddressing(Actor):
            def on_start(self, id, o: Out):
                if int(id) == 0:
                    o.send(Id(0), 1)  # seed channel (0, 0)
                return 0

            def on_msg(self, id, state, src, msg, o: Out):
                o.send(Id(4), 2)  # recipient does not exist
                return state + 1

        class BadModel(PackedActorModel):
            def __init__(self):
                super().__init__(cfg=self, init_history=None)
                for _ in range(3):
                    self.actor(Misaddressing())
                self.init_network(Network.new_ordered())
                self.property(Expectation.ALWAYS, "true",
                              lambda m, s: True)
                self.actor_widths = [1, 1, 1]
                self.msg_width = 1
                self.net_capacity = 4
                self.max_sends = 1
                self.history_width = 0
                self.finalize_layout()

            def cache_key(self):
                return ("bad_recipient_ordered",)

            def encode_actor(self, index, state):
                return [int(state)]

            def decode_actor(self, index, words):
                return int(words[0])

            def encode_msg(self, msg):
                return [int(msg)]

            def decode_msg(self, words):
                return int(words[0])

            def packed_deliver(self, actors, src, dst, msg):
                import jax.numpy as jnp
                sel = jnp.arange(3, dtype=jnp.uint32) == dst
                new_actors = jnp.where(sel, actors + 1, actors) \
                    .astype(jnp.uint32)
                send = (jnp.uint32(4), jnp.full((1,), 2, jnp.uint32),
                        jnp.bool_(True))
                return new_actors, jnp.bool_(True), [send]

            def packed_properties(self, words):
                import jax.numpy as jnp
                return jnp.stack([jnp.bool_(True)])

        with pytest.raises(RuntimeError, match="capacity overflow"):
            (BadModel().checker().tpu_options(capacity=1 << 10)
             .spawn_tpu().join())
