"""Continuous verification fleet: soak/fuzz job kinds + burn-in mode
(README § Continuous verification).

ACCEPTANCE pins, all on the CPU-forced virtual mesh:

* a ``kind: soak`` job runs a seeded chaos soak on a scheduler worker
  thread and lands the standard per-job artifact set (history.jsonl,
  schema-valid trace.jsonl, result.json with verdict + op/fault
  counts);
* a scheduler with burn-in enabled SATURATES a 2-device pool with fuzz
  jobs; a submitted real checking job is granted within one
  op-boundary preemption and finishes bit-identical (sha256 digest) to
  a solo run; the preempted fuzz job resumes its remaining op budget
  and completes;
* a seeded violating config run as a service job auto-files its
  rejected history into the corpus directory under the
  ``(protocol, tester, sha256(ops))`` dedup key, and the corpus replay
  check keeps rejecting it;
* pause → resume of a soak job crosses segments (op-boundary stop,
  remaining budget resumed).

The ``bench.py --burnin-smoke`` contract subprocess pin rides ``-m
slow`` (tier-1 budget discipline — the in-process pins above cover the
same machinery).
"""

import hashlib
import json
import os
import sys
import time

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402
from stateright_tpu.obs import validate_event  # noqa: E402
from stateright_tpu.service import (BURNIN_PRIORITY, JobSpec,  # noqa: E402
                                    JobStore, Scheduler)
from stateright_tpu.soak import check_artifact  # noqa: E402

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: pinned engine shapes (shared with tests/test_service.py so the
#: persistent compile cache is reused)
OPTS = {"capacity": 1 << 12, "fmax": 64, "chunk_steps": 2}


def _digest(checker) -> str:
    fps = sorted(int(f) for f in checker.generated_fingerprints())
    return hashlib.sha256("\n".join(map(str, fps)).encode()).hexdigest()


@pytest.fixture(scope="module")
def solo_2pc3_digest():
    ck = (TwoPhaseSys(3).checker()
          .tpu_options(race=False, **OPTS).spawn_tpu().join())
    return _digest(ck)


def _wait_running(sched, n, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        running = [j for j in sched.jobs() if j.state == "running"]
        if len(running) >= n:
            return running
        time.sleep(0.05)
    raise AssertionError(
        f"pool never reached {n} running jobs: "
        f"{[(j.id, j.state) for j in sched.jobs()]}")


class TestSoakJobKinds:
    def test_soak_job_lands_standard_artifacts(self, tmp_path):
        sched = Scheduler(JobStore(str(tmp_path / "svc")),
                          devices=jax.devices()[:1])
        try:
            job = sched.submit(JobSpec(
                "write_once", kind="soak",
                kwargs={"ops": 80, "seed": 3, "crashes": 1,
                        "partitions": 0, "deadline": 20.0}))
            assert sched.wait(job.id, timeout=60.0) == "done"
            view = job.view()
            assert view["kind"] == "soak"
            assert view["history_ok"] is True
            result = job.read_result()
            assert result["kind"] == "soak"
            assert result["protocol"] == "write_once"
            assert result["history_ok"] is True
            assert result["ops"] == 80 and result["completed"] > 0
            assert result["faults"]["crashes"] == 1
            assert result["segments"] == 1
            assert result["lifecycle"]["queue_wait_s"] >= 0
            # the standard artifact set: history + schema-valid trace
            assert os.path.exists(os.path.join(job.dir,
                                               "history.jsonl"))
            with open(os.path.join(job.dir, "trace.jsonl")) as f:
                events = [json.loads(line) for line in f]
            for ev in events:
                validate_event(ev)
            kinds = {e["ev"] for e in events}
            assert {"run_start", "soak_start", "soak_done"} <= kinds
            prof = sched.profile()
            assert prof["soak_jobs"] == 1
            assert prof["fuzz_ops"] == result["completed"]
        finally:
            sched.shutdown()

    def test_fuzz_kind_derives_knobs_from_seed(self, tmp_path):
        # the registry config + seed fully determine the fault mix;
        # unknown configs fail loudly with the known list
        from stateright_tpu.soak import build_soak_config
        a = build_soak_config("write_once", {"seed": 9}, kind="fuzz")
        b = build_soak_config("write_once", {"seed": 9}, kind="fuzz")
        c = build_soak_config("write_once", {"seed": 10}, kind="fuzz")
        knobs = ("loss", "duplicate", "delay", "crashes", "partitions",
                 "put_ratio", "clients")
        assert [getattr(a, k) for k in knobs] \
            == [getattr(b, k) for k in knobs]
        assert [getattr(a, k) for k in knobs] \
            != [getattr(c, k) for k in knobs]
        # explicit overrides always win over the perturbation
        pinned = build_soak_config("write_once",
                                   {"seed": 9, "crashes": 0},
                                   kind="fuzz")
        assert pinned.crashes == 0
        with pytest.raises(ValueError, match="known configs"):
            build_soak_config("nope", {})
        with pytest.raises(ValueError, match="unknown SoakConfig"):
            build_soak_config("write_once", {"bogus_knob": 1})
        # spec validation: soak jobs cannot ride the batch lanes
        with pytest.raises(ValueError, match="batch"):
            JobSpec("write_once", kind="soak", batch="auto")
        with pytest.raises(ValueError, match="kind"):
            JobSpec("write_once", kind="chaos")

    def test_pause_resumes_remaining_budget_as_new_segment(
            self, tmp_path):
        sched = Scheduler(JobStore(str(tmp_path / "svc")),
                          devices=jax.devices()[:1])
        try:
            job = sched.submit(JobSpec(
                "write_once", kind="soak",
                kwargs={"ops": 1200, "seed": 5, "crashes": 0,
                        "partitions": 0, "delay": 0.0,
                        "op_timeout": 0.15, "deadline": 60.0}))
            _wait_running(sched, 1)
            time.sleep(0.4)  # let some ops land
            assert sched.pause(job.id)
            assert sched.wait(job.id, timeout=30.0,
                              states=("paused",)) == "paused"
            ops_done = job.status["ops_done"]
            assert 0 < ops_done < 1200, ops_done
            assert job.status["segments"] == 1
            assert sched.resume(job.id)
            assert sched.wait(job.id, timeout=90.0) == "done"
            result = job.read_result()
            assert result["segments"] == 2
            assert result["ops"] == 1200
            assert result["history_ok"] is True
        finally:
            sched.shutdown()

    def test_violating_config_auto_files_into_corpus(self, tmp_path):
        corpus = str(tmp_path / "corpus")
        sched = Scheduler(JobStore(str(tmp_path / "svc")),
                          devices=jax.devices()[:1],
                          corpus_dir=corpus)
        try:
            job = sched.submit(JobSpec("write_once_volatile",
                                       kind="soak",
                                       kwargs={"seed": 4}))
            assert sched.wait(job.id, timeout=60.0) == "done"
            result = job.read_result()
            assert result["history_ok"] is False
            # the ONLINE checker pinned the offending op strictly
            # inside the history
            assert result["violation_op"] is not None
            assert result["violation_op"] < result["completed"]
            assert sched.profile()["violations"] == 1
            # the artifact landed under its dedup key, in the corpus
            # dir, and the corpus replay check keeps rejecting it —
            # exactly what tests/test_fuzz_differential.py runs over
            # the committed tests/soak_seeds/ layout
            files = [f for f in os.listdir(corpus)
                     if f.endswith(".jsonl")]
            assert len(files) == 1
            assert files[0].startswith(
                "soak_write_once_volatile_linearizability_")
            verdicts = check_artifact(os.path.join(corpus, files[0]))
            assert verdicts and not any(verdicts.values())
            assert result["artifact"] == os.path.join(corpus, files[0])
            # the violation event rode the job's trace
            with open(os.path.join(job.dir, "trace.jsonl")) as f:
                events = [json.loads(line) for line in f]
            viol = [e for e in events if e["ev"] == "violation"]
            assert viol and viol[0]["tester"] == "linearizability"
            assert viol[0]["op_index"] == result["violation_op"]
        finally:
            sched.shutdown()


class TestBurninMode:
    def test_burnin_e2e_preemption_parity_and_resume(
            self, tmp_path, solo_2pc3_digest):
        """THE acceptance pin: saturation → op-boundary preemption →
        bit-identical real job → preempted fuzz lane resumes and
        completes."""
        sched = Scheduler(
            JobStore(str(tmp_path / "svc")),
            devices=jax.devices()[:2],
            burnin={"kind": "fuzz", "config": "write_once",
                    "overrides": {"ops": 700, "deadline": 40.0,
                                  "crashes": 0, "partitions": 0,
                                  "delay": 0.0, "op_timeout": 0.15},
                    "max_jobs": 2})
        try:
            running = _wait_running(sched, 2)
            assert all(j.spec.burnin for j in running)
            assert all(j.spec.kind == "fuzz" for j in running)
            assert all(j.priority == BURNIN_PRIORITY for j in running)
            util = sched.utilization()
            assert util["busy_frac"] == 1.0
            assert util["burnin_frac"] == 1.0
            # a real checking job preempts a fuzz lane and lands the
            # solo-identical digest
            real = sched.submit(JobSpec("twopc", args=[3],
                                        options=OPTS))
            assert sched.wait(real.id, timeout=120.0) == "done"
            result = real.read_result()
            assert result["fingerprints_sha256"] == solo_2pc3_digest
            prof = sched.profile()
            assert prof["preemptions"] >= 1
            preempted = [j for j in sched.jobs()
                         if j.status.get("preempted")]
            assert preempted, "no burn-in lane was preempted"
            victim = preempted[0]
            # the preempted fuzz job resumes and completes its budget
            assert sched.wait(victim.id, timeout=120.0) == "done"
            vres = victim.read_result()
            assert vres["segments"] >= 2
            assert vres["ops"] == 700
            assert vres["history_ok"] is True
            # burn-in visibility: the preemption event + submit marks
            with open(os.path.join(str(tmp_path / "svc"),
                                   "service.jsonl")) as f:
                events = [json.loads(line) for line in f]
            for ev in events:
                validate_event(ev)
            kinds = {}
            for ev in events:
                kinds[ev["ev"]] = kinds.get(ev["ev"], 0) + 1
            assert kinds.get("burnin_preempt", 0) >= 1
            assert any(e["ev"] == "job_submit" and e.get("burnin")
                       for e in events)
        finally:
            sched.shutdown()

    def test_burnin_caps_and_drains(self, tmp_path):
        # max_jobs bounds synthesis: the fleet runs its seeds to
        # completion and the pool drains back to idle
        sched = Scheduler(
            JobStore(str(tmp_path / "svc")),
            devices=jax.devices()[:2],
            burnin={"kind": "soak", "config": "write_once",
                    "overrides": {"ops": 60, "deadline": 20.0,
                                  "crashes": 0, "partitions": 0,
                                  "delay": 0.0, "op_timeout": 0.15},
                    "max_jobs": 3})
        try:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                jobs = sched.jobs()
                if len(jobs) == 3 and all(j.state == "done"
                                          for j in jobs):
                    break
                time.sleep(0.1)
            jobs = sched.jobs()
            assert len(jobs) == 3
            assert all(j.state == "done" for j in jobs)
            # distinct seeds per synthesized job (seed0 + sequence)
            seeds = sorted(j.spec.kwargs["seed"] for j in jobs)
            assert seeds == [0, 1, 2]
            assert sched.profile()["soak_jobs"] == 3
            util = sched.utilization()
            assert util["burnin_frac"] == 0.0
            assert util["busy_frac"] == 0.0
        finally:
            sched.shutdown()


@pytest.mark.slow
class TestBurninBenchContract:
    def test_bench_burnin_smoke_contract(self):
        import subprocess
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--burnin-smoke"],
            capture_output=True, text=True, timeout=300, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        contract = json.loads(proc.stdout.strip().splitlines()[-1])
        assert contract["burnin"] is True
        assert contract["unit"] == "jobs/min"
        assert contract["jobs_per_min"]["burnin"] > 0
        assert contract["jobs_per_min"]["real"] > 0
        assert contract["preemptions"] >= 1
        assert "partial" not in contract
