"""DenseNatMap and VectorClock, mirroring the reference's coverage
(`/root/reference/src/util/densenatmap.rs:238-329`,
`src/util/vector_clock.rs:108-273`)."""

import pytest

from stateright_tpu import DenseNatMap, VectorClock, stable_fingerprint
from stateright_tpu.actor.core import Id
from stateright_tpu.checker.representative import RewritePlan


class TestDenseNatMap:
    def test_insert_in_order(self):
        m = DenseNatMap()
        assert m.insert(Id(0), "first") is None
        assert m.insert(Id(1), "second") is None
        assert len(m) == 2
        assert m[Id(0)] == "first" and m[Id(1)] == "second"

    def test_insert_overwrites(self):
        m = DenseNatMap(["a", "b"])
        assert m.insert(Id(1), "B") == "b"
        assert m[1] == "B"

    def test_insert_out_of_order_raises(self):
        m = DenseNatMap()
        with pytest.raises(IndexError):
            m.insert(Id(1), "second")

    def test_from_pairs_any_order(self):
        m = DenseNatMap.from_pairs([(Id(1), "second"), (Id(0), "first")])
        assert list(m.values()) == ["first", "second"]

    def test_from_pairs_gap_raises(self):
        with pytest.raises(ValueError):
            DenseNatMap.from_pairs([(Id(0), "a"), (Id(2), "c")])

    def test_get(self):
        m = DenseNatMap(["a"])
        assert m.get(Id(0)) == "a"
        assert m.get(Id(1)) is None

    def test_iter_yields_ids(self):
        m = DenseNatMap(["a", "b"])
        assert list(m) == [(Id(0), "a"), (Id(1), "b")]

    def test_value_semantics(self):
        assert DenseNatMap(["a"]) == DenseNatMap(["a"])
        assert hash(DenseNatMap(["a"])) == hash(DenseNatMap(["a"]))
        assert DenseNatMap(["a"]) != DenseNatMap(["b"])
        assert stable_fingerprint(DenseNatMap(["a"])) \
            == stable_fingerprint(DenseNatMap(["a"]))

    def test_rewrite_reindexes_keys_and_values(self):
        # plan sorting ['B', 'A'] swaps ids 0 and 1; values that are Ids
        # are themselves rewritten (densenatmap.rs:209-223)
        m = DenseNatMap(["B", "A"])
        plan = RewritePlan.from_values_to_sort(["B", "A"])
        assert m.rewrite(plan) == DenseNatMap(["A", "B"])
        # keys AND values both permute, so a swap map is a fixed point
        ids = DenseNatMap([Id(1), Id(0)])
        assert ids.rewrite(plan) == DenseNatMap([Id(1), Id(0)])
        ids2 = DenseNatMap([Id(0), Id(0)])
        assert ids2.rewrite(plan) == DenseNatMap([Id(1), Id(1)])


class TestVectorClock:
    def test_equality_ignores_trailing_zeros(self):
        assert VectorClock() == VectorClock([0, 0])
        assert VectorClock([1, 2]) == VectorClock([1, 2, 0])
        assert VectorClock([1, 2]) != VectorClock([1, 2, 3])

    def test_hash_ignores_trailing_zeros(self):
        assert hash(VectorClock([1, 0])) == hash(VectorClock([1]))
        assert stable_fingerprint(VectorClock([1, 0])) \
            == stable_fingerprint(VectorClock([1]))

    def test_incremented_grows(self):
        c = VectorClock().incremented(2)
        assert c == VectorClock([0, 0, 1])
        assert c.incremented(0) == VectorClock([1, 0, 1])

    def test_merge_max(self):
        a = VectorClock([1, 5])
        b = VectorClock([2, 3, 4])
        assert VectorClock.merge_max(a, b) == VectorClock([2, 5, 4])

    def test_partial_order(self):
        assert VectorClock([1, 2]) < VectorClock([1, 3])
        assert VectorClock([1, 3]) > VectorClock([1, 2])
        assert VectorClock([1, 2]) <= VectorClock([1, 2, 0])
        assert VectorClock([1, 2]) >= VectorClock([1, 2])
        # incomparable: neither <= nor >=
        a, b = VectorClock([1, 2, 4]), VectorClock([1, 3, 0])
        assert not a <= b and not a >= b and not a < b and not a > b

    def test_display(self):
        assert str(VectorClock([1, 2, 3, 4])) == "<1, 2, 3, 4, ...>"
        assert str(VectorClock()) == "<...>"
        assert str(VectorClock([0])) == "<0, ...>"
