"""Flight recorder, live streaming, device-time attribution, and the
bench-trend gate (the PR-8 observability layer).

The load-bearing guarantees:

* a run that dies with NO trace configured still leaves a schema-valid
  JSONL postmortem artifact (the flight recorder's whole point);
* the Explorer's ``/.events`` SSE stream and ``tools/watch.py`` render
  a live run without perturbing it (a slow client drops, never blocks);
* ``device_s``/``xfer_s`` split the old host-side sync conflation;
* ``tools/bench_history.py`` flags the BENCH_r05-style empty artifact
  and synthetic regressions machine-readably.
"""

import io
import json
import os
import sys

import pytest

from stateright_tpu.obs import (EVENT_SCHEMA, FlightRecorder, GLOSSARY,
                                validate_event)

pytestmark = pytest.mark.obs

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
_DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _twopc(n=3, **opts):
    from stateright_tpu.models.twopc import TwoPhaseSys
    return TwoPhaseSys(n).checker().tpu_options(
        capacity=1 << 12, race=False, **opts)


def _unavailable_hook(chunk, shards=None):
    raise RuntimeError(
        "UNAVAILABLE: injected transient backend fault (flight test)")


# --- the ring itself -------------------------------------------------------

class TestFlightRecorder:
    def test_ring_bounds_and_counters(self):
        rec = FlightRecorder(limit=16)
        for i in range(40):
            rec.record({"t": i, "ev": "compile", "engine": "E",
                        "reason": "x"})
        snap = rec.snapshot()
        assert len(snap) == 16
        assert snap[0]["t"] == 24  # oldest surviving
        assert rec.recorded == 40
        assert rec.dropped == 24

    def test_dump_roundtrip(self, tmp_path):
        rec = FlightRecorder()
        rec.record({"t": 0.0, "ev": "grow", "engine": "E",
                    "capacity": 8})
        path = tmp_path / "flight.jsonl"
        assert rec.dump(path) == 1
        evs = [json.loads(l) for l in path.read_text().splitlines()]
        assert evs[0]["capacity"] == 8
        validate_event(evs[0])


# --- zero-config crash artifacts -------------------------------------------

class TestFlightArtifacts:
    def test_single_chip_crash_leaves_artifact(self, tmp_path):
        """No trace configured; the engine dies on an injected
        transient fault — the artifact lands, validates against the
        schema, and trace_report --validate accepts it."""
        path = tmp_path / "boom.flight.jsonl"
        ck = _twopc(fault_hook=_unavailable_hook,
                    flight_path=str(path)).spawn_tpu()
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            ck.join()
        assert ck.flight_path() == str(path)
        assert ck.profile().get("recorder_dumps", 0) >= 1
        evs = [json.loads(l) for l in path.read_text().splitlines()]
        for ev in evs:
            validate_event(ev)
        kinds = [e["ev"] for e in evs]
        assert "run_start" in kinds and "error" in kinds
        assert "recorder_dump" in kinds  # the artifact names itself
        trace_report = _tool("trace_report")
        assert trace_report.main([str(path), "--validate"]) == 0

    def test_sharded_fault_exhausted_retries_artifact(self, tmp_path):
        """Acceptance: a sharded run killed by an injected transient
        fault (retry budget exhausted, ladder off) leaves a validating
        artifact with the retry burst in it — zero config beyond the
        pinned destination."""
        import jax
        import numpy as np
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:2]), ("shards",))
        path = tmp_path / "sharded.flight.jsonl"
        ck = _twopc(mesh=mesh, retries=1, backoff=0.0, degrade=False,
                    fault_hook=_unavailable_hook,
                    flight_path=str(path)).spawn_tpu()
        with pytest.raises(RuntimeError, match="transient device fault"):
            ck.join()
        evs = [json.loads(l) for l in path.read_text().splitlines()]
        for ev in evs:
            validate_event(ev)
        kinds = [e["ev"] for e in evs]
        assert "retry" in kinds and "error" in kinds
        # both triggers fired on the same stable path: the later dump
        # (error) superseded the exhausted-retries one in place
        dumps = [e for e in evs if e["ev"] == "recorder_dump"]
        assert dumps and dumps[0]["path"] == str(path)

    def test_flight_false_restores_null_trace(self):
        from stateright_tpu.obs import NULL_TRACE
        ck = _twopc(flight=False).spawn_tpu()
        assert ck._trace is NULL_TRACE
        ck.join()
        assert ck.flight_path() is None
        assert "recorder_dumps" not in ck.profile()

    def test_clean_run_dumps_nothing(self):
        ck = _twopc().spawn_tpu().join()
        assert ck.flight_path() is None
        assert ck.unique_state_count() == 288  # recorder changes nothing


# --- device-time attribution -----------------------------------------------

class TestDeviceTime:
    def test_device_xfer_split_rides_profile_and_chunks(self):
        events = []
        ck = _twopc(trace=events).spawn_tpu().join()
        prof = ck.profile()
        assert prof.get("device_s", -1) >= 0.0
        assert prof.get("xfer_s", -1) >= 0.0
        assert "device_s" in GLOSSARY and "xfer_s" in GLOSSARY
        chunk = [e for e in events if e["ev"] == "chunk"][-1]
        assert chunk["device_s"] >= 0.0
        assert chunk["xfer_s"] >= 0.0
        # the split partitions (a slice of) the old conflated stall:
        # both components are bounded by the run's wall time
        assert prof["device_s"] <= prof["search"] + 1.0

    @pytest.mark.slow  # the profiler session costs ~10s on CPU
    def test_profile_dir_capture_smoke(self, tmp_path):
        # jax.profiler capture is best-effort (never kills the run);
        # on the CPU backend it should produce a trace directory
        prof_dir = tmp_path / "jaxprof"
        ck = _twopc(profile_dir=str(prof_dir)).spawn_tpu().join()
        assert ck.unique_state_count() == 288


# --- live streaming: SSE + watch console -----------------------------------

class TestLiveStreaming:
    def test_events_sse_and_metrics_history(self):
        import urllib.request

        from stateright_tpu.checker.explorer import serve
        from stateright_tpu.models.twopc import TwoPhaseSys
        checker, server = serve(TwoPhaseSys(3).checker(),
                                ("127.0.0.1", 0), block=False)
        host, port = server.server_address
        try:
            checker.join()
            # flight-recorder backlog replays even post-done, so a
            # late client still reads the whole run
            with urllib.request.urlopen(
                    f"http://{host}:{port}/.events", timeout=30) as r:
                assert r.headers["Content-Type"].startswith(
                    "text/event-stream")
                body = r.read().decode()
            evs = [json.loads(l[len("data:"):])
                   for l in body.splitlines() if l.startswith("data:")]
            kinds = [e["ev"] for e in evs]
            assert kinds[0] == "run_start" and "done" in kinds
            for ev in evs:
                validate_event(ev)
            with urllib.request.urlopen(
                    f"http://{host}:{port}/.metrics?history",
                    timeout=30) as r:
                hist = json.loads(r.read())
            assert hist["samples"], "sampler recorded nothing"
            assert "unique_state_count" in hist["samples"][0]
            assert "wall" in hist["samples"][0]
        finally:
            server.shutdown()
            server.server_close()

    def test_watch_renders_committed_fixture(self, capsys):
        watch = _tool("watch")
        fixture = os.path.join(_DATA, "trace_fixture.jsonl")
        assert watch.main([fixture, "--once"]) == 0
        out = capsys.readouterr().out
        assert "uniq/s" in out            # chunk throughput
        assert "dedup=" in out            # dedup hit-rate
        assert "retry" in out             # the resilience event
        assert "== done" in out

    def test_watch_attached_to_live_run(self):
        """Acceptance: watch.py attached to a live (faulted, recovered)
        run renders chunk throughput, dedup hit-rate, and a resilience
        event before the run completes."""
        watch = _tool("watch")
        state = {"fired": False}

        def hook(chunk):
            if chunk >= 1 and not state["fired"]:
                state["fired"] = True
                raise RuntimeError("UNAVAILABLE: injected (watch test)")

        ck = _twopc(4, chunk_steps=4, retries=2, backoff=0.0,
                    retry_seed=0, fault_hook=hook).spawn_tpu()
        buf = io.StringIO()
        console = watch.attach(ck, out=buf)
        ck.join()
        out = buf.getvalue()
        assert console.rendered_progress >= 1
        assert "uniq/s" in out and "dedup=" in out
        assert "retry" in out
        assert "== done" in out


# --- bench-trend gate ------------------------------------------------------

class TestBenchHistory:
    def test_flags_real_r05_empty_artifact(self, capsys):
        bench_history = _tool("bench_history")
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        report = bench_history.build_report(
            [os.path.join(root, f) for f in sorted(os.listdir(root))
             if f.startswith("BENCH_") and f.endswith(".json")])
        empty = [f for f in report["flags"]
                 if f["kind"] == "empty_artifact"]
        assert any(f["round"] == "r05" for f in empty), report["flags"]
        # markdown renders without blowing up
        out = io.StringIO()
        bench_history.render_markdown(report, out)
        assert "empty_artifact" in out.getvalue()
        # --check turns flags into a failing exit code (the gate)
        assert bench_history.main([root, "--check"]) == 1
        capsys.readouterr()

    def test_flags_synthetic_regression(self, tmp_path):
        bench_history = _tool("bench_history")

        def art(name, rows, value=100.0):
            tail = "\n".join(json.dumps(r) for r in rows)
            (tmp_path / name).write_text(json.dumps({
                "n": 1, "rc": 0, "tail": tail,
                "parsed": {"metric": "m", "value": value,
                           "unit": "uniq/s", "backend": "tpu"}}))

        row = {"workload": "tpu 2pc7 full 296448", "unit": "uniq/s",
               "uniq": 1, "gen": 2, "gen_per_uniq": 2.0, "fused": False,
               "metrics": {}}
        art("BENCH_r01.json", [dict(row, best=1000.0)], value=100.0)
        art("BENCH_r02.json", [dict(row, best=400.0),
                               {"workload": "extra", "error": "boom"}],
            value=95.0)
        report = bench_history.build_report(
            [str(tmp_path / "BENCH_r01.json"),
             str(tmp_path / "BENCH_r02.json")])
        kinds = {f["kind"] for f in report["flags"]}
        assert "regression" in kinds, report["flags"]
        assert "workload_error" in kinds
        reg = [f for f in report["flags"] if f["kind"] == "regression"][0]
        assert reg["workload"] == "tpu 2pc7"
        assert reg["drop"] == pytest.approx(0.6)
        # contract value within threshold: no flag for it
        assert not any(f.get("workload") == bench_history.CONTRACT
                       for f in report["flags"]
                       if f["kind"] == "regression")

    def test_committed_artifacts_pass_gate(self, capsys):
        # THE tier-1 bench-trend gate (CI/tooling satellite): the
        # committed BENCH_*.json set must be clean apart from the
        # ACKNOWLEDGED r05 empty artifact (the round-5 rc=1 hole this
        # tool exists to catch). A new empty/partial/regressed artifact
        # in a future round fails the suite right here.
        bench_history = _tool("bench_history")
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        assert bench_history.main(
            [root, "--check", "--allow", "empty_artifact:r05"]) == 0
        out = capsys.readouterr().out
        assert "(allowed)" in out  # still reported, just not fatal

    def test_allow_does_not_mask_new_flags(self, tmp_path, capsys):
        bench_history = _tool("bench_history")
        (tmp_path / "BENCH_r05.json").write_text(json.dumps(
            {"n": 1, "rc": 1, "tail": "", "parsed": None}))
        (tmp_path / "BENCH_r07.json").write_text(json.dumps(
            {"n": 1, "rc": 1, "tail": "", "parsed": None}))
        # the acknowledged r05 alone passes; a NEW empty r07 still fails
        assert bench_history.main(
            [str(tmp_path), "--check", "--allow",
             "empty_artifact:r05,empty_artifact:r06"]) == 1
        capsys.readouterr()

    def test_spilled_tag_rides_trend_and_flags(self, tmp_path):
        # a primary metric that survived its HBM budget via host-tier
        # spills must surface in the trend table tags and as a flag —
        # a spilled rate is not comparable to an all-HBM rate
        bench_history = _tool("bench_history")
        row = {"workload": "tpu 2pc7 full 296448", "best": 900.0,
               "unit": "uniq/s", "uniq": 1, "gen": 2,
               "gen_per_uniq": 2.0, "fused": False, "spilled": True,
               "metrics": {"spills": 3, "host_tier_keys": 123}}
        (tmp_path / "BENCH_r09.json").write_text(json.dumps({
            "n": 1, "rc": 0, "tail": json.dumps(row),
            "parsed": {"metric": "m", "value": 100.0, "unit": "uniq/s",
                       "backend": "tpu", "spilled": True,
                       "host_tier_keys": 123}}))
        report = bench_history.build_report(
            [str(tmp_path / "BENCH_r09.json")])
        wl = report["rounds"][0]["workloads"]
        assert "spilled" in wl["tpu 2pc7"]["tags"]
        assert "spilled" in wl[bench_history.CONTRACT]["tags"]
        spilled = [f for f in report["flags"] if f["kind"] == "spilled"]
        assert spilled and "123" in spilled[0]["detail"]

    def test_normalization_keeps_model_sizes(self):
        bench_history = _tool("bench_history")
        norm = bench_history.normalize_workload
        assert norm("tpu 2pc7 full 296448") == "tpu 2pc7"
        assert norm("tpu 2pc10 capped 1M-gen") == "tpu 2pc10"
        assert norm("tpu paxos3 capped 500k") \
            == norm("tpu paxos3 capped 40000")
        assert norm("tpu 2pc7 full 296448") != norm(
            "tpu 2pc10 capped 1M-gen")
