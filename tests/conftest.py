"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path; benchmarks run on the real chip).

Must run before any ``import jax`` — pytest imports conftest first.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
