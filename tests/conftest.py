"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path; benchmarks run on the real chip).

Must run before any ``import jax`` — pytest imports conftest first.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's sitecustomize force-registers an `axon` TPU PJRT plugin
# and overrides the jax_platforms *config* (not just the env var) to
# "axon,cpu"; initializing it opens a tunnel to the real chip, which tests
# must never depend on. Re-override the config back to cpu before any
# backend initialization.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running oracle pins (deselect with -m 'not slow')")
    config.addinivalue_line(
        "markers", "faults: fault-injection coverage (crash–restart, lossy "
        "networks, corrupt checkpoints); select with -m faults. Fast "
        "configs run in tier-1 by default.")
    config.addinivalue_line(
        "markers", "obs: observability coverage (run-trace schema, "
        "trace-on/off parity, metrics registry, /.metrics); select "
        "with -m obs. Fast configs run in tier-1 by default.")
