"""Crash–restart fault injection across the host and device engines.

The fault class real consensus protocols are defined against: a ``Crash``
wipes an actor's volatile state (only its durable projection survives)
and cancels its timer; a ``Restart`` rejoins it. Coverage:

* host ``ActorModel`` semantics (budget, down-actor delivery suppression,
  timer cancellation, ``durable()``/``on_restart()`` hooks);
* the packed device lanes agree with the host bit-for-bit
  (:func:`validate_packed_model` — successor-multiset equality per state)
  alone and composed with Timeout and lossy-Drop lanes;
* engine parity on the acceptance workloads: the write-once register and
  a small paxos config enumerate identical state counts and identical
  discoveries on host BFS and ``spawn_tpu``; the volatile write-once
  variant is *caught* losing an acknowledged write on both engines, with
  a replayable counterexample path containing the Crash/Restart actions.
"""

import pytest

from stateright_tpu.actor import ActorModel, Id, Out
from stateright_tpu.actor.core import Actor, Down
from stateright_tpu.actor.model import Crash, Deliver, Restart, Timeout
from stateright_tpu.actor.network import Network
from stateright_tpu.actor.write_once_register import (
    Get, GetOk, Put, PutFail, PutOk, WORegisterClient, WORegisterServer,
    record_invocations, record_returns)
from stateright_tpu.core import Expectation
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.write_once_register import WORegister

pytestmark = pytest.mark.faults


class VolatileWOServer(Actor):
    """Unreplicated write-once server keeping its value in volatile
    memory only — the deliberately buggy variant."""

    def on_start(self, id: Id, o: Out):
        return None  # unwritten

    def on_msg(self, id: Id, state, src: Id, msg, o: Out):
        if isinstance(msg, Put):
            if state is None or state == msg.value:
                o.send(src, PutOk(msg.request_id))
                return msg.value if state is None else None
            o.send(src, PutFail(msg.request_id))
            return None
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state))
            return None
        return None


class DurableWOServer(VolatileWOServer):
    """The fixed variant: the register value is on stable storage."""

    def durable(self, id: Id, state):
        return state

    def on_restart(self, id: Id, durable, o: Out):
        return durable


def wo_model(server: Actor, client_count: int = 1) -> ActorModel:
    model = ActorModel(cfg=None,
                       init_history=LinearizabilityTester(WORegister()))
    model.actor(WORegisterServer(server))
    for _ in range(client_count):
        model.actor(WORegisterClient(put_count=1, server_count=1))
    return (model
            .init_network(Network.new_unordered_nonduplicating())
            .property(Expectation.ALWAYS, "linearizable",
                      lambda _, state:
                      state.history.serialized_history() is not None)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations))


class TestHostSemantics:
    def test_crash_wipes_volatile_state_and_timer(self):
        class TimerHolder(Actor):
            def on_start(self, id, o):
                o.set_timer((0.0, 0.0))
                return 7

        model = ActorModel().actor(TimerHolder()).crash_restart(1)
        init = model.init_states()[0]
        assert init.is_timer_set == (True,) and init.crashes == (0,)
        crashed = model.next_state(init, Crash(Id(0)))
        assert crashed.actor_states == (Down(None),)
        assert crashed.is_timer_set == (False,)
        assert crashed.crashes == (1,)
        # the crash budget is spent: no further Crash action is offered
        actions = []
        model.actions(crashed, actions)
        assert actions == [Restart(Id(0))]

    def test_restart_reruns_on_start_by_default(self):
        class Sender(Actor):
            def on_start(self, id, o):
                o.send(Id(9), "hello")  # undeliverable: sits in network
                return "up"

        model = (ActorModel().actor(Sender())
                 .init_network(Network.new_unordered_nonduplicating())
                 .crash_restart(1))
        init = model.init_states()[0]
        crashed = model.next_state(init, Crash(Id(0)))
        restarted = model.next_state(crashed, Restart(Id(0)))
        assert restarted.actor_states == ("up",)
        # on_start ran again: its send was re-emitted (the multiset
        # network counts both copies)
        assert len(restarted.network) == 2

    def test_down_actor_takes_no_deliveries_or_timeouts(self):
        model = wo_model(VolatileWOServer()).crash_restart(1, actors=[0])
        init = model.init_states()[0]  # client's Put is in flight
        crashed = model.next_state(init, Crash(Id(0)))
        actions = []
        model.actions(crashed, actions)
        assert not any(isinstance(a, Deliver) and int(a.dst) == 0
                       for a in actions)
        assert not any(isinstance(a, Timeout) for a in actions)
        # the Put waits in the network rather than being lost
        assert len(crashed.network) == 1
        # and defensive next_state agrees with the action filter
        env = next(iter(crashed.network.iter_deliverable()))
        assert model.next_state(
            crashed, Deliver(src=env.src, dst=env.dst, msg=env.msg)) \
            is None

    def test_crashable_restricts_eligible_actors(self):
        model = wo_model(VolatileWOServer()).crash_restart(1, actors=[0])
        init = model.init_states()[0]
        actions = []
        model.actions(init, actions)
        crashes = [a for a in actions if isinstance(a, Crash)]
        assert crashes == [Crash(Id(0))]

    def test_no_crash_config_is_bit_identical(self):
        # states of an uninjected model keep crashes=None, so existing
        # fingerprints (and checkpoint identity) are unchanged
        model = wo_model(VolatileWOServer())
        init = model.init_states()[0]
        assert init.crashes is None
        actions = []
        model.actions(init, actions)
        assert not any(isinstance(a, (Crash, Restart)) for a in actions)


class TestHostWriteOnceRegister:
    def test_volatile_server_caught_losing_write(self):
        model = wo_model(VolatileWOServer()).crash_restart(1, actors=[0])
        checker = model.checker().spawn_bfs().join()
        path = checker.assert_any_discovery("linearizable")
        actions = path.into_actions()
        assert any(isinstance(a, Crash) for a in actions)
        assert any(isinstance(a, Restart) for a in actions)
        assert path.last_state().history.serialized_history() is None

    def test_durable_server_safe_under_crashes(self):
        model = wo_model(DurableWOServer()).crash_restart(1, actors=[0])
        checker = model.checker().spawn_bfs().join()
        checker.assert_properties()

    def test_bfs_dfs_parity_under_crashes(self):
        bfs = (wo_model(DurableWOServer()).crash_restart(1, actors=[0])
               .checker().spawn_bfs().join())
        dfs = (wo_model(DurableWOServer()).crash_restart(1, actors=[0])
               .checker().spawn_dfs().join())
        assert (bfs.generated_fingerprints()
                == dfs.generated_fingerprints())


class TestPackedContract:
    """Device crash/restart lanes agree with the host model bit-for-bit
    (successor multisets, fingerprints, properties) — alone and composed
    with the Timeout and lossy-Drop lane families."""

    def test_write_once_durable(self):
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce
        from stateright_tpu.models.packed import validate_packed_model

        m = PackedWriteOnce(1, durable=True).crash_restart(1, actors=[0])
        assert validate_packed_model(m, max_states=100) == 15

    def test_write_once_volatile(self):
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce
        from stateright_tpu.models.packed import validate_packed_model

        m = PackedWriteOnce(1, durable=False).crash_restart(1,
                                                            actors=[0])
        assert validate_packed_model(m, max_states=100) == 21

    def test_crash_composes_with_timeout_lanes(self):
        from stateright_tpu.actor.test_util import PackedTimerCount
        from stateright_tpu.models.packed import validate_packed_model

        m = PackedTimerCount(2, 2).crash_restart(2)
        assert validate_packed_model(m, max_states=200) == 49

    def test_crash_composes_with_lossy_drop_lanes(self):
        from stateright_tpu.actor.test_util import PackedPingPong
        from stateright_tpu.models.packed import validate_packed_model

        m = PackedPingPong(2, duplicating=False)
        m.lossy_network(True).crash_restart(1)
        validate_packed_model(m, max_states=500)

    def test_paxos_contract_prefix(self):
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        from stateright_tpu.models.packed import validate_packed_model

        m = PackedPaxos(1).crash_restart(1, actors=[0, 1, 2])
        assert validate_packed_model(m, max_states=600) == 600


class TestEngineParity:
    """Acceptance: host BFS and the device engine enumerate identical
    state counts and identical discoveries under crash_restart(1)."""

    def test_write_once_durable_counts_and_discoveries(self):
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce

        def mk():
            return PackedWriteOnce(2, durable=True).crash_restart(
                1, actors=[0])

        host = mk().checker().spawn_bfs().join()
        dev = (mk().checker().tpu_options(race=False, capacity=1 << 12)
               .spawn_tpu().join())
        assert host.unique_state_count() == dev.unique_state_count() == 51
        assert (host.generated_fingerprints()
                == dev.generated_fingerprints())
        assert (set(host.discoveries()) == set(dev.discoveries())
                == {"value chosen"})
        host.assert_properties()
        dev.assert_properties()

    def test_write_once_volatile_caught_on_both_engines(self):
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce

        def mk():
            return PackedWriteOnce(2, durable=False).crash_restart(
                1, actors=[0])

        model = mk()
        host = model.checker().spawn_bfs().join()
        dev_model = mk()
        dev = (dev_model.checker().tpu_options(race=False,
                                               capacity=1 << 12)
               .spawn_tpu().join())
        for checker, m in ((host, model), (dev, dev_model)):
            path = checker.assert_any_discovery("linearizable")
            actions = path.into_actions()  # replay validates the trace
            assert any(isinstance(a, Crash) for a in actions)
            assert path.last_state().history.serialized_history() is None

    @pytest.mark.slow  # ~24s warm: paxos parity across both engines
    def test_paxos_small_config_parity(self):
        from stateright_tpu.examples.paxos_packed import PackedPaxos

        def mk():
            return PackedPaxos(1).crash_restart(1, actors=[0, 1, 2])

        host = mk().checker().spawn_bfs().join()
        dev = (mk().checker().tpu_options(race=False, capacity=1 << 15)
               .spawn_tpu().join())
        assert (host.unique_state_count() == dev.unique_state_count()
                == 7155)
        assert (host.generated_fingerprints()
                == dev.generated_fingerprints())
        assert (set(host.discoveries()) == set(dev.discoveries())
                == {"value chosen"})
        host.assert_properties()
        dev.assert_properties()


class TestDeviceGuards:
    def test_ordered_network_crash_is_host_only(self):
        from stateright_tpu.examples.abd_packed import PackedAbd

        m = PackedAbd(1, ordered=True).crash_restart(1, actors=[0, 1])
        with pytest.raises(NotImplementedError, match="spawn_bfs"):
            m.max_actions

    def test_too_many_crashes_rejected(self):
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce

        with pytest.raises(NotImplementedError, match="k <= 7"):
            PackedWriteOnce(1).crash_restart(8, actors=[0])


class TestLossyOrderedFallback:
    """The device engine's lossy-ordered dead end names the working host
    fallback, and the host path really does check the same model."""

    def test_error_names_host_fallback(self):
        from stateright_tpu.examples.abd_packed import PackedAbd

        m = PackedAbd(1, ordered=True).lossy_network(True)
        with pytest.raises(NotImplementedError,
                           match="spawn_bfs.*spawn_dfs"):
            m.max_actions

    def test_host_engines_check_it_with_identical_discoveries(self):
        from stateright_tpu.examples.abd_packed import PackedAbd

        def mk():
            return (PackedAbd(1, ordered=True, channel_depth=2,
                              net_capacity=8)
                    .lossy_network(True))

        bfs = mk().checker().spawn_bfs().join()
        dfs = mk().checker().spawn_dfs().join()
        assert (bfs.generated_fingerprints()
                == dfs.generated_fingerprints())
        assert set(bfs.discoveries()) == set(dfs.discoveries())
