"""Host engine behavior tests.

Ports of the reference's engine tests: BFS/DFS visitation order and exact
unique-state counts (`/root/reference/src/checker/bfs.rs:350-394`,
`dfs.rs:351-392`), the eventually-property semantics pins including the
documented false-negative (`src/checker.rs:350-415`), path reconstruction
(`src/checker.rs:417-442`, `src/checker/path.rs:189-225`), the golden report
format (`src/checker.rs:444-513`), and DFS symmetry reduction
(`dfs.rs:394-483`).
"""

import io

import pytest

from stateright_tpu import (
    Model,
    NondeterministicModelError,
    Path,
    PathRecorder,
    Property,
    RewritePlan,
    StateRecorder,
    fingerprint,
)
from stateright_tpu.models import DGraph, FnModel, Guess, LinearEquation


# --- eventually-property semantics (src/checker.rs:350-415) ---------------

def eventually_odd():
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def test_eventually_can_validate():
    (DGraph.with_property(eventually_odd())
     .with_path([1])
     .with_path([2, 3])
     .with_path([2, 6, 7])
     .with_path([4, 9, 10])
     .check().assert_properties())
    DGraph.with_property(eventually_odd()).with_path([1]).check().assert_properties()
    DGraph.with_property(eventually_odd()).with_path([2, 3]).check().assert_properties()
    DGraph.with_property(eventually_odd()).with_path([2, 6, 7]).check().assert_properties()
    DGraph.with_property(eventually_odd()).with_path([4, 9, 10]).check().assert_properties()


def test_eventually_can_discover_counterexample():
    c = (DGraph.with_property(eventually_odd())
         .with_path([0, 1])
         .with_path([0, 2])
         .check())
    assert c.discovery("odd").into_states() == [0, 2]

    c = (DGraph.with_property(eventually_odd())
         .with_path([0, 1])
         .with_path([2, 4])
         .check())
    assert c.discovery("odd").into_states() == [2, 4]

    c = (DGraph.with_property(eventually_odd())
         .with_path([0, 1, 4, 6])
         .with_path([2, 4, 8])
         .check())
    assert c.discovery("odd").into_states() == [2, 4, 6]


def test_fixme_can_miss_counterexample_when_revisiting_a_state():
    # Replicates the reference's accepted unsoundness (checker.rs:402-414):
    # a cycle or a DAG rejoin is not treated as terminal, so these
    # counterexamples are (incorrectly, but compatibly) missed.
    c = DGraph.with_property(eventually_odd()).with_path([0, 2, 4, 2]).check()
    assert c.discovery("odd") is None
    c = (DGraph.with_property(eventually_odd())
         .with_path([0, 2, 4])
         .with_path([1, 4, 6])
         .check())
    assert c.discovery("odd") is None


# --- BFS engine (bfs.rs:344-395) ------------------------------------------

def test_bfs_visits_states_in_bfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    (LinearEquation(2, 10, 14).checker()
     .visitor(recorder)
     .spawn_bfs().join())
    assert accessor() == [
        (0, 0),
        (1, 0), (0, 1),
        (2, 0), (1, 1), (0, 2),
        (3, 0), (2, 1),
    ]


def test_bfs_can_complete_by_enumerating_all_states():
    checker = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_bfs_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 12
    assert checker.discovery("solvable").into_actions() == [
        Guess.INCREASE_X, Guess.INCREASE_X, Guess.INCREASE_Y,
    ]
    checker.assert_discovery("solvable", [Guess.INCREASE_Y] * 27)


# --- DFS engine (dfs.rs:345-484) ------------------------------------------

def test_dfs_visits_states_in_dfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    (LinearEquation(2, 10, 14).checker()
     .visitor(recorder)
     .spawn_dfs().join())
    assert accessor() == [(0, y) for y in range(28)]


def test_dfs_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 55
    assert checker.discovery("solvable").into_actions() == \
        [Guess.INCREASE_Y] * 27
    checker.assert_discovery("solvable", [
        Guess.INCREASE_X, Guess.INCREASE_Y, Guess.INCREASE_X,
    ])


def test_dfs_can_apply_symmetry_reduction():
    # Port of dfs.rs:394-483 including the enqueue-original-state subtlety:
    # process states advance Loading -> Running -> (Paused <-> Running), and
    # the representative sorts them, so canonicalized successors may have no
    # valid path extension — the DFS must keep extending the original.
    # Sort order mirrors the Rust enum: Paused < Loading < Running.
    PAUSED, LOADING, RUNNING = 0, 1, 2

    class Sys(Model):
        def init_states(self):
            return [(LOADING, LOADING)]

        def actions(self, state, actions):
            actions.extend([0, 1])

        def next_state(self, state, action):
            procs = list(state)
            procs[action] = {LOADING: RUNNING,
                             RUNNING: PAUSED,
                             PAUSED: RUNNING}[procs[action]]
            return tuple(procs)

        def properties(self):
            return [
                Property.always("visit all states", lambda _, s: True),
                Property.sometimes(
                    "a process pauses",
                    lambda _, s: s[0] == PAUSED or s[1] == PAUSED),
            ]

    def representative(state):
        plan = RewritePlan.from_values_to_sort(state)
        return tuple(plan.reindex(state))

    checker = Sys().checker().spawn_dfs().join()
    assert checker.unique_state_count() == 9
    checker = Sys().checker().spawn_bfs().join()
    assert checker.unique_state_count() == 9

    # 6 states with symmetry reduction; PathRecorder raises on invalid paths.
    visitor, _ = PathRecorder.new_with_accessor()
    checker = (Sys().checker().symmetry_fn(representative)
               .visitor(visitor).spawn_dfs().join())
    assert checker.unique_state_count() == 6


# --- path reconstruction (checker.rs:417-442, path.rs:189-225) -------------

def test_can_build_path_from_fingerprints():
    model = LinearEquation(2, 10, 14)
    fps = [fingerprint((0, 0)), fingerprint((0, 1)),
           fingerprint((1, 1)), fingerprint((2, 1))]
    path = Path.from_fingerprints(model, fps)
    assert path.last_state() == (2, 1)
    assert path.last_state() == Path.final_state(model, fps)


def test_raises_if_unable_to_reconstruct_init_state():
    def fn(prev, out):
        if prev is None:
            out.append("UNEXPECTED")
    with pytest.raises(NondeterministicModelError):
        Path.from_fingerprints(FnModel(fn), [fingerprint("expected")])


def test_raises_if_unable_to_reconstruct_next_state():
    def fn(prev, out):
        out.append("expected" if prev is None else "UNEXPECTED")
    with pytest.raises(NondeterministicModelError):
        Path.from_fingerprints(
            FnModel(fn), [fingerprint("expected"), fingerprint("expected")])


# --- report golden output (checker.rs:444-513) -----------------------------

def test_report_includes_property_names_and_paths():
    w = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_bfs().report(w)
    output = w.getvalue()
    assert output.startswith(
        "Checking. states=1, unique=1\n"
        "Done. states=15, unique=12, sec="), output
    assert output.endswith(
        'Discovered "solvable" example Path[3]:\n'
        "- IncreaseX\n"
        "- IncreaseX\n"
        "- IncreaseY\n"), output

    w = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_dfs().report(w)
    output = w.getvalue()
    assert output.startswith(
        "Checking. states=1, unique=1\n"
        "Done. states=55, unique=55, sec="), output
    assert output.endswith(
        'Discovered "solvable" example Path[27]:\n'
        + "- IncreaseY\n" * 27), output


# --- misc ------------------------------------------------------------------

def test_binary_clock():
    from stateright_tpu.models import BinaryClock
    checker = BinaryClock().checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 2


def test_target_state_count():
    checker = (LinearEquation(2, 4, 7).checker()
               .target_state_count(100).spawn_bfs().join())
    assert checker.state_count() >= 100
    assert checker.unique_state_count() < 256 * 256


def test_rewrite_plan_from_sort_sorts():
    # rewrite_plan.rs:121-131
    original = ["B", "D", "C", "A"]
    plan = RewritePlan.from_values_to_sort(original)
    assert plan.reindex(original) == ["A", "B", "C", "D"]
    assert plan.reindex([1, 3, 2, 0]) == [0, 1, 2, 3]


def test_rewrite_plan_can_reindex():
    # rewrite_plan.rs:134-154
    swap_first_and_last = RewritePlan.from_values_to_sort([2, 1, 0])
    rotate_left = RewritePlan.from_values_to_sort([2, 0, 1])
    original = ["A", "B", "C"]
    assert swap_first_and_last.reindex(original) == ["C", "B", "A"]
    assert rotate_left.reindex(original) == ["B", "C", "A"]
