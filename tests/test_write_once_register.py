"""Write-once-register actor adapter tests
(`/root/reference/src/actor/write_once_register.rs`): the protocol
vocabulary, the keep-going-past-PutFail client, the history hooks, and the
rewrite support that lets WO-register systems combine consistency testing
with symmetry reduction."""

from typing import Any, Optional

from stateright_tpu.actor import ActorModel, Id, Out
from stateright_tpu.actor.core import Actor
from stateright_tpu.actor.network import Network
from stateright_tpu.actor.write_once_register import (
    Get, GetOk, Put, PutFail, PutOk, WORegisterClient, WORegisterServer,
    record_invocations, record_returns)
from stateright_tpu.core import Expectation
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.write_once_register import WORegister


class WOServer(Actor):
    """Unreplicated write-once server: first Put wins; a Put of a
    different value fails; same value re-succeeds (mirroring the
    WORegister spec semantics)."""

    def on_start(self, id: Id, o: Out) -> Optional[Any]:
        return None  # unwritten

    def on_msg(self, id: Id, state: Any, src: Id, msg: Any,
               o: Out) -> Optional[Any]:
        if isinstance(msg, Put):
            if state is None or state == msg.value:
                o.send(src, PutOk(msg.request_id))
                return msg.value if state is None else None
            o.send(src, PutFail(msg.request_id))
            return None
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state))
            return None
        return None


def wo_model(client_count: int) -> ActorModel:
    model = ActorModel(cfg=None,
                       init_history=LinearizabilityTester(WORegister()))
    model.actor(WORegisterServer(WOServer()))
    for _ in range(client_count):
        model.actor(WORegisterClient(put_count=1, server_count=1))
    return (model
            .init_network(Network.new_unordered_nonduplicating())
            .property(Expectation.ALWAYS, "linearizable",
                      lambda _, state:
                      state.history.serialized_history() is not None)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations))


class TestWORegisterAdapter:
    def test_single_client_linearizable(self):
        ck = wo_model(1).checker().spawn_bfs().join()
        ck.assert_properties()
        assert ck.unique_state_count() > 1

    def test_two_clients_conflicting_puts_linearizable(self):
        # clients write 'B' and 'Z' — one must fail; history with
        # WriteFail must still linearize against the WO spec
        ck = wo_model(2).checker().spawn_bfs().join()
        ck.assert_properties()

    def test_client_continues_after_put_fail(self):
        # drive the client FSM directly: PutFail advances like PutOk
        client = WORegisterClient(put_count=2, server_count=1)
        o = Out()
        st = client.on_start(Id(1), o)
        assert st.op_count == 1 and o  # first Put sent
        o = Out()
        st2 = client.on_msg(Id(1), st, Id(0), PutFail(st.awaiting), o)
        assert st2 is not None and st2.op_count == 2
        assert any(isinstance(c.msg, Put) for c in o)

    def test_symmetry_reduction_agrees(self):
        # the adapter's rewrite support: symmetry-reduced DFS reaches the
        # same verdicts with fewer (or equal) states
        model = wo_model(2)
        plain = model.checker().spawn_dfs().join()
        model2 = wo_model(2)
        sym = (model2.checker()
               .symmetry_fn(lambda s: s.representative())
               .spawn_dfs().join())
        assert sym.unique_state_count() <= plain.unique_state_count()
        plain.assert_properties()
        sym.assert_properties()
