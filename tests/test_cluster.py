"""Fleet mesh: multi-host ``jax.distributed`` checking over DCN
(stateright_tpu/cluster + the sharded engine on a global mesh).

The load-bearing guarantees:

* **cross-process parity** — a 2-process CPU mesh (launcher-spawned
  subprocesses, per-process device forcing like
  ``__graft_entry__.dryrun_multichip``) enumerates a fingerprint set
  and discovery list BIT-IDENTICAL (sha256 digest) to the same model
  on a single-process mesh;
* **cross-process resume** — the shard-agnostic checkpoint format now
  spans *process* boundaries: a checkpoint written by the 2-process
  mesh resumes on a single process (and vice versa, ``-m slow``) to
  the identical fingerprint set;
* **host rung** — on a multi-host mesh the degradation ladder drops a
  blamed chip's ENTIRE host (the surviving mesh never straddles the
  dead host), re-routing by ``owner_of(fp, D/2)`` exactly like the
  chip rung — bit-identical to an uninterrupted single-host run;
* **owner_of width guard** — the D <= 256 top-bit assumption
  (``checker/resilience.py`` SPILL_PREFIX_BITS nesting) raises with an
  actionable message instead of silently mis-routing;
* obs: ``mesh_init`` / ``host_join`` / ``host_drop`` are schema-valid
  and ``tools/trace_report.py`` renders the ``fleet:`` summary.
"""

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "mesh_launch.py")

#: pinned engine shapes shared with tests/test_service.py and
#: tests/test_resilience.py (persistent compile cache reuse)
OPTS = {"capacity": 1 << 12, "fmax": 64, "chunk_steps": 2}


def _digest(fps) -> str:
    fps = sorted(int(f) for f in fps)
    return hashlib.sha256("\n".join(map(str, fps)).encode()).hexdigest()


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("shards",))


def _run_launcher(out_dir, *extra, timeout=300):
    """Coordinator-mode tools/mesh_launch.py; returns rank 0's result."""
    cmd = [sys.executable, LAUNCH, "--procs", "2",
           "--devices-per-proc", "2", "--model", "twopc", "--args",
           "3", "--capacity", "4096", "--fmax", "64", "--chunk-steps",
           "2", "--out", str(out_dir)] + list(extra)
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    line = proc.stdout.strip().splitlines()[-1]
    return json.loads(line)


@pytest.fixture(scope="module")
def solo_2pc3():
    """The oracle: an uninterrupted single-chip run."""
    return (TwoPhaseSys(3).checker()
            .tpu_options(race=False, **OPTS).spawn_tpu().join())


# --- owner_of width guard ---------------------------------------------

class TestOwnerGuard:
    def test_owner_of_within_limit(self):
        from stateright_tpu.parallel.sharded import owner_of
        fp = 0xDEADBEEF12345678
        assert owner_of(fp, 1) == 0
        assert owner_of(fp, 256) == fp >> 56

    def test_owner_of_past_limit_raises_naming_the_width(self):
        from stateright_tpu.parallel.sharded import owner_of
        with pytest.raises(ValueError, match="256-shard limit"):
            owner_of(0x1, 512)
        with pytest.raises(ValueError, match="SPILL_PREFIX_BITS"):
            owner_of(0x1, 1 << 12)

    def test_limit_is_locked_to_the_spill_prefix(self):
        # the guard exists BECAUSE eviction ranges (top-8-bit
        # prefixes) must nest inside owner_of's top-bit routing; the
        # two constants must move in lockstep
        from stateright_tpu.checker.resilience import SPILL_PREFIX_BITS
        from stateright_tpu.parallel.sharded import MAX_MESH_SHARDS
        assert MAX_MESH_SHARDS == 1 << SPILL_PREFIX_BITS

    def test_chunk_build_guards_too(self):
        from stateright_tpu.parallel.sharded import _owner_bits
        assert _owner_bits(256) == 8
        with pytest.raises(ValueError, match="256"):
            _owner_bits(512)


# --- fleet mesh construction ------------------------------------------

class TestFleetMesh:
    def test_single_process_is_one_host(self):
        from stateright_tpu.cluster import fleet_mesh, mesh_hosts
        mesh = fleet_mesh(devices=jax.devices()[:4])
        assert mesh.shape["shards"] == 4
        assert set(mesh_hosts(mesh)) == {0}

    def test_host_map_trims_per_host_and_orders_host_major(self):
        # two simulated hosts of 3 devices each: per-host pow2 floor
        # is 2, so the fleet mesh is 4 wide and host-major
        from stateright_tpu.cluster import (device_host, fleet_mesh,
                                            mesh_hosts)
        devs = jax.devices()[:6]
        host_map = {d.id: ("a" if i < 3 else "b")
                    for i, d in enumerate(devs)}
        mesh = fleet_mesh(devices=devs, host_map=host_map)
        assert mesh.shape["shards"] == 4
        labels = mesh_hosts(mesh, host_map)
        assert labels == ["a", "a", "b", "b"]
        assert device_host(devs[0], host_map) == "a"
        assert device_host(devs[0]) == 0  # process_index fallback

    def test_pull_global_is_plain_device_get_on_one_process(self):
        from stateright_tpu.cluster import pull_global
        mesh = _mesh(2)
        import jax.numpy as jnp
        a, b = pull_global((jnp.arange(4), np.int32(7)), mesh)
        assert list(a) == [0, 1, 2, 3] and int(b) == 7


# --- the degradation ladder's host rung -------------------------------

class TestHostRung:
    @pytest.mark.faults
    def test_blamed_chip_drops_its_whole_host(self):
        # D=4 across two simulated hosts (a: devices 0,1 / b: 2,3); a
        # permanent fault blaming device 1 must drop ALL of host a —
        # the chip rung would keep {0, 2}, straddling the dead host —
        # and finish bit-identical to an uninterrupted single-host D=2
        # run. This is the service-facing acceptance: a D=4-across-2-
        # hosts run resumes on one host.
        devs = jax.devices()
        if len(devs) < 4:
            pytest.skip("need 4 devices")
        host_map = {d.id: ("a" if d.id < 2 else "b")
                    for d in devs[:4]}

        def hook(chunk, shards):
            if shards > 2:
                raise RuntimeError(
                    "UNAVAILABLE: device 1 fell off the mesh "
                    "(injected)")

        trace = []
        faulty = (TwoPhaseSys(3).checker()
                  .tpu_options(race=False, **OPTS, mesh=_mesh(4),
                               retries=1, backoff=0.0,
                               fault_hook=hook, host_map=host_map,
                               trace=trace)
                  .spawn_tpu().join())
        clean = (TwoPhaseSys(3).checker()
                 .tpu_options(race=False, **OPTS, mesh=_mesh(2))
                 .spawn_tpu().join())
        assert faulty.unique_state_count() == clean.unique_state_count()
        assert (faulty.generated_fingerprints()
                == clean.generated_fingerprints())
        assert set(faulty.discoveries()) == set(clean.discoveries())
        # the surviving mesh is host b, whole — never {0, 2}
        surv = sorted(d.id for d in faulty._mesh.devices.flat)
        assert surv == [2, 3]
        prof = faulty.profile()
        assert prof["degrades"] == 1
        assert prof["mesh_shards"] == 2
        assert prof["hosts"] == 1  # dropped from 2
        drops = [e for e in trace if e["ev"] == "host_drop"]
        assert len(drops) == 1 and drops[0]["host"] == "a"
        assert drops[0]["from_shards"] == 4
        assert drops[0]["to_shards"] == 2
        mesh_init = [e for e in trace if e["ev"] == "mesh_init"]
        assert mesh_init and mesh_init[0]["hosts"] == 2
        assert mesh_init[0]["procs"] == 1
        from stateright_tpu.obs import validate_event
        for ev in trace:
            validate_event(ev)


# --- 2-process CPU mesh: the acceptance pins --------------------------

class TestMultiProcess:
    def test_two_process_mesh_bit_identical_to_single_process(
            self, tmp_path, solo_2pc3):
        # launcher-spawned subprocesses, per-process CPU device
        # forcing; the all-to-all spans the process boundary — and the
        # fingerprint set + discovery list are pinned byte-identical
        # (sha256) to a single-process mesh AND the single-chip oracle
        result = _run_launcher(tmp_path / "fleet")
        assert result["procs"] == 2
        assert result["hosts"] == 2
        assert result["shards"] == 4
        single = (TwoPhaseSys(3).checker()
                  .tpu_options(race=False, **OPTS, mesh=_mesh(4))
                  .spawn_tpu().join())
        want = _digest(single.generated_fingerprints())
        assert result["fingerprints_sha256"] == want
        assert want == _digest(solo_2pc3.generated_fingerprints())
        assert result["unique"] == solo_2pc3.unique_state_count()
        assert (result["discoveries"]
                == sorted(solo_2pc3.discoveries()))
        # fleet trace: both ranks joined, mesh_init landed, schema OK
        from stateright_tpu.obs import validate_event
        with open(tmp_path / "fleet" / "fleet.jsonl") as f:
            fleet = [json.loads(line) for line in f if line.strip()]
        for ev in fleet:
            validate_event(ev)
        assert sorted(e["host"] for e in fleet
                      if e["ev"] == "host_join") == [0, 1]
        assert any(e["ev"] == "mesh_init" and e["procs"] == 2
                   for e in fleet)
        # rank 0's engine trace carries the DCN probe
        with open(tmp_path / "fleet" / "trace.jsonl") as f:
            trace = [json.loads(line) for line in f if line.strip()]
        for ev in trace:
            validate_event(ev)
        mi = [e for e in trace if e["ev"] == "mesh_init"]
        assert mi and mi[0]["procs"] == 2 and mi[0]["hosts"] == 2
        assert mi[0]["dcn_exchange_s"] is not None

    def test_trace_report_renders_fleet_summary(self, tmp_path):
        # reuses nothing: a tiny launcher round just for the renderer
        # would cost another fleet spawn, so render from a synthetic
        # trace carrying the real event shapes
        trace = tmp_path / "fleet.jsonl"
        evs = [
            {"t": 0.1, "ev": "host_join", "engine": "fleet", "host": 0},
            {"t": 0.2, "ev": "host_join", "engine": "fleet", "host": 1},
            {"t": 0.3, "ev": "mesh_init", "engine": "fleet",
             "shards": 4, "hosts": 2, "procs": 2,
             "dcn_exchange_s": 0.0021},
            {"t": 0.9, "ev": "host_drop", "engine": "fleet",
             "host": 1, "from_shards": 4, "to_shards": 2},
        ]
        trace.write_text("\n".join(json.dumps(e) for e in evs) + "\n")
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "trace_report.py"),
             str(trace), "--validate"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "fleet:" in proc.stdout
        assert "procs=2" in proc.stdout
        assert "hosts=2" in proc.stdout
        assert "host_drops=['1']" in proc.stdout

    def test_checkpoint_from_two_process_mesh_resumes_on_one(
            self, tmp_path, solo_2pc3):
        # the shard-agnostic checkpoint claim across PROCESS
        # boundaries: a target-capped 2-process run saves (rank 0's
        # checkpoint is canonical), a plain single-chip resume
        # completes to the oracle's exact fingerprint set
        result = _run_launcher(tmp_path / "fleet", "--target", "150",
                               "--save")
        assert result["unique"] < solo_2pc3.unique_state_count()
        ckpt = tmp_path / "fleet" / "checkpoint.npz"
        assert ckpt.exists()
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(race=False, **OPTS)
                   .resume_from(str(ckpt))
                   .spawn_tpu().join())
        assert (_digest(resumed.generated_fingerprints())
                == _digest(solo_2pc3.generated_fingerprints()))
        assert (set(resumed.discoveries())
                == set(solo_2pc3.discoveries()))

    @pytest.mark.slow
    def test_single_process_checkpoint_resumes_on_two_process_mesh(
            self, tmp_path, solo_2pc3):
        # the reverse direction: a single-chip capped save, resumed by
        # the 2-process fleet to the identical fingerprint set
        ckpt = tmp_path / "solo.npz"
        capped = (TwoPhaseSys(3).checker()
                  .tpu_options(race=False, **OPTS, resumable=True)
                  .target_state_count(150)
                  .spawn_tpu().join())
        capped.save(str(ckpt))
        result = _run_launcher(tmp_path / "fleet", "--resume",
                               str(ckpt))
        assert result["resumed"] is True
        assert (result["fingerprints_sha256"]
                == _digest(solo_2pc3.generated_fingerprints()))
        assert (result["discoveries"]
                == sorted(solo_2pc3.discoveries()))

    @pytest.mark.slow
    def test_two_process_parity_on_a_deeper_model(self, tmp_path):
        # a heavier pin: 2pc n=4 (1,764 states) across the process
        # boundary vs the single-chip oracle
        solo = (TwoPhaseSys(4).checker()
                .tpu_options(race=False, **OPTS).spawn_tpu().join())
        result = _run_launcher(tmp_path / "fleet", "--model", "twopc",
                               "--args", "4")
        assert (result["fingerprints_sha256"]
                == _digest(solo.generated_fingerprints()))
        assert result["unique"] == solo.unique_state_count()


# --- rolling host join: the ready-marker contract ----------------------

class TestReadyMarkers:
    """cluster/launch.py's ready contract: workers land atomic
    ``rank<k>.ready`` JSON markers; ``scan_ready`` is idempotent over a
    ``seen`` set and deliberately unbounded by the launched rank count
    — a LATE rank's marker is the rolling-join signal — and
    ``attach_ready_watcher`` bridges it into a live scheduler as
    ``join_host``. No devices involved: the bridge is pure files."""

    def test_write_and_scan_are_idempotent(self, tmp_path):
        from stateright_tpu.cluster.launch import (scan_ready,
                                                   write_ready_marker)
        seen: set = set()
        assert scan_ready(str(tmp_path), seen) == []
        write_ready_marker(str(tmp_path), 0, local_devices=2)
        write_ready_marker(str(tmp_path), 1, local_devices=2,
                           shards=4)
        got = scan_ready(str(tmp_path), seen)
        assert [r for r, _ in got] == [0, 1]
        assert got[0][1]["local_devices"] == 2
        assert got[1][1]["shards"] == 4
        assert scan_ready(str(tmp_path), seen) == []  # all seen
        # no half-written marker is ever visible (atomic replace)
        assert not [n for n in os.listdir(str(tmp_path))
                    if n.endswith(".tmp")]
        # a LATE rank beyond the original fleet is still picked up
        write_ready_marker(str(tmp_path), 2, local_devices=2)
        assert [r for r, _ in scan_ready(str(tmp_path), seen)] == [2]

    def test_watcher_bridges_late_ranks_to_join_host(self, tmp_path):
        import time as _time

        from stateright_tpu.cluster.launch import (attach_ready_watcher,
                                                   write_ready_marker)

        class FakeScheduler:
            def __init__(self):
                self.joined = []

            def join_host(self, label, devices):
                self.joined.append((label, list(devices)))

        sched = FakeScheduler()
        seen = {0, 1}  # the original fleet: never re-joined
        write_ready_marker(str(tmp_path), 0, local_devices=2)
        stop = attach_ready_watcher(
            str(tmp_path), sched,
            lambda rank, info: [rank * 10 + i
                                for i in range(info["local_devices"])],
            seen=seen, poll=0.01)
        try:
            write_ready_marker(str(tmp_path), 2, local_devices=2)
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline and not sched.joined:
                _time.sleep(0.01)
        finally:
            stop()
            stop()  # idempotent
        assert sched.joined == [("rank2", [20, 21])]


# --- bench contract + bench_history tag --------------------------------

class TestBenchMultihostSmoke:
    def test_contract_line_lands_rc0(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--multihost-smoke"],
            capture_output=True, text=True, timeout=420)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        contract = json.loads(line)
        assert contract.get("partial") is None, contract
        assert contract["hosts"] == 2
        assert contract["procs"] == 2
        assert contract["value"] and contract["value"] > 0
        assert contract["mesh"]["unique"] == 288
        # the two-level pool spread the width-1 jobs over BOTH hosts
        assert sorted(contract["jobs_by_host"]) == ["h0", "h1"]
        assert sum(contract["jobs_by_host"].values()) == 4

    def test_bench_history_learns_the_multihost_tag(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import bench_history
        finally:
            sys.path.pop(0)
        art = tmp_path / "BENCH_r90.json"
        art.write_text(json.dumps({
            "n": 1, "rc": 0, "tail": "",
            "parsed": {"metric": "multihost smoke", "value": 104.6,
                       "unit": "uniq/s", "hosts": 2, "procs": 2}}))
        report = bench_history.build_report([str(art)])
        row = report["rounds"][0]["workloads"][bench_history.CONTRACT]
        assert "multihost" in row["tags"]
