"""Batch lane engine (service/batch.py + checker/batch_loop.py).

The load-bearing guarantees, all pinned on CPU:

* **normalizer soundness** — padding a spec's shape knobs up to its
  power-of-two bucket (capacity/fmax) NEVER changes the model's
  reachable fingerprint set (dedup is set-semantics; shapes only move
  batching granularity);
* **per-lane digest parity** — every batched job's sha256
  fingerprint digest is bit-identical to a solo run of the same job,
  across lane positions AND for jobs backfilled into retired lanes
  mid-flight;
* **graceful degradation** — ineligible specs and lanes that outgrow
  the bucket transparently run/re-run on the solo engine, same
  digest;
* **pause/resume** — pausing a batched lane lands a standard
  ``resume_from``-loadable checkpoint; the resumed (solo) run
  restores per-lane parity;
* **throughput** — ``bench.py --job-storm`` (subprocess): >=24 tiny
  same-bucket jobs complete with <=2 distinct compiles (vs >=24
  unbatched) and batched ``jobs_per_min`` >= 3x the unbatched
  baseline (ROADMAP target: >=50 small-job completions/min on one
  chip).
"""

import hashlib
import json
import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402
from stateright_tpu.service import (JobSpec, JobStore,  # noqa: E402
                                    Scheduler, build_model,
                                    normalize_shapes, plan_batch,
                                    register_model)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: pinned solo-engine shapes shared with tests/test_service.py (the
#: persistent compile cache reuses the programs)
OPTS = {"capacity": 1 << 12, "fmax": 64, "chunk_steps": 2}


def _digest(fps) -> str:
    fps = sorted(int(f) for f in fps)
    return hashlib.sha256("\n".join(map(str, fps)).encode()).hexdigest()


def _solo_fps(n: int, **extra):
    ck = (TwoPhaseSys(n).checker()
          .tpu_options(race=False, **{**OPTS, **extra})
          .spawn_tpu().join())
    return set(int(f) for f in ck.generated_fingerprints())


@pytest.fixture(scope="module")
def solo_2pc3_digest():
    return _digest(_solo_fps(3))


@pytest.fixture(scope="module")
def solo_2pc4_digest():
    return _digest(_solo_fps(4))


@pytest.fixture(scope="module")
def solo_2pc5_digest():
    return _digest(_solo_fps(5))


# --- the spec normalizer ---------------------------------------------------

class TestNormalizer:
    def test_shapes_pad_up_to_pow2_buckets(self):
        assert normalize_shapes({"capacity": 3000, "fmax": 70}) \
            == (4096, 128)
        assert normalize_shapes({"capacity": 1 << 12, "fmax": 64}) \
            == (4096, 64)
        # floors and clamps
        assert normalize_shapes({"capacity": 64, "fmax": 1}) \
            == (4096, 32)
        assert normalize_shapes({"fmax": 100000})[1] == 512
        # defaults land on the grid
        cap, fmax = normalize_shapes({})
        assert cap & (cap - 1) == 0 and fmax & (fmax - 1) == 0

    def test_same_bucket_iff_same_padded_shapes(self):
        spec_a = JobSpec("twopc", args=[3], batch="auto",
                         options={"capacity": 1 << 11, "fmax": 70})
        spec_b = JobSpec("twopc", args=[3], batch="auto",
                         options={"capacity": 1 << 12, "fmax": 128})
        spec_c = JobSpec("twopc", args=[3], batch="auto",
                         options={"capacity": 1 << 12, "fmax": 40})
        keys = [plan_batch(s)[2] for s in (spec_a, spec_b, spec_c)]
        assert keys[0] == keys[1]          # both pad to (4096, 128)
        assert keys[0] != keys[2]          # fmax 40 pads to the 64 bucket

    @pytest.mark.parametrize("seed", [3, 17])
    def test_padding_never_changes_the_fingerprint_set(self, seed):
        # PROPERTY: a run at the requested (unpadded) shapes and a run
        # at the normalizer's padded shapes enumerate the identical
        # fingerprint set — capacity/fmax only change batching
        # granularity, never reachability
        import random
        rng = random.Random(seed)
        requested = {"capacity": rng.choice((1 << 11, 1 << 12)),
                     "fmax": rng.randrange(65, 129)}
        padded_cap, padded_fmax = normalize_shapes(requested)
        base = _solo_fps(3, capacity=requested["capacity"],
                         fmax=requested["fmax"])
        padded = _solo_fps(3, capacity=padded_cap, fmax=padded_fmax)
        assert base == padded

    def test_eligibility_reasons(self):
        # opt-out, wide meshes, caps, exotic options, host-prop models
        assert plan_batch(JobSpec("twopc", args=[3]))[0] \
            == "batch=False"
        assert "width" in plan_batch(
            JobSpec("twopc", args=[3], batch="auto", width=2))[0]
        assert "target" in plan_batch(
            JobSpec("twopc", args=[3], batch="auto", target=100))[0]
        assert "options" in plan_batch(
            JobSpec("twopc", args=[3], batch="auto",
                    options={"max_capacity": 1 << 20}))[0]
        assert "host-evaluated" in plan_batch(
            JobSpec("single_copy", args=[2, 2], batch="auto"))[0]
        reason, model, key, label = plan_batch(
            JobSpec("twopc", args=[3], batch="auto", options=OPTS))
        assert reason is None and model is not None
        assert "twopc" in label


# --- the registry unification satellite ------------------------------------

class TestModelRegistry:
    def test_single_lazily_populated_registry(self):
        from stateright_tpu.service.jobs import MODEL_REGISTRY, \
            known_models
        names = known_models()
        assert {"twopc", "paxos", "single_copy", "abd"} <= set(names)
        assert names == sorted(names)  # deterministic listing
        # built-ins live in THE registry after first use
        assert "twopc" in MODEL_REGISTRY

    def test_unknown_model_error_lists_known_sorted(self):
        register_model("zz_custom", TwoPhaseSys)
        try:
            with pytest.raises(ValueError) as err:
                build_model("nope", (), {})
            msg = str(err.value)
            assert "'nope'" in msg and "zz_custom" in msg
            assert "twopc" in msg
        finally:
            from stateright_tpu.service.jobs import MODEL_REGISTRY
            MODEL_REGISTRY.pop("zz_custom", None)

    def test_runtime_registration_wins_once(self):
        sentinel = object()
        register_model("twopc_alias", lambda *a, **k: sentinel)
        try:
            assert build_model("twopc_alias", (), {}) is sentinel
        finally:
            from stateright_tpu.service.jobs import MODEL_REGISTRY
            MODEL_REGISTRY.pop("twopc_alias", None)


# --- the lane engine through the scheduler ---------------------------------

def _sched(tmp_path, lanes=2, wait=0.05, **kw):
    return Scheduler(JobStore(tmp_path / "svc"),
                     devices=jax.devices()[:1], batch_lanes=lanes,
                     batch_wait=wait, **kw)


class TestBatchedJobs:
    def test_digest_parity_all_lane_positions_and_backfill(
            self, tmp_path, solo_2pc3_digest):
        # ACCEPTANCE: 5 same-bucket jobs on 2 lanes — jobs 3..5 are
        # BACKFILLED into retired lanes mid-flight; every per-job
        # digest is bit-identical to the solo run, regardless of lane
        # position or backfill order
        sched = _sched(tmp_path, lanes=2)
        jobs = [sched.submit(JobSpec(
            "twopc", args=[3], batch="auto",
            options={"capacity": 1 << 12, "fmax": 65 + 7 * i}))
            for i in range(5)]
        lanes_used = []
        for job in jobs:
            assert sched.wait(job.id, timeout=120.0) == "done", \
                job.status
            result = job.read_result()
            assert result["fingerprints_sha256"] == solo_2pc3_digest
            assert result["unique_state_count"] == 288
            assert "batch" in job.status and "lane" in job.status
            lanes_used.append(job.status["lane"])
        # 5 jobs over 2 lanes: some lane MUST have been backfilled
        assert len(lanes_used) > len(set(lanes_used))
        prof = sched.profile()
        # one bucket (every fmax pads to 128) -> ONE compiled program
        assert prof.get("compiles") == 1
        assert prof.get("batched_jobs") == 5
        assert prof.get("compile_reuse") == 4
        assert prof.get("bucket_hits") == 4
        sched.shutdown()

    def test_batch_artifacts_and_events(self, tmp_path):
        # per-job trace.jsonl (run_start/chunk/done) + the service
        # stream's bucket_flush/batch_form/lane_retire are all
        # schema-valid, and trace_report renders the batching summary
        from stateright_tpu.obs import validate_event
        sched = _sched(tmp_path, lanes=2)
        jobs = [sched.submit(JobSpec("twopc", args=[3], batch="auto",
                                     options=dict(OPTS)))
                for _ in range(2)]
        for job in jobs:
            assert sched.wait(job.id, timeout=120.0) == "done"
        service_events = []
        with open(sched.store.service_trace_path) as f:
            for line in f:
                ev = json.loads(line)
                validate_event(ev)
                service_events.append(ev["ev"])
        for wanted in ("bucket_flush", "batch_form", "lane_retire",
                       "job_start", "job_done"):
            assert wanted in service_events, service_events
        job_events = []
        with open(jobs[0].paths["trace"]) as f:
            for line in f:
                ev = json.loads(line)
                validate_event(ev)
                job_events.append(ev["ev"])
        assert job_events[0] == "run_start"
        assert "chunk" in job_events and job_events[-1] == "done"
        # view surfaces the lane; trace_report renders the summary
        view = jobs[0].view()
        assert view["batch"].startswith("b") and "lane" in view
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "trace_report.py"),
             "--validate", sched.store.service_trace_path],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "batching:" in out.stdout
        sched.shutdown()

    def test_ineligible_and_solo_parity(self, tmp_path,
                                        solo_2pc3_digest):
        # a spec the batch matrix rejects (target cap) quietly runs
        # solo — and a batch=False spec never touches the lane engine
        sched = _sched(tmp_path, lanes=2)
        capped = sched.submit(JobSpec("twopc", args=[3], batch="auto",
                                      target=100_000,
                                      options=dict(OPTS)))
        plain = sched.submit(JobSpec("twopc", args=[3],
                                     options=dict(OPTS)))
        for job in (capped, plain):
            assert sched.wait(job.id, timeout=120.0) == "done"
            assert "batch" not in job.status
            assert job.read_result()["fingerprints_sha256"] \
                == solo_2pc3_digest
        assert not sched.profile().get("batched_jobs")
        sched.shutdown()

    def test_bucket_overflow_falls_back_solo(self, tmp_path,
                                             solo_2pc4_digest):
        # a lane whose state space outgrows the bucket (2pc4's 2832
        # uniques vs the bucket's growth limit) retires with reason
        # "grow" and re-runs on the solo engine — identical digest
        sched = _sched(tmp_path, lanes=2)
        job = sched.submit(JobSpec(
            "twopc", args=[4], batch="auto",
            options={"capacity": 1 << 11, "fmax": 128}))
        assert sched.wait(job.id, timeout=180.0) == "done", job.status
        assert job.status.get("batch_fallback") == "grow"
        assert job.read_result()["fingerprints_sha256"] \
            == solo_2pc4_digest
        sched.shutdown()

    def test_pause_batched_lane_resumes_solo_to_parity(
            self, tmp_path, solo_2pc5_digest):
        # ACCEPTANCE: pause a batched lane mid-flight -> a standard
        # resume_from-loadable checkpoint lands; the resumed job (solo
        # engine) restores per-lane parity; the OTHER lane's job is
        # untouched by the pause
        sched = _sched(tmp_path, lanes=2)
        # chunk_steps=1 -> one iteration per batched chunk: 2pc5 at
        # fmax 32 needs hundreds of chunks, so the pause control lands
        # mid-flight deterministically once the lane is RUNNING
        slow_opts = {"capacity": 1 << 14, "fmax": 32, "chunk_steps": 1}
        j1 = sched.submit(JobSpec("twopc", args=[5], batch="auto",
                                  options=dict(slow_opts)))
        j2 = sched.submit(JobSpec("twopc", args=[5], batch="auto",
                                  options=dict(slow_opts)))
        assert sched.wait(j1.id, timeout=120.0,
                          states=("running",)) == "running"
        assert sched.pause(j1.id)
        assert sched.wait(j1.id, timeout=120.0,
                          states=("paused",)) == "paused", j1.status
        assert sched.wait(j2.id, timeout=180.0) == "done"
        assert j2.read_result()["fingerprints_sha256"] \
            == solo_2pc5_digest
        assert j1.has_checkpoint()
        assert j1.status.get("resume") is True
        # partial progress landed in the checkpoint mid-flight
        assert 0 < j1.status.get("seq", 1)
        assert sched.resume(j1.id)
        assert sched.wait(j1.id, timeout=180.0) == "done", j1.status
        # resumed SOLO from the lane checkpoint, to the identical set
        assert "batch" not in j1.status
        assert j1.read_result()["fingerprints_sha256"] \
            == solo_2pc5_digest
        sched.shutdown()

    def test_cancel_batched_lane(self, tmp_path):
        sched = _sched(tmp_path, lanes=2)
        slow_opts = {"capacity": 1 << 14, "fmax": 32, "chunk_steps": 1}
        j1 = sched.submit(JobSpec("twopc", args=[4], batch="auto",
                                  options=dict(slow_opts)))
        j2 = sched.submit(JobSpec("twopc", args=[4], batch="auto",
                                  options=dict(slow_opts)))
        assert sched.cancel(j1.id)
        assert sched.wait(j1.id, timeout=120.0) in ("cancelled",
                                                    "done")
        assert sched.wait(j2.id, timeout=180.0) == "done"
        sched.shutdown()


# --- the throughput pin (bench --job-storm subprocess) ---------------------

class TestJobStorm:
    @pytest.mark.slow  # ~38s warm: two bench subprocesses (cold cache)
    def test_storm_contract_compiles_and_speedup(self):
        # ACCEPTANCE: >=24 tiny same-bucket-family jobs on one CPU
        # device complete with <=2 distinct compiles (vs >=24
        # unbatched) and batched jobs_per_min >= 3x unbatched (and >=
        # the ROADMAP 50/min target). The storm uses a FRESH
        # persistent-cache dir internally, so this pin is warm-cache
        # deterministic.
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--job-storm", "--storm-jobs", "24"],
            capture_output=True, text=True, timeout=560, env=env,
            cwd=REPO)
        assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("{")][-1]
        contract = json.loads(line)
        assert contract.get("storm") is True
        assert not contract.get("partial"), contract
        assert contract["jobs"] == 24
        assert contract["compiles"]["batched"] <= 2, contract
        assert contract["compiles"]["unbatched"] >= 24, contract
        assert contract["speedup"] >= 3.0, contract
        assert contract["jobs_per_min"]["batched"] >= 50.0, contract
        # bench_history picks the per-mode rows up as jobs/min trends
        rows = [json.loads(ln) for ln in proc.stderr.splitlines()
                if ln.startswith("{") and "job-storm" in ln]
        assert {r.get("mode") for r in rows} == {"batched",
                                                "unbatched"}


class TestBenchHistoryStorm:
    def test_jobs_per_min_trend_and_regression_flag(self, tmp_path):
        # synthetic two-round trend: the storm rows land as their own
        # jobs/min trend lines and a collapsed batched rate flags a
        # regression
        def art(jpm_batched):
            tail = json.dumps({
                "workload": "job-storm batched", "mode": "batched",
                "done": 24, "failed": 0, "wall_s": 5.0,
                "jobs_per_min": jpm_batched, "compiles": 2,
                "batched_jobs": 24, "bucket_hits": 22,
                "compile_reuse": 22})
            return {"rc": 0, "parsed": {
                "metric": "job-storm", "value": jpm_batched,
                "unit": "jobs/min", "storm": True, "service": True,
                "backend": "cpu"}, "tail": tail}
        p1 = tmp_path / "BENCH_r90.json"
        p2 = tmp_path / "BENCH_r91.json"
        p1.write_text(json.dumps(art(300.0)))
        p2.write_text(json.dumps(art(90.0)))  # 70% collapse
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import bench_history
            report = bench_history.build_report([str(p1), str(p2)])
        finally:
            sys.path.pop(0)
        trend = report["trend"]["job-storm batched"]
        assert [e["best"] for e in trend] == [300.0, 90.0]
        assert trend[0]["unit"] == "jobs/min"
        assert "storm" in trend[0]["tags"]
        kinds = {f["kind"] for f in report["flags"]}
        assert "regression" in kinds, report["flags"]
