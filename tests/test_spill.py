"""Memory tiering: HBM -> host visited-set spill (README § Memory tiering).

The acceptance contract: a run whose device table is capped FAR below
the reachable state space (``tpu_options(max_capacity=...)``) completes
via host-tier spills with a fingerprint set and discovery list
identical to an uncapped run — single-chip and sharded, pipelined and
synchronous, and composed with the degradation ladder (a rung inherits
the survivor shards' spill state). An injected ``RESOURCE_EXHAUSTED``
at grow time recovers (``profile()['spills'] >= 1``) with a ``spill``
trace event instead of terminating; capacity-class termination (spill
disabled) now leaves a resumable autosave checkpoint and a
flight-recorder dump; a wedged ``kovf`` abort re-routes through the
retry envelope with a grown k-buffer.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.checker.resilience import (  # noqa: E402
    CandidateOverflowError, FaultKind, SpillPolicy, classify_error,
    find_candidate_overflow, fp_prefix, spill_eligible)
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402

pytestmark = pytest.mark.faults


def _run(mk, **opts):
    return (mk().checker().tpu_options(race=False, **opts)
            .spawn_tpu().join())


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("shards",))


def _assert_parity(capped, clean):
    assert capped.unique_state_count() == clean.unique_state_count()
    assert (capped.generated_fingerprints()
            == clean.generated_fingerprints())
    assert set(capped.discoveries()) == set(clean.discoveries())


def _dead_after(alive, k):
    """A chip that dies for good at chunk ``k`` while the mesh is wider
    than ``alive`` — lets the capped run SPILL first, then forces a
    ladder rung that must inherit the spill state."""

    def hook(chunk, shards):
        if chunk >= k and shards > alive:
            raise RuntimeError(
                "UNAVAILABLE: fake permanent chip death (injected)")

    return hook


class TestPolicyAndClassification:
    def test_spill_policy_bounds(self):
        with pytest.raises(ValueError, match="max_capacity"):
            SpillPolicy(max_capacity=300)  # not a power of two
        with pytest.raises(ValueError, match="spill_frac"):
            SpillPolicy(frac=0.0)
        with pytest.raises(ValueError, match="spill_frac"):
            SpillPolicy(frac=1.5)
        p = SpillPolicy.from_options({"max_capacity": 1 << 10})
        assert p.enabled and p.max_capacity == 1 << 10
        assert p.can_grow(1 << 7) and not p.can_grow(1 << 9)
        assert SpillPolicy.from_options({}).can_grow(1 << 30)
        assert not SpillPolicy.from_options({"spill": False}).enabled

    def test_max_capacity_below_capacity_rejected(self):
        with pytest.raises(ValueError, match="max_capacity"):
            (TwoPhaseSys(3).checker()
             .tpu_options(race=False, capacity=1 << 12,
                          max_capacity=1 << 10).spawn_tpu())

    def test_sound_eventually_rejects_tiering(self):
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph
        m = (PackedDGraph.with_property(
            Property.eventually("odd", lambda _, s: s % 2 == 1))
            .with_path([0, 2, 4, 2]))
        with pytest.raises(NotImplementedError, match="tiering"):
            (m.checker().sound_eventually()
             .tpu_options(race=False, capacity=1 << 10,
                          max_capacity=1 << 10).spawn_tpu())

    def test_spill_eligibility(self):
        # the table/allocation capacity subset spills; the packed-state
        # encoding bound (xovf) stays terminal — tiering can't fix a
        # model bound
        assert spill_eligible(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert spill_eligible(RuntimeError(
            "device hash table probe overflow below the growth limit"))
        assert not spill_eligible(RuntimeError(
            "packed-state capacity overflow: a successor state could "
            "not be encoded"))
        assert not spill_eligible(ValueError("a model bug"))

    def test_candidate_overflow_is_recoverable_capacity(self):
        e = CandidateOverflowError(
            "candidate-buffer capacity overflow (kovf) wedged",
            vmax=100, dmax=80, bmax=10)
        assert classify_error(e) is FaultKind.CAPACITY
        assert spill_eligible(e)
        assert find_candidate_overflow(e) is e
        # found through a wrapping cause chain, like classify_error
        try:
            try:
                raise e
            except CandidateOverflowError as inner:
                raise RuntimeError("chunk failed") from inner
        except RuntimeError as wrapped:
            assert find_candidate_overflow(wrapped) is e
        assert find_candidate_overflow(RuntimeError("x")) is None


class TestEvictOp:
    """ops/hashtable.py table_evict_prefix: in-place range eviction +
    per-bucket compaction, the device half of the tiering."""

    def _filled(self, n=512, capacity=1 << 11, seed=0):
        import jax.numpy as jnp

        from stateright_tpu.ops.hashtable import make_table, table_insert
        rng = np.random.default_rng(seed)
        fps = rng.integers(1, 2 ** 63, n, dtype=np.uint64)
        hi = (fps >> np.uint64(32)).astype(np.uint32)
        lo = fps.astype(np.uint32)
        khi, klo = make_table(capacity)
        ins, khi, klo, ovf = table_insert(
            khi, klo, jnp.asarray(hi), jnp.asarray(lo),
            jnp.ones(n, bool))
        assert int(ins.sum()) == n and not bool(ovf)
        return fps, hi, lo, khi, klo

    def test_evict_count_and_membership(self):
        import jax.numpy as jnp

        from stateright_tpu.ops.hashtable import (table_evict_prefix,
                                                  table_insert)
        fps, hi, lo, khi, klo = self._filled()
        pref = fp_prefix(fps)
        mask = np.zeros(256, bool)
        mask[pref[:200]] = True
        khi2, klo2, cnt = table_evict_prefix(khi, klo,
                                             jnp.asarray(mask))
        in_range = mask[pref]
        assert int(cnt) == int(in_range.sum())
        # evicted keys re-insert as fresh; surviving keys still dedup
        ins_e, _h, _l, _o = table_insert(
            khi2, klo2, jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(in_range))
        assert int(ins_e.sum()) == int(in_range.sum())
        ins_s, _h, _l, _o = table_insert(
            khi2, klo2, jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(~in_range))
        # compaction can open earlier slots a survivor once probed past
        # (the documented maybe-fresh caveat) — but with a fresh dense
        # table there were no full buckets to probe past, so none here
        assert int(ins_s.sum()) == 0

    def test_bucket_occupancy_stays_a_prefix(self):
        import jax.numpy as jnp

        from stateright_tpu.ops.hashtable import table_evict_prefix
        fps, _hi, _lo, khi, klo = self._filled(seed=3)
        mask = np.zeros(256, bool)
        mask[fp_prefix(fps)[::3]] = True
        khi2, klo2, _cnt = table_evict_prefix(khi, klo,
                                              jnp.asarray(mask))
        k2 = np.asarray(khi2).reshape(-1, 4)
        l2 = np.asarray(klo2).reshape(-1, 4)
        ne = (k2 != 0) | (l2 != 0)
        # the insert invariant (claim the FIRST empty slot) needs every
        # bucket's occupied slots compacted to the front
        assert bool((ne[:, 1:] <= ne[:, :-1]).all())

    def test_flat_layout_round_trips(self):
        import jax.numpy as jnp

        from stateright_tpu.ops.hashtable import table_evict_prefix
        fps, _hi, _lo, khi, klo = self._filled(n=64, capacity=1 << 8)
        flat_hi = jnp.asarray(np.asarray(khi).reshape(-1))
        flat_lo = jnp.asarray(np.asarray(klo).reshape(-1))
        mask = np.zeros(256, bool)
        mask[fp_prefix(fps)] = True  # evict everything
        khi2, klo2, cnt = table_evict_prefix(flat_hi, flat_lo,
                                             jnp.asarray(mask))
        assert khi2.ndim == 1 and khi2.shape == flat_hi.shape
        assert int(cnt) == 64
        assert int((np.asarray(khi2) != 0).sum()) == 0


@pytest.fixture(scope="module")
def clean_2pc3():
    return _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                chunk_steps=2)


@pytest.fixture(scope="module")
def clean_2pc4():
    return _run(lambda: TwoPhaseSys(4), capacity=1 << 12, fmax=16,
                chunk_steps=2)


class TestCappedParity:
    """Acceptance: the device table capped far below the 288-state
    (2pc3) / 1568-state (2pc4) space completes via spill with identical
    fingerprint sets and discoveries."""

    def test_single_chip_pipelined(self, clean_2pc3):
        trace = []
        capped = _run(lambda: TwoPhaseSys(3), capacity=1 << 8,
                      max_capacity=1 << 8, fmax=8, chunk_steps=2,
                      trace=trace)
        _assert_parity(capped, clean_2pc3)
        prof = capped.profile()
        assert prof["spills"] >= 1
        assert prof["evicted_keys"] >= 1
        assert prof["host_tier_keys"] >= 1
        assert prof["host_probe_hits"] >= 1  # rediscoveries filtered
        assert not prof.get("grows")  # the budget really did bind
        evs = {e["ev"] for e in trace}
        assert "spill" in evs and "evict" in evs
        from stateright_tpu.obs import validate_event
        for e in trace:
            validate_event(e)

    def test_single_chip_sync(self, clean_2pc3):
        capped = _run(lambda: TwoPhaseSys(3), capacity=1 << 8,
                      max_capacity=1 << 8, fmax=8, chunk_steps=2,
                      pipeline=False)
        _assert_parity(capped, clean_2pc3)
        assert capped.profile()["spills"] >= 1

    def test_sharded(self, clean_2pc4):
        trace = []
        capped = _run(lambda: TwoPhaseSys(4), capacity=1 << 11,
                      max_capacity=1 << 11, fmax=8, chunk_steps=2,
                      mesh=_mesh(2), trace=trace)
        _assert_parity(capped, clean_2pc4)
        prof = capped.profile()
        assert prof["spills"] >= 1
        assert prof["host_tier_keys"] >= 1
        from stateright_tpu.obs import validate_event
        for e in trace:
            validate_event(e)

    def test_spill_composes_with_degrade(self, clean_2pc4):
        # the capped D=2 run spills (~chunk 27 of ~61 in this config),
        # THEN the chip dies for good: the ladder's single-chip rung
        # adopts the shadow WITH its evicted ranges and finishes the
        # search against the inherited host tier
        trace = []
        capped = _run(lambda: TwoPhaseSys(4), capacity=1 << 11,
                      max_capacity=1 << 11, fmax=8, chunk_steps=2,
                      mesh=_mesh(2), retries=1, backoff=0.0,
                      fault_hook=_dead_after(1, 35), trace=trace)
        _assert_parity(capped, clean_2pc4)
        prof = capped.profile()
        assert prof["spills"] >= 2  # at D=2, and again after the rung
        assert prof["degrades"] == 1
        assert prof["mesh_shards"] == 1
        assert prof["host_tier_keys"] >= 1
        # the pre-degrade spill really happened on the mesh
        evs = [e["ev"] for e in trace]
        assert evs.index("spill") < evs.index("degrade")

class TestCapacityFaultRecovery:
    def test_injected_resource_exhausted_recovers(self, clean_2pc3):
        # an allocation-class error inside the retry envelope: spill,
        # clamp the growth budget at the current capacity, resume
        def hook(chunk):
            if chunk == 2:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: injected allocation failure "
                    "at grow")

        trace = []
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                      fmax=64, chunk_steps=2, retries=2, backoff=0.0,
                      fault_hook=hook, trace=trace)
        _assert_parity(faulty, clean_2pc3)
        assert faulty.profile()["spills"] >= 1
        spills = [e for e in trace if e["ev"] == "spill"]
        assert spills and spills[0]["reason"] == "fault"
        assert "RESOURCE_EXHAUSTED" in spills[0]["error"]
        from stateright_tpu.obs import validate_event
        for e in trace:
            validate_event(e)

    @pytest.mark.slow
    def test_sharded_resource_exhausted_recovers(self, clean_2pc4):
        def hook(chunk):
            if chunk == 2:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: injected allocation failure")

        faulty = _run(lambda: TwoPhaseSys(4), capacity=1 << 12,
                      fmax=16, chunk_steps=2, mesh=_mesh(2),
                      retries=2, backoff=0.0, fault_hook=hook)
        _assert_parity(faulty, clean_2pc4)
        assert faulty.profile()["spills"] >= 1

    def test_spill_disabled_capacity_terminal_leaves_artifacts(
            self, tmp_path, clean_2pc3):
        # satellite: capacity-class termination writes the autosave
        # checkpoint + flight-recorder dump before raising, like
        # watchdog/retry exhaustion already do — and the checkpoint
        # resumes to the full reached set
        path = tmp_path / "cap.npz"

        def hook(chunk):
            if chunk >= 2:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: injected, never recovers")

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, retries=1, backoff=0.0,
                           spill=False, autosave=os.fspath(path),
                           fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="resume_from"):
            ck.join()
        assert path.exists()
        assert ck.profile()["autosaves"] >= 1
        flight = ck.flight_path()
        assert flight and os.path.exists(flight)
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path).spawn_tpu().join())
        assert (resumed.generated_fingerprints()
                == clean_2pc3.generated_fingerprints())

    def test_spill_budget_exhaustion_is_terminal(self):
        # max_spills bounds CONSECUTIVE capacity recoveries: a fault
        # that reproduces on every chunk must land the terminal ending,
        # not spin forever
        def hook(chunk):
            raise RuntimeError("RESOURCE_EXHAUSTED: every chunk")

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, retries=1, backoff=0.0,
                           max_spills=2, fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="capacity exhausted"):
            ck.join()
        assert ck.profile()["spills"] <= 2

    def test_wedged_kovf_recovers_with_grown_kbuffer(self, clean_2pc3):
        # satellite: the kovf pre-mutation abort, reclassified as a
        # capacity fault, routes through the retry envelope with a
        # grown k-buffer instead of raising to the user
        def hook(chunk):
            if chunk == 2:
                raise CandidateOverflowError(
                    "candidate-buffer capacity overflow (kovf) wedged "
                    "at kraw=1 kmax=1", vmax=64, dmax=48)

        trace = []
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                      fmax=64, chunk_steps=2, retries=1, backoff=0.0,
                      fault_hook=hook, trace=trace)
        _assert_parity(faulty, clean_2pc3)
        assert faulty.profile()["kovfs"] >= 1
        assert not faulty.profile().get("spills")  # k-buffer, no evict
        kovfs = [e for e in trace if e["ev"] == "kovf"]
        assert any(e.get("recovered") for e in kovfs)

    def test_xovf_stays_terminal_even_with_spill(self):
        # the packed-state encoding bound is a model capacity issue —
        # tiering must NOT swallow it into a futile spill loop
        def hook(chunk):
            if chunk == 2:
                raise RuntimeError(
                    "packed-state capacity overflow: a successor state "
                    "could not be encoded (injected)")

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, retries=2, backoff=0.0,
                           fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError,
                           match="packed-state capacity overflow"):
            ck.join()
        assert not ck.profile().get("spills")


class TestReporting:
    def test_trace_report_renders_tiering_summary(self, tmp_path,
                                                  capsys):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"))
        try:
            import trace_report
        finally:
            sys.path.pop(0)
        path = tmp_path / "spill.jsonl"
        _run(lambda: TwoPhaseSys(3), capacity=1 << 8,
             max_capacity=1 << 8, fmax=8, chunk_steps=2,
             trace=str(path))
        assert trace_report.main([str(path), "--validate"]) == 0
        out = capsys.readouterr().out
        assert "tiering:" in out
        assert "spills=" in out and "host_tier_keys=" in out

    def test_profile_keys_documented(self):
        from stateright_tpu.obs import GLOSSARY
        capped = _run(lambda: TwoPhaseSys(3), capacity=1 << 8,
                      max_capacity=1 << 8, fmax=8, chunk_steps=2)
        unknown = set(capped.profile()) - set(GLOSSARY)
        assert not unknown, f"undocumented profile keys: {unknown}"

    def test_bench_contract_tags_spilled(self):
        import bench

        class SpilledCk:
            def profile(self):
                return {"spills": 3, "host_tier_keys": 123}

        class CleanCk:
            def profile(self):
                return {"chunks": 5}

        saved = dict(bench.SPILLED)
        try:
            bench.SPILLED.update(any=False, host_tier_keys=None)
            bench._note_degraded(CleanCk())
            assert bench.SPILLED["any"] is False
            bench._note_degraded(SpilledCk())
            assert bench.SPILLED == {"any": True, "host_tier_keys": 123}
        finally:
            bench.SPILLED.update(saved)


@pytest.mark.slow
class TestCappedParitySlow:
    def test_host_props_capped_parity(self):
        # paxos: 'linearizable' is host-evaluated — the spill re-seed
        # must re-arm the in-carry history dedup each epoch and keep
        # memoized results
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        clean = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                     chunk_steps=2)
        # 265 uniques vs a 256-slot budget (grow limit ~126)
        capped = _run(lambda: PackedPaxos(1), capacity=1 << 8,
                      max_capacity=1 << 8, fmax=8, chunk_steps=2)
        _assert_parity(capped, clean)
        assert capped.profile()["spills"] >= 1
        capped.assert_properties()

    def test_symmetry_capped_parity(self):
        # canonical-orbit dedup keys tier exactly like plain fps —
        # under a COMPLETE canonicalization parity is exact (every
        # orbit member's successors canonicalize identically, so the
        # spill path's re-expansion of rediscovered members changes
        # nothing). The default reference-style PARTIAL representative
        # (sort by RM state only) makes the reached canonical set
        # exploration-order-dependent, so a spilled run may enumerate a
        # slight superset there — pinned below.
        def mk():
            return TwoPhaseSys(4, complete_symmetry=True)

        clean = (mk().checker().symmetry_fn(mk().representative)
                 .tpu_options(race=False, capacity=1 << 12, fmax=16,
                              chunk_steps=2).spawn_tpu().join())
        # 166 orbits vs a 256-slot budget; fmax=4 keeps one iteration's
        # headroom (fmax * 22 actions) inside the budgeted growth limit
        capped = (mk().checker().symmetry_fn(mk().representative)
                  .tpu_options(race=False, capacity=1 << 8,
                               max_capacity=1 << 8, fmax=4,
                               chunk_steps=2).spawn_tpu().join())
        _assert_parity(capped, clean)
        assert capped.profile()["spills"] >= 1

    def test_partial_symmetry_capped_is_sound_superset(self):
        # the reference-style partial representative: re-expanding a
        # rediscovered orbit member can reach canonical keys the
        # first-member-only exploration never produced — the spilled
        # run enumerates a SUPERSET (every extra state is genuinely
        # reachable, so safety verdicts only get stronger), with
        # identical discoveries
        def mk():
            return TwoPhaseSys(4)

        clean = (mk().checker().symmetry_fn(mk().representative)
                 .tpu_options(race=False, capacity=1 << 12, fmax=16,
                              chunk_steps=2).spawn_tpu().join())
        capped = (mk().checker().symmetry_fn(mk().representative)
                  .tpu_options(race=False, capacity=1 << 8,
                               max_capacity=1 << 8, fmax=4,
                               chunk_steps=2).spawn_tpu().join())
        assert capped.profile()["spills"] >= 1
        assert (capped.generated_fingerprints()
                >= clean.generated_fingerprints())
        assert set(capped.discoveries()) == set(clean.discoveries())
