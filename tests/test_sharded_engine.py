"""Multi-chip sharded engine tests on the virtual 8-device CPU mesh.

The sharded engine cannot promise visitation order (nor can the
reference's multithreaded engines), so per SURVEY.md §4 the tests assert
set-equality of visited fingerprints and exact unique counts against the
host BFS oracle across 1/2/8 shards, witness validity via replay, growth
behavior, and early-exit semantics.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jax.sharding import Mesh  # noqa: E402

from stateright_tpu.models.packed import PackedLinearEquation  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


def _mesh(n: int) -> Mesh:
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), ("shards",))


def _sharded_checker(model, n_shards: int, **opts):
    return (model.checker()
            .tpu_options(mesh=_mesh(n_shards), **opts)
            .spawn_tpu()
            .join())


class TestShardedTwoPhase:
    """2pc n=3: 288 unique states (`/root/reference/examples/2pc.rs:128`)."""

    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_full_enumeration_matches_host(self, n_shards):
        model = TwoPhaseSys(3)
        host = model.checker().spawn_bfs().join()
        sharded = _sharded_checker(model, n_shards,
                                   capacity=1 << 12, fmax=64)
        assert sharded.unique_state_count() == 288
        assert (sharded.generated_fingerprints()
                == host.generated_fingerprints())
        # same verdicts: no "consistent" counterexample, both agreement
        # examples found
        assert set(sharded.discoveries()) == set(host.discoveries())

    def test_discovery_paths_replay(self):
        # Path.from_fingerprints raises on any mirror corruption, so a
        # successful reconstruction is itself the validity oracle.
        model = TwoPhaseSys(3)
        sharded = _sharded_checker(model, 8, capacity=1 << 12, fmax=64)
        for name, path in sharded.discoveries().items():
            prop = model.property(name)
            assert prop.condition(model, path.last_state())


class TestShardedGrowth:
    def test_growth_preserves_enumeration(self, tmp_path):
        # REGRESSION (round 6 root-cause of the isolation-only flake):
        # this test's donated D=2 shard_map chunk program is unreliable
        # when its executable is DESERIALIZED from the persistent
        # XLA:CPU compilation cache — a warm cache (even one written by
        # a passing run) reproducibly yields a spurious packed-capacity
        # xovf (garbage program output), a segfault, or an abort at
        # dispatch, while a cold cache dir or a cache-disabled run
        # always passes. In the full suite the shapes happened to
        # compile in-process first, so only isolation runs (cold
        # process + warm shared cache) hit the deserialize path — the
        # "cold-process state dependent" flake. Pin: compile under a
        # fresh per-run cache dir so this program's executables are
        # never read back across processes (and never poison the shared
        # cache for the next run).
        import jax
        prior = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir",
                          str(tmp_path / "xla"))
        try:
            # 2pc n=5 = 8,832 states (2pc.rs:133) with a deliberately
            # small table: the engine must grow mid-run and still
            # enumerate exactly.
            model = TwoPhaseSys(5)
            # small kraw/kmax keep the growth headroom small enough
            # that the initial capacity pre-grow does not already cover
            # the space — the run must actually exercise _grow_sharded
            sharded = _sharded_checker(model, 2, capacity=1 << 12,
                                       fmax=32, kraw=512, kmax=512)
            assert sharded.profile().get("grows", 0) > 0
            assert sharded.unique_state_count() == 8832
            host = model.checker().spawn_bfs().join()
            assert (sharded.generated_fingerprints()
                    == host.generated_fingerprints())
        finally:
            jax.config.update("jax_compilation_cache_dir", prior)


class TestShardedEarlyExit:
    def test_all_properties_discovered_stops(self):
        # LinearEquation's single sometimes-property: the engine may stop
        # as soon as a solution is found; the witness must replay.
        model = PackedLinearEquation(2, 10, 14)
        sharded = _sharded_checker(model, 2, capacity=1 << 12, fmax=32)
        path = sharded.assert_any_discovery("solvable")
        x, y = path.last_state()
        assert 2 * x + 10 * y == 14
        assert sharded.unique_state_count() <= 65536

    def test_target_state_count(self):
        model = PackedLinearEquation(2, 0, 10**9)  # unsatisfiable
        sharded = (model.checker()
                   .tpu_options(mesh=_mesh(2), capacity=1 << 14, fmax=32)
                   .target_state_count(500)
                   .spawn_tpu()
                   .join())
        assert sharded.state_count() >= 500


class TestShardedValidation:
    def test_visitor_rides_sharded_engine(self):
        # round 5: visitors replay post-hoc from the per-shard logs
        # (global interleaving unspecified, like the reference's
        # multithreaded visitors) — the visited SET must match host BFS
        from stateright_tpu.checker.visitor import StateRecorder
        rec, states = StateRecorder.new_with_accessor()
        model = TwoPhaseSys(3)
        ck = (model.checker()
              .tpu_options(mesh=_mesh(2), capacity=1 << 12)
              .visitor(rec)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 288
        assert len(states()) == 288

    def test_owner_routing_covers_all_shards(self):
        # the fingerprint-prefix partition actually spreads 2pc n=3's
        # states over the mesh (sanity: sharding isn't degenerate)
        from stateright_tpu.parallel import owner_of
        model = TwoPhaseSys(3)
        host = model.checker().spawn_bfs().join()
        owners = {owner_of(fp, 8) for fp in host.generated_fingerprints()}
        assert len(owners) == 8


class TestShardedModelOverflowFatal:
    def test_sharded_raises(self):
        from test_tpu_engine import _OverflowingEquation
        model = _OverflowingEquation(2, 0, 10**9)
        with pytest.raises(RuntimeError, match="capacity overflow"):
            _sharded_checker(model, 2, capacity=1 << 12, fmax=32)


class TestShardedHostProps:
    """Host-evaluated properties on the multi-chip engine: paxos — the
    flagship combination (linearizability checked over distinct histories
    per shard, merged on the host)."""

    @pytest.mark.parametrize("n_shards", [2, 8])
    def test_paxos_n1_265(self, n_shards):
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        ck = (PackedPaxos(1).checker()
              .tpu_options(mesh=_mesh(n_shards), capacity=1 << 12,
                           fmax=64)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 265
        ck.assert_properties()
        assert ck.discovery("value chosen") is not None
        # witness replays through the host model
        path = ck.discoveries()["value chosen"]
        assert len(path.into_actions()) >= 1

    def test_host_prop_violation_found(self):
        # the 2-server single-copy register linearizability violation must
        # surface on the sharded engine too (packed via paxos machinery is
        # unavailable; use the synthetic host-prop model)
        from test_tpu_engine import _HostPropEquation
        model = _HostPropEquation(2, 0, 10**9)
        ck = (model.checker()
              .tpu_options(mesh=_mesh(2), capacity=1 << 12, fmax=16,
                           chunk_steps=4)
              .spawn_tpu().join())
        path = ck.assert_any_discovery("x small")
        assert path.last_state()[0] > 3
        assert ck.unique_state_count() < 20000  # early exit


class TestShardedHostEventuallyRejected:
    def test_host_eventually_raises(self):
        # the sharded loop has no per-level point to correct host
        # EVENTUALLY ebits before enqueue; running anyway would silently
        # report a violated property as passing (advisor r3, high)
        from test_tpu_engine import _HostPropEquation

        class _HostEvEquation(_HostPropEquation):
            def properties(self):
                from stateright_tpu.core import Property

                def x_big(_model, state):
                    return state[0] > 3
                return [Property.eventually("x big", x_big)]

        model = _HostEvEquation(2, 0, 10**9)
        with pytest.raises(NotImplementedError, match="eventually"):
            (model.checker()
             .tpu_options(mesh=_mesh(2), capacity=1 << 12, fmax=16)
             .spawn_tpu())


class TestShardedEventually:
    def test_eventually_pins_on_mesh(self):
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph

        def eventually_odd():
            return Property.eventually("odd", lambda _, s: s % 2 == 1)

        c = (PackedDGraph.with_property(eventually_odd())
             .with_path([0, 1]).with_path([0, 2]).checker()
             .tpu_options(mesh=_mesh(2), capacity=1 << 10, fmax=16)
             .spawn_tpu().join())
        assert c.discovery("odd").into_states() == [0, 2]
        # the accepted cycle unsoundness holds SPMD too
        c2 = (PackedDGraph.with_property(eventually_odd())
              .with_path([0, 2, 4, 2]).checker()
              .tpu_options(mesh=_mesh(2), capacity=1 << 10, fmax=16)
              .spawn_tpu().join())
        assert c2.discovery("odd") is None


class TestShardedKmaxOverflowRecovery:
    @pytest.mark.slow  # ~56s warm: two sharded compiles + full rebuild
    def test_undersized_kmax_grows_and_completes(self):
        # the sharded kovf protocol: all shards abort the iteration in
        # lockstep (replicated flag), the host rebuilds with a doubled
        # kmax, and the enumeration stays exact
        model = TwoPhaseSys(5)
        sharded = _sharded_checker(model, 2, capacity=1 << 14, kmax=16)
        assert sharded.unique_state_count() == 8832
        host = model.checker().spawn_bfs().join()
        assert (sharded.generated_fingerprints()
                == host.generated_fingerprints())


class TestExchanges:
    """Both ownership exchanges — the D-hop ring and the default
    bucketed all_to_all — must produce the host BFS reached set exactly
    (set-equality; visitation order is unspecified either way)."""

    @pytest.mark.parametrize("exchange", ["ring", "bucket"])
    def test_exchange_parity_2pc_n5(self, exchange):
        model = TwoPhaseSys(5)
        host = model.checker().spawn_bfs().join()
        sharded = _sharded_checker(model, 4, capacity=1 << 16,
                                   exchange=exchange, race=False)
        assert sharded.unique_state_count() == 8832
        assert (sharded.generated_fingerprints()
                == host.generated_fingerprints())

    def test_bucket_kb_overflow_rebuild(self):
        # a tiny kb forces the bucketed exchange through its
        # abort-and-rebuild path (bmax rides the stats); the run must
        # still complete exactly
        model = TwoPhaseSys(3)
        sharded = _sharded_checker(model, 2, capacity=1 << 12, fmax=64,
                                   exchange="bucket", kb=16,
                                   race=False)
        assert sharded.unique_state_count() == 288
        # the rebuild really happened: the per-destination bound was hit
        assert sharded.profile()["chunks"] > 1
