"""TPU engine tests (run on the virtual CPU mesh; same code path as TPU).

Strategy per SURVEY.md §4: the TPU engine cannot promise visitation order,
so tests assert (a) bit-identical host/device fingerprints, (b) device hash
table behavior against a host set simulation, (c) set-equality of visited
fingerprints and exact unique counts on full-enumeration workloads, and
(d) validity of discovered witnesses via replay (differential vs the host
BFS oracle).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.fingerprint import fp64_words  # noqa: E402
from stateright_tpu.models.packed import (  # noqa: E402
    PackedLinearEquation,
    validate_packed_model,
)
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402
from stateright_tpu.ops.hash_kernel import fp64_device  # noqa: E402
from stateright_tpu.ops.hashtable import make_table, table_insert  # noqa: E402


# --- device hash kernel ----------------------------------------------------

def test_fp64_device_matches_host():
    rng = np.random.default_rng(0)
    for w in (1, 2, 4, 7, 16):
        words = rng.integers(0, 2**32, size=(64, w), dtype=np.uint32)
        hi, lo = fp64_device(jnp.asarray(words))
        hi, lo = np.asarray(hi), np.asarray(lo)
        for r in range(words.shape[0]):
            expect = fp64_words(words[r].tolist())
            got = (int(hi[r]) << 32) | int(lo[r])
            assert got == expect, f"row {r} width {w}"


# --- device hash table -----------------------------------------------------

def _fps(n, seed=0):
    rng = np.random.default_rng(seed)
    hi = rng.integers(0, 2**32, size=n, dtype=np.uint32)
    lo = rng.integers(1, 2**32, size=n, dtype=np.uint32)
    return hi, lo


def test_table_insert_basic():
    key_hi, key_lo = make_table(256)
    hi, lo = _fps(100)
    valid = np.ones(100, dtype=bool)
    inserted, key_hi, key_lo, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    assert not bool(overflow)
    assert np.asarray(inserted).sum() == 100  # all unique fps inserted

    # Re-inserting the same batch: nothing is new.
    inserted2, key_hi, key_lo, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    assert not bool(overflow)
    assert np.asarray(inserted2).sum() == 0


def test_table_insert_batch_duplicates():
    # Duplicates *within* a batch: exactly one insertion per distinct fp.
    key_hi, key_lo = make_table(256)
    hi = np.array([7, 7, 7, 9, 9], dtype=np.uint32)
    lo = np.array([1, 1, 1, 2, 2], dtype=np.uint32)
    valid = np.ones(5, dtype=bool)
    inserted, key_hi, key_lo, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    inserted = np.asarray(inserted)
    assert not bool(overflow)
    assert inserted[:3].sum() == 1
    assert inserted[3:].sum() == 1


def test_table_insert_collision_chains():
    # Tiny table, heavy collisions: all distinct keys still land.
    key_hi, key_lo = make_table(64)
    hi, lo = _fps(48, seed=3)
    valid = np.ones(48, dtype=bool)
    inserted, key_hi, key_lo, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    assert not bool(overflow)
    assert np.asarray(inserted).sum() == 48
    # Table contents equal the key set.
    khi, klo = np.asarray(key_hi), np.asarray(key_lo)
    stored = {(int(a), int(b)) for a, b in zip(khi, klo) if (a, b) != (0, 0)}
    assert stored == {(int(a), int(b)) for a, b in zip(hi, lo)}


def test_table_insert_overflow_detected():
    key_hi, key_lo = make_table(16)
    hi, lo = _fps(32, seed=5)
    valid = np.ones(32, dtype=bool)
    _, _, _, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid),
        max_rounds=64)
    assert bool(overflow)


def test_table_insert_respects_valid_mask():
    key_hi, key_lo = make_table(64)
    hi, lo = _fps(10)
    valid = np.zeros(10, dtype=bool)
    valid[::2] = True
    inserted, *_ = table_insert(
        key_hi, key_lo, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    assert np.asarray(inserted).sum() == 5


# --- packed model contracts ------------------------------------------------

def test_packed_linear_equation_contract():
    validate_packed_model(PackedLinearEquation(2, 10, 14), max_states=300)


def test_packed_twopc_contract():
    validate_packed_model(TwoPhaseSys(3), max_states=300)


# --- end-to-end engine -----------------------------------------------------

def test_tpu_twopc_check3():
    # SURVEY.md §7 stage 3's minimum end-to-end slice: 2pc with 3 RMs on the
    # device engine matches the host oracle: 288 unique states
    # (2pc.rs:128) and the same property verdicts.
    model = TwoPhaseSys(3)
    checker = (model.checker()
               .tpu_options(capacity=1 << 12)
               .spawn_tpu().join())
    assert checker.unique_state_count() == 288
    checker.assert_properties()  # both sometimes found; always holds

    # Discovered witnesses replay correctly through the host model.
    for name in ("abort agreement", "commit agreement"):
        path = checker.discovery(name)
        assert path is not None
        prop = model.property(name)
        assert prop.condition(model, path.last_state())


def test_tpu_matches_host_visited_set():
    model = TwoPhaseSys(2)
    host = TwoPhaseSys(2).checker().spawn_bfs().join()
    tpu = (model.checker().tpu_options(capacity=1 << 10)
           .spawn_tpu().join())
    # Set equality of visited fingerprints (order is engine-specific).
    assert tpu.generated_fingerprints() == host.generated_fingerprints()


def test_tpu_linear_equation_full_enumeration():
    # Unsolvable equation forces full enumeration: 256*256 unique states
    # (bfs.rs:371). Also exercises table growth (initial capacity 2^14 must
    # grow to hold 65,536 fingerprints).
    checker = (PackedLinearEquation(2, 4, 7).checker()
               .tpu_options(capacity=1 << 14)
               .spawn_tpu().join())
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_tpu_finds_sometimes_discovery():
    checker = (PackedLinearEquation(2, 10, 14).checker()
               .tpu_options(capacity=1 << 12)
               .spawn_tpu().join())
    path = checker.assert_any_discovery("solvable")
    x, y = path.last_state()
    assert (2 * x + 10 * y) & 0xFF == 14


def test_tpu_target_state_count():
    checker = (PackedLinearEquation(2, 4, 7).checker()
               .target_state_count(500)
               .tpu_options(capacity=1 << 14)
               .spawn_tpu().join())
    assert checker.state_count() >= 500
    assert checker.unique_state_count() < 256 * 256


def test_tpu_requires_packed_model():
    from stateright_tpu.models import LinearEquation
    with pytest.raises(TypeError):
        LinearEquation(2, 10, 14).checker().spawn_tpu()


def test_tpu_level_mode_grows_mid_level():
    # Regression: in per-level mode a single level's insert batch can exceed
    # the growth headroom; the engine must grow and retry the level rather
    # than overflow (and join() must surface engine errors, not swallow
    # them).
    checker = (TwoPhaseSys(4).checker()
               .tpu_options(mode="level", capacity=256, max_segment=64)
               .spawn_tpu().join())
    host = TwoPhaseSys(4).checker().spawn_bfs().join()
    assert checker.unique_state_count() == host.unique_state_count()
    assert checker.generated_fingerprints() == host.generated_fingerprints()


def test_tpu_visitor_rides_device_engine():
    # round 5: a visitor no longer forces the per-level engine — visits
    # replay post-hoc from the device log (insertion order); the visited
    # set must equal the host BFS visitation exactly
    from stateright_tpu.checker.visitor import StateRecorder
    rec, states = StateRecorder.new_with_accessor()
    ck = (TwoPhaseSys(3).checker().visitor(rec)
          .tpu_options(mode="device", capacity=1 << 12, race=False)
          .spawn_tpu().join())
    assert ck.unique_state_count() == 288
    assert len(states()) == 288
    host_rec, host_states = StateRecorder.new_with_accessor()
    TwoPhaseSys(3).checker().visitor(host_rec).spawn_bfs().join()
    assert {tuple(map(str, (s,))) for s in states()} \
        == {tuple(map(str, (s,))) for s in host_states()}


def test_tpu_unknown_mode_rejected():
    with pytest.raises(ValueError):
        (TwoPhaseSys(2).checker()
         .tpu_options(mode="lvel").spawn_tpu().join())


def test_join_reraises_engine_errors():
    # spawn_tpu runs init_states on the background worker, so the failure
    # must travel through the _error capture to join() (spawn_bfs would
    # raise synchronously at construction and not exercise that path).
    class Exploding(TwoPhaseSys):
        def init_states(self):
            raise RuntimeError("boom")
    with pytest.raises(RuntimeError, match="boom"):
        Exploding(2).checker().spawn_tpu().join()


def test_report_surfaces_engine_errors():
    import io
    checker = TwoPhaseSys(2).checker().tpu_options(mode="lvel").spawn_tpu()
    with pytest.raises(ValueError):
        checker.report(io.StringIO())


def test_tpu_device_mode_grows_from_tiny_capacity():
    # Regression: device mode must leave one iteration of table headroom so
    # growth fires before a probe overflow even with tiny capacity.
    checker = (TwoPhaseSys(4).checker()
               .tpu_options(mode="device", capacity=256, fmax=64)
               .spawn_tpu().join())
    host = TwoPhaseSys(4).checker().spawn_bfs().join()
    assert checker.unique_state_count() == host.unique_state_count()


# --- model capacity overflow is fatal, never silent ------------------------

class _OverflowingEquation(PackedLinearEquation):
    """Reports encoding overflow once x exceeds a threshold — exercises the
    optional third packed_step output (models/packed.py docstring)."""

    def packed_step(self, words):
        import jax.numpy as jnp
        succ, valid = super().packed_step(words)
        overflow = valid & (succ[:, 0] > 5)
        return succ, valid & ~overflow, overflow


class TestModelOverflowFatal:
    def test_level_mode_raises(self):
        model = _OverflowingEquation(2, 0, 10**9)  # unsatisfiable: must walk
        with pytest.raises(RuntimeError, match="capacity overflow"):
            (model.checker().tpu_options(capacity=1 << 12, mode="level")
             .spawn_tpu().join())

    def test_device_mode_raises(self):
        model = _OverflowingEquation(2, 0, 10**9)
        with pytest.raises(RuntimeError, match="capacity overflow"):
            (model.checker().tpu_options(capacity=1 << 12, mode="device")
             .spawn_tpu().join())

    def test_paxos_starved_net_capacity_raises(self):
        # the real scenario from actor/packed.py: more distinct in-flight
        # envelopes than network slots must abort, not under-explore
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        model = PackedPaxos(client_count=1, net_capacity=2)
        # race=False: this pins the DEVICE guard — a raced run may
        # legitimately adopt the host racer's complete result instead
        with pytest.raises(RuntimeError, match="capacity overflow"):
            (model.checker().tpu_options(capacity=1 << 14, race=False)
             .spawn_tpu().join())

    def test_cache_not_shared_across_subclasses(self):
        # jit memoization must key on the concrete class: running the plain
        # model first must not leak its compiled step to the subclass
        plain = PackedLinearEquation(2, 0, 10**9)
        (plain.checker().tpu_options(capacity=1 << 12, mode="device")
         .target_state_count(200).spawn_tpu().join())
        over = _OverflowingEquation(2, 0, 10**9)
        with pytest.raises(RuntimeError, match="capacity overflow"):
            (over.checker().tpu_options(capacity=1 << 12, mode="device")
             .spawn_tpu().join())


class _HostPropEquation(PackedLinearEquation):
    """Equation walk whose ONLY property is host-evaluated: an ALWAYS that
    a shallow state violates — pins device-mode early exit via the
    per-chunk post-hoc evaluation."""

    host_property_indices = (0,)

    def properties(self):
        from stateright_tpu.core import Property

        def x_small(_model, state):
            return state[0] <= 3
        return [Property.always("x small", x_small)]


class _MixedPropEquation(_HostPropEquation):
    """Host ALWAYS violation + an unsatisfiable device SOMETIMES: the
    engine must run to exhaustion (the sometimes needs the whole space)
    while still reporting the host counterexample."""

    host_property_indices = (1,)

    def properties(self):
        from stateright_tpu.core import Property
        return (PackedLinearEquation.properties(self)
                + _HostPropEquation.properties(self))

    def packed_properties(self, words):
        import jax.numpy as jnp
        bits = super().packed_properties(words)
        # placeholder bit for the host-evaluated property (index 1)
        return jnp.concatenate([bits, jnp.ones((1,), bool)])


class TestPosthocHostProps:
    def test_violation_exits_early(self):
        model = _HostPropEquation(2, 0, 10**9)
        # small chunks so the per-chunk post-hoc pass gets a chance to
        # observe the shallow violation long before exhaustion
        ck = (model.checker()
              .tpu_options(capacity=1 << 12, mode="device", fmax=64,
                           chunk_steps=4)
              .spawn_tpu().join())
        path = ck.assert_any_discovery("x small")
        assert path.last_state()[0] > 3
        # 65,536-state space; the violation is shallow, so the search must
        # stop far short of exhaustion
        assert ck.unique_state_count() < 20000

    def test_undiscovered_sometimes_requires_exhaustion(self):
        model = _MixedPropEquation(2, 0, 10**9)  # unsatisfiable sometimes
        ck = (model.checker()
              .tpu_options(capacity=1 << 12, mode="device", fmax=64,
                           chunk_steps=4)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 65536
        assert ck.discovery("x small") is not None
        assert ck.discovery("solvable") is None


def test_packed_contract_2pc_n5_full():
    """Full 8,832-state contract check (2pc.rs:133): every reachable
    state's encode/decode round-trip, device fingerprint, and packed
    successors against the host model."""
    assert validate_packed_model(TwoPhaseSys(5), max_states=10_000) == 8832


def test_table_insert_minimum_capacity():
    # the bucketed probe reads whole 4-slot buckets; capacity 4 is the
    # smallest legal table and must still behave (single bucket, wraps)
    key_hi, key_lo = make_table(4)
    hi = np.array([1, 2, 3, 4], dtype=np.uint32)
    lo = np.array([1, 1, 1, 1], dtype=np.uint32)
    valid = np.ones(4, dtype=bool)
    inserted, key_hi, key_lo, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    assert not bool(overflow)
    assert np.asarray(inserted).sum() == 4  # exactly full, no overflow
    # one more distinct key cannot land: overflow must be reported
    _, _, _, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(np.array([9], np.uint32)),
        jnp.asarray(np.array([9], np.uint32)),
        jnp.asarray(np.ones(1, bool)), max_rounds=16)
    assert bool(overflow)
    # but a duplicate of a stored key still resolves as already-present
    inserted, _, _, overflow = table_insert(
        key_hi, key_lo, jnp.asarray(np.array([3], np.uint32)),
        jnp.asarray(np.array([1], np.uint32)),
        jnp.asarray(np.ones(1, bool)), max_rounds=16)
    assert not bool(overflow)
    assert np.asarray(inserted).sum() == 0


def test_make_table_rejects_tiny_capacity():
    with pytest.raises(AssertionError):
        make_table(2)


def test_posthoc_incremental_growth_paths():
    # a tiny hcap forces the in-carry history-key table through its
    # growth protocol (occupancy-pressure growth and/or hovf
    # abort-and-reseed, checker/tpu.py) while the verdicts must stay
    # identical to the defaults
    from stateright_tpu.examples.single_copy_packed import PackedSingleCopy

    ck = (PackedSingleCopy(2, server_count=2).checker()
          .tpu_options(capacity=1 << 12, hcap=4)
          .spawn_tpu().join())
    path = ck.assert_any_discovery("linearizable")
    assert path.last_state().history.serialized_history() is None

    ck = (PackedSingleCopy(2, server_count=1).checker()
          .tpu_options(capacity=1 << 10, hcap=4)
          .spawn_tpu().join())
    assert ck.unique_state_count() == 93
    ck.assert_properties()


def test_plan_insert_host_matches_device_probe():
    # the host placement plan and the device probe implement the same
    # invariant INDEPENDENTLY; every planned key must read as
    # already-present to the device, or seeded states would silently be
    # re-explored
    from stateright_tpu.ops.hashtable import plan_insert_host

    rng = np.random.default_rng(11)
    fps = [int(f) for f in
           rng.integers(1, 2**63, size=300, dtype=np.uint64)]
    fps += fps[:20]  # duplicates plan to -1
    plan = plan_insert_host(fps, 512)
    assert (plan[-20:] == -1).all()
    khi = np.zeros(512, np.uint32)
    klo = np.zeros(512, np.uint32)
    for fp, i in zip(fps, plan):
        if i >= 0:
            khi[i] = fp >> 32
            klo[i] = fp & 0xFFFFFFFF
    hi = jnp.asarray(np.array([f >> 32 for f in fps], np.uint32))
    lo = jnp.asarray(np.array([f & 0xFFFFFFFF for f in fps], np.uint32))
    inserted, _, _, ovf = table_insert(
        jnp.asarray(khi), jnp.asarray(klo), hi, lo,
        jnp.ones(len(fps), bool))
    assert not bool(ovf)
    assert int(np.asarray(inserted).sum()) == 0


class TestKmaxOverflowRecovery:
    @pytest.mark.slow  # ~46s warm: kovf abort + doubled-kmax recompile
    def test_undersized_kmax_grows_and_completes(self):
        # force the kovf abort-and-rebuild protocol: a candidate buffer
        # far below the real branching must abort the first iteration
        # BEFORE any mutation, double (vmax-scaled), and still produce
        # the exact enumeration
        ck = (TwoPhaseSys(5).checker()
              .tpu_options(capacity=1 << 14, kmax=16, race=False)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 8832  # 2pc.rs:133
        host = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert ck.generated_fingerprints() == host.generated_fingerprints()


def test_per_row_hint_path_parity():
    # opt-in per-row stage-one compaction (tpu_options(hint=N),
    # device_loop.py): same counts and discoveries as the default
    # global-compaction path
    from stateright_tpu.examples.paxos_packed import PackedPaxos
    ck = (PackedPaxos(1).checker()
          .tpu_options(capacity=1 << 12, fmax=64, hint=12, race=False)
          .spawn_tpu().join())
    assert ck.unique_state_count() == 265
    assert ck.discovery("value chosen") is not None


def test_per_row_hint_overflow_rebuilds():
    # a hint below the true per-row branching must abort pre-mutation
    # and rebuild with a grown hint (rmax rides the stats) — the run
    # still enumerates exactly
    from stateright_tpu.examples.paxos_packed import PackedPaxos
    ck = (PackedPaxos(1).checker()
          .tpu_options(capacity=1 << 12, fmax=64, hint=2, race=False)
          .spawn_tpu().join())
    assert ck.unique_state_count() == 265
    assert ck.profile()["rmax"] > 2  # the observed bound that grew it
