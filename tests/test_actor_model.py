"""ActorModel tests, mirroring the reference's oracles
(`/root/reference/src/actor/model.rs` tests)."""

from typing import Optional

import pytest

from stateright_tpu.actor import (Actor, ActorModel, Deliver, Drop, Envelope,
                                 Id, Network, Out, Timeout, model_timeout)
from stateright_tpu.actor.test_util import PingPongCfg
from stateright_tpu.checker.visitor import PathRecorder, StateRecorder
from stateright_tpu.core import Expectation


def test_ping_pong_lossy_duplicating_counts():
    # `model.rs:603-614`: 4,094 unique states; safety holds.
    checker = (PingPongCfg(max_nat=5, maintains_history=False)
               .into_model()
               .lossy_network(True)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 4_094
    checker.assert_no_discovery("delta within 1")


def test_ping_pong_may_never_reach_max_on_lossy_network():
    # `model.rs:616-631`: dropping the first message gets stuck.
    checker = (PingPongCfg(max_nat=5, maintains_history=False)
               .into_model()
               .lossy_network(True)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 4_094
    from stateright_tpu.actor.test_util import Ping
    checker.assert_discovery("must reach max", [
        Drop(Envelope(src=Id(0), dst=Id(1), msg=Ping(0))),
    ])


def test_ping_pong_eventually_reaches_max_on_perfect_network():
    # `model.rs:633-646`: 11 unique states, no liveness counterexample.
    checker = (PingPongCfg(max_nat=5, maintains_history=False)
               .into_model()
               .init_network(Network.new_unordered_nonduplicating())
               .lossy_network(False)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_ping_pong_can_reach_max():
    # `model.rs:648-663`
    checker = (PingPongCfg(max_nat=5, maintains_history=False)
               .into_model()
               .lossy_network(False)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 11
    found = checker.discovery("can reach max")
    assert found.last_state().actor_states == (4, 5)


def test_ping_pong_might_never_reach_beyond_max():
    # `model.rs:665-687`: falsifiable liveness due to the boundary.
    checker = (PingPongCfg(max_nat=5, maintains_history=False)
               .into_model()
               .init_network(Network.new_unordered_nonduplicating())
               .lossy_network(False)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 11
    found = checker.discovery("must exceed max")
    assert found.last_state().actor_states == (5, 5)


def test_ping_pong_history_properties():
    checker = (PingPongCfg(max_nat=3, maintains_history=True)
               .into_model()
               .init_network(Network.new_unordered_nonduplicating())
               .checker().spawn_bfs().join())
    checker.assert_no_discovery("#in <= #out")
    checker.assert_no_discovery("#out <= #in + 1")


def test_handles_undeliverable_messages():
    # `model.rs:689-699`: a message to a nonexistent actor is ignored.
    class Unit(Actor):
        def on_start(self, id, o):
            return ()

    checker = (ActorModel()
               .actor(Unit())
               .property(Expectation.ALWAYS, "unused", lambda _, __: True)
               .init_network(Network.new_unordered_duplicating(
                   [Envelope(src=Id(0), dst=Id(99), msg=())]))
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 1


class _CountdownActor(Actor):
    """`model.rs:697-716`: actor 0 sends 2 then 1; actor 1 records order."""

    def on_start(self, id, o):
        if id == Id(0):
            o.send(Id(1), 2)
            o.send(Id(1), 1)
        return ()

    def on_msg(self, id, state, src, msg, o):
        return state + (msg,)


def test_ordered_network_flag():
    # `model.rs:695-752`: ordered nets deliver 2 then 1 only; unordered
    # nets explore both interleavings.
    def recipient_states(network):
        recorder, accessor = StateRecorder.new_with_accessor()
        (ActorModel()
         .with_actors([_CountdownActor(), _CountdownActor()])
         .property(Expectation.ALWAYS, "", lambda _, __: True)
         .init_network(network)
         .checker().visitor(recorder).spawn_bfs().join())
        return [s.actor_states[1] for s in accessor()]

    ordered = recipient_states(Network.new_ordered())
    assert ordered == [(), (2,), (2, 1)]

    unordered = recipient_states(Network.new_unordered_nonduplicating())
    assert sorted(unordered) == sorted(
        [(), (2,), (1,), (2, 1), (1, 2)])


class _DupCounter(Actor):
    """`model.rs:754-836`: actor 0 sends the same message twice."""

    def on_start(self, id, o):
        if id == Id(0):
            o.send(Id(1), ())
            o.send(Id(1), ())
        return 0

    def on_msg(self, id, state, src, msg, o):
        return state + 1


def _action_sequences(lossy: bool, network):
    recorder, accessor = PathRecorder.new_with_accessor()
    (ActorModel()
     .with_actors([_DupCounter(), _DupCounter()])
     .init_network(network)
     .lossy_network(lossy)
     .property(Expectation.ALWAYS, "force visiting all states",
               lambda _, __: True)
     .within_boundary_fn(lambda _, s: s.actor_states[1] < 4)
     .checker().visitor(recorder).spawn_dfs().join())
    return {tuple(p.into_actions()) for p in accessor()}


def test_unordered_network_drop_semantics():
    # The reference's meta-test of modeled race semantics
    # (`model.rs:754-836`).
    deliver = Deliver(src=Id(0), dst=Id(1), msg=())
    drop = Drop(Envelope(src=Id(0), dst=Id(1), msg=()))

    ordered_lossless = _action_sequences(False, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossless
    assert (deliver, deliver, deliver) not in ordered_lossless
    ordered_lossy = _action_sequences(True, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossy
    assert (deliver, drop) in ordered_lossy
    assert (drop, drop) in ordered_lossy

    unord_dup_lossless = _action_sequences(
        False, Network.new_unordered_duplicating())
    assert (deliver, deliver, deliver) in unord_dup_lossless
    unord_dup_lossy = _action_sequences(
        True, Network.new_unordered_duplicating())
    assert (deliver, deliver, deliver) in unord_dup_lossy
    assert (deliver, deliver, drop) in unord_dup_lossy
    assert (deliver, drop) in unord_dup_lossy
    assert (drop,) in unord_dup_lossy
    # drop means "never deliver again"
    assert (drop, deliver) not in unord_dup_lossy

    unord_nondup_lossless = _action_sequences(
        False, Network.new_unordered_nonduplicating())
    assert (deliver, deliver) in unord_nondup_lossless
    unord_nondup_lossy = _action_sequences(
        True, Network.new_unordered_nonduplicating())
    assert (deliver, drop) in unord_nondup_lossy
    assert (drop, drop) in unord_nondup_lossy


def test_resets_timer():
    # `model.rs:838-861`: timer set at init; timeout clears it.
    class TimerActor(Actor):
        def on_start(self, id, o):
            o.set_timer(model_timeout())
            return ()

        def on_msg(self, id, state, src, msg, o):
            return None

    checker = (ActorModel()
               .actor(TimerActor())
               .property(Expectation.ALWAYS, "unused", lambda _, __: True)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 2


def test_timeout_noop_with_reset_keeps_timer_pruned():
    # `model.rs:288-306`: a no-op timeout that re-sets its timer is pruned.
    class RearmActor(Actor):
        def on_start(self, id, o):
            o.set_timer(model_timeout())
            return ()

        def on_timeout(self, id, state, o):
            o.set_timer(model_timeout())
            return None

    checker = (ActorModel()
               .actor(RearmActor())
               .property(Expectation.ALWAYS, "unused", lambda _, __: True)
               .checker().spawn_bfs().join())
    # only the init state: the rearming timeout is a no-op transition
    assert checker.unique_state_count() == 1


def test_actor_model_state_representative():
    # sorting actor states + rewriting ids (`model_state.rs:103-118`)
    from stateright_tpu.actor import ActorModelState
    state = ActorModelState(
        actor_states=(2, 1),
        network=Network.new_unordered_nonduplicating(
            [Envelope(src=Id(0), dst=Id(1), msg=7)]),
        is_timer_set=(True, False),
        history=None)
    rep = state.representative()
    assert rep.actor_states == (1, 2)
    assert rep.is_timer_set == (False, True)
    assert list(rep.network.iter_all()) == [
        Envelope(src=Id(1), dst=Id(0), msg=7)]
