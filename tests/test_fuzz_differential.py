"""Seeded random-graph differential fuzzing across engines.

The reference pins exact counts on a handful of hand-written graphs; this
module additionally cross-checks the engines against each other on
pseudo-random digraphs (fixed seeds — deterministic in CI):

* default mode: BFS and DFS reach the same state set on full exploration;
* sound mode: a BFS-visible ``eventually`` counterexample (a terminal
  with pending bits is a property of the node graph, not of visit order)
  implies the DFS engine also reports one, and every reported trace both
  replays and genuinely never satisfies the property;
* device engine: reached-set parity with host BFS, in both modes (a few
  cases only — each random graph compiles a fresh device program);
* soak seed corpus: rejected-history artifacts dumped by the chaos soak
  harness (``tools/soak.py``) replay as regressions — the consistency
  cross-check must keep rejecting every committed ``soak_seeds/*.jsonl``.
"""

import os
import random
import sys

import pytest

from stateright_tpu.core import Property
from stateright_tpu.models.fixtures import DGraph


def random_graph(cls, seed: int, nodes: int = 18, edges: int = 26):
    rng = random.Random(seed)
    g = cls.with_property(
        Property.eventually("odd", lambda _, s: s % 2 == 1))
    for _ in range(edges):
        path = [rng.randrange(nodes) for _ in range(rng.randint(2, 4))]
        g = g.with_path(path)
    return g


def never_fires(cls, seed: int):
    rng = random.Random(seed)
    g = cls.with_property(
        Property.eventually("impossible", lambda _, s: s >= 10_000))
    for _ in range(20):
        path = [rng.randrange(16) for _ in range(rng.randint(2, 4))]
        g = g.with_path(path)
    return g


class TestHostFuzz:
    @pytest.mark.parametrize("seed", range(8))
    def test_bfs_dfs_reached_set_parity(self, seed):
        # "impossible" never fires as a SOMETIMES-style hit, but either
        # engine may still exit early on a terminal-flush counterexample;
        # reached sets are only comparable on full exploration, so
        # restrict the assertion to runs where neither exited early
        g = never_fires(DGraph, seed)
        bfs = g.checker().spawn_bfs().join()
        dfs = g.checker().spawn_dfs().join()
        if bfs.discovery("impossible") is None \
                and dfs.discovery("impossible") is None:
            assert (bfs.generated_fingerprints()
                    == dfs.generated_fingerprints())

    @pytest.mark.parametrize("seed", range(10))
    def test_sound_bfs_implies_sound_dfs(self, seed):
        g = random_graph(DGraph, seed)
        bfs = g.checker().sound_eventually().spawn_bfs().join()
        dfs = g.checker().sound_eventually().spawn_dfs().join()
        b = bfs.discovery("odd")
        d = dfs.discovery("odd")
        if b is not None:
            # a terminal with pending bits exists in the node graph; DFS
            # must report something (that terminal, or a lasso it hit
            # first)
            assert d is not None, \
                f"seed {seed}: sound BFS found a counterexample, DFS none"
        for path in (b, d):
            if path is not None:
                states = path.into_states()  # replay validates the trace
                assert not any(s % 2 == 1 for s in states), \
                    f"seed {seed}: trace satisfies the property: {states}"

    @pytest.mark.parametrize("seed", range(10))
    def test_sound_never_weaker_than_default(self, seed):
        # sound mode explores a refinement: a default-mode counterexample
        # (terminal + pending) is still a terminal with pending bits in
        # node space
        g = random_graph(DGraph, seed + 100)
        default = g.checker().spawn_bfs().join()
        sound = g.checker().sound_eventually().spawn_bfs().join()
        if default.discovery("odd") is not None:
            assert sound.discovery("odd") is not None, \
                f"seed {seed}: sound mode lost a default-mode discovery"


_SOAK_SEEDS = sorted(
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "soak_seeds", name)
    for name in os.listdir(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "soak_seeds"))
    if name.endswith(".jsonl"))


def _soak():
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        import soak
    finally:
        sys.path.pop(0)
    return soak


@pytest.mark.faults
class TestSoakSeedCorpus:
    """Rejected-history seed artifacts dumped by the chaos soak harness
    (stateright_tpu/soak.py) replay as regressions: each committed
    corpus entry captured a REAL runtime consistency violation (e.g.
    the volatile write-once server losing an acknowledged write across
    a live crash–restart), and the cross-check must keep rejecting it
    — a tester change that starts accepting one of these histories has
    broken the semantics, not fixed the bug. The corpus mixes the
    legacy seed-named layout with the PR-15 keyed layout
    (``soak_<protocol>_<kind>_<tester>_<sha256(ops)[:16]>.jsonl`` —
    auto-filed finds dedup in place); the parametrized replay covers
    both."""

    @pytest.mark.parametrize(
        "path", _SOAK_SEEDS, ids=[os.path.basename(p)
                                  for p in _SOAK_SEEDS])
    def test_seed_artifact_still_rejected(self, path):
        verdicts = _soak().check_artifact(path)
        assert verdicts, f"empty artifact {path}"
        assert not any(verdicts.values()), \
            f"{path}: history now ACCEPTED by {verdicts}"

    def test_corpus_contains_keyed_layout_entries(self):
        soak = _soak()
        from stateright_tpu.semantics import RecordedHistory
        keyed = [p for p in _SOAK_SEEDS
                 if "_linearizability_" in os.path.basename(p)]
        assert keyed, "no keyed-layout corpus entries committed"
        for path in keyed:
            meta, history = RecordedHistory.load(path)
            # the filename embeds the content digest — the dedup key
            # a re-found violation maps back onto
            expected = soak.artifact_filename(
                meta["protocol"],
                "durable" if meta.get("durable", True) else "volatile",
                meta["testers"][0], history.ops_digest())
            assert os.path.basename(path) == expected

    def test_refound_violation_updates_in_place(self, tmp_path):
        # filing the SAME history twice lands ONE file (updated), a
        # different history lands a second — the dedup key is the op
        # stream, not the run
        soak = _soak()
        from stateright_tpu.semantics import (RecordedHistory, Write,
                                              WriteOk)
        events = [("inv", "a", Write("x")), ("ret", "a", WriteOk())]
        h1 = RecordedHistory(events)
        meta = {"spec": "woregister"}
        p1 = soak.file_violation(str(tmp_path), "write_once",
                                 "volatile", "linearizability", h1,
                                 meta)
        p2 = soak.file_violation(str(tmp_path), "write_once",
                                 "volatile", "linearizability", h1,
                                 meta)
        assert p1 == p2
        h2 = RecordedHistory(events + [("inv", "b", Write("y"))])
        p3 = soak.file_violation(str(tmp_path), "write_once",
                                 "volatile", "linearizability", h2,
                                 meta)
        assert p3 != p1
        assert len([f for f in os.listdir(str(tmp_path))
                    if f.endswith(".jsonl")]) == 2


@pytest.mark.slow
class TestDeviceFuzz:
    @pytest.fixture(autouse=True)
    def _jax(self):
        pytest.importorskip("jax")

    @pytest.mark.parametrize("seed", [3, 7, 11, 19])
    def test_device_host_parity_default(self, seed):
        from stateright_tpu.models.fixtures import PackedDGraph

        g = never_fires(PackedDGraph, seed)
        host = g.checker().spawn_bfs().join()
        dev = (g.checker().tpu_options(capacity=1 << 10, fmax=16)
               .spawn_tpu().join())
        if host.discovery("impossible") is None \
                and dev.discovery("impossible") is None:
            assert (dev.generated_fingerprints()
                    == host.generated_fingerprints())

    @pytest.mark.parametrize("seed", [2, 9])
    def test_raced_winner_agnostic(self, seed):
        # the default spawn_tpu() races host BFS vs the device engine;
        # whichever wins, a full enumeration must produce the same
        # fingerprint set as the device engine forced alone. The graph
        # is a cycle plus chords — NO terminal states — so the
        # eventually-property never flushes a counterexample, both runs
        # explore the whole graph, and the parity assertion is
        # unconditional.
        from stateright_tpu.models.fixtures import PackedDGraph

        rng = random.Random(seed)
        g = PackedDGraph.with_property(
            Property.eventually("impossible", lambda _, s: s >= 10_000))
        cycle = list(range(16)) + [0]
        g = g.with_path(cycle)
        for _ in range(10):
            g = g.with_path([rng.randrange(16), rng.randrange(16)])
        raced = (g.checker().tpu_options(capacity=1 << 10, fmax=16)
                 .spawn_tpu().join())
        forced = (g.checker().tpu_options(capacity=1 << 10, fmax=16,
                                          race=False)
                  .spawn_tpu().join())
        assert raced.discovery("impossible") is None
        assert forced.discovery("impossible") is None
        assert (raced.generated_fingerprints()
                == forced.generated_fingerprints())
        assert raced.unique_state_count() == 16

    @pytest.mark.parametrize("seed", [5, 13, 21])
    def test_device_host_parity_sound(self, seed):
        from stateright_tpu.models.fixtures import DGraph, PackedDGraph

        g = random_graph(PackedDGraph, seed)
        # the lasso-complete oracle is the sound host DFS (round 5: the
        # device engine runs the same SCC sweep at exhaustion, so it can
        # legitimately find cycle counterexamples sound BFS misses)
        gh = random_graph(DGraph, seed)
        host = gh.checker().sound_eventually().spawn_dfs().join()
        dev = (g.checker().sound_eventually()
               .tpu_options(capacity=1 << 10, fmax=16)
               .spawn_tpu().join())
        h = host.discovery("odd")
        d = dev.discovery("odd")
        assert (h is None) == (d is None), \
            f"seed {seed}: sound host-dfs={h!r} device={d!r}"
        if d is not None:
            states = d.into_states()
            assert not any(s % 2 == 1 for s in states)


@pytest.mark.slow
class TestPackedActorFuzz:
    """Random configurations of the packed actor fixtures through the
    full host/device contract validator — every reachable state's
    successor set, property bits, and fingerprint must agree bit-for-bit
    across (network semantics x lossiness x timers x sizes)."""

    @pytest.fixture(autouse=True)
    def _jax(self):
        pytest.importorskip("jax")

    @pytest.mark.parametrize("max_nat,lossy,duplicating", [
        (2, False, True), (2, True, False), (3, True, True),
        (4, False, False),
    ])
    def test_ping_pong_grid(self, max_nat, lossy, duplicating):
        from stateright_tpu.actor.test_util import PackedPingPong
        from stateright_tpu.models.packed import validate_packed_model

        validate_packed_model(
            PackedPingPong(max_nat, lossy=lossy, duplicating=duplicating),
            max_states=3000)

    @pytest.mark.parametrize("n,mx", [(1, 4), (2, 2), (3, 3)])
    def test_timer_grid(self, n, mx):
        from stateright_tpu.actor.test_util import PackedTimerCount
        from stateright_tpu.models.packed import validate_packed_model

        assert validate_packed_model(
            PackedTimerCount(n, mx), max_states=300) == (mx + 1) ** n

    @pytest.mark.parametrize("clients,servers", [(1, 2), (2, 3)])
    def test_abd_ordered_grid(self, clients, servers):
        from stateright_tpu.examples.abd_packed import PackedAbd
        from stateright_tpu.models.packed import validate_packed_model

        validate_packed_model(
            PackedAbd(clients, server_count=servers, ordered=True,
                      channel_depth=8),
            max_states=800)

    @pytest.mark.parametrize("clients,servers", [(1, 3), (2, 2)])
    def test_paxos_grid(self, clients, servers):
        from stateright_tpu.examples.paxos_packed import PackedPaxos
        from stateright_tpu.models.packed import validate_packed_model

        validate_packed_model(
            PackedPaxos(clients, server_count=servers), max_states=800)
