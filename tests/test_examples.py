"""Oracle-count and witness pins for the example protocols.

Every deterministic baseline from BASELINE.md §"Deterministic baselines" is
asserted here (fast ones inline, the big paxos/2pc-sym runs gated behind
``-m slow`` like the reference gates its slow tests behind
``#[cfg(not(debug_assertions))]``, `dfs.rs:367-368`).

Early-exit counts (a run that stops when every property has a discovery)
depend on exploration order; this suite pins *our* deterministic order's
counts where they differ from the reference's (whose counts reflect its
hash-map iteration order) and replays the reference's exact witness traces
via ``assert_discovery``, which is order-independent.
"""

import pytest

from stateright_tpu.actor import Id, Network
from stateright_tpu.actor.model import Deliver
from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
from stateright_tpu.core import Property


class TestIncrement:
    def test_full_space_is_13_states(self):
        """`increment.rs:36-75`: 2-thread space = 13 unique states; the
        ``fin`` counterexample is reachable (final write of a stale read)."""
        from stateright_tpu.examples.increment import Increment
        checker = Increment(2).checker().spawn_bfs().join()
        assert checker.unique_state_count() == 13
        assert checker.discovery("fin") is not None
        # the witness from the doc comment: both threads read 0, then both
        # write 1 — the second write breaks the invariant
        checker.assert_discovery("fin", [
            ("Read", 1), ("Read", 0), ("Write", 1), ("Write", 0)])

    def test_symmetry_reduces_13_to_8(self):
        """`increment.rs:78-105`: symmetry reduction leaves 8 canonical
        states. Enumerated with an undiscoverable property so the engines
        cover the full space (the real ``fin`` counterexample would stop
        the run early at an order-dependent count)."""
        from stateright_tpu.examples.increment import Increment

        class Full(Increment):
            def properties(self):
                return [Property.sometimes("unreachable",
                                           lambda _m, _s: False)]

        model = Full(2)
        plain = model.checker().spawn_dfs().join()
        assert plain.unique_state_count() == 13
        sym = (model.checker().symmetry_fn(model.representative)
               .spawn_dfs().join())
        assert sym.unique_state_count() == 8

    def test_packed_contract(self):
        from stateright_tpu.examples.increment import Increment
        from stateright_tpu.models.packed import validate_packed_model
        assert validate_packed_model(Increment(2)) == 13


class TestIncrementLock:
    def test_lock_protects_invariants(self):
        """`increment_lock.rs`: with the lock, ``fin`` and ``mutex`` hold.
        Full 3-thread space = 61 unique states (our deterministic count;
        the reference publishes none for this example)."""
        from stateright_tpu.examples.increment_lock import IncrementLock
        checker = IncrementLock(3).checker().spawn_bfs().join()
        checker.assert_properties()
        assert checker.unique_state_count() == 61
        dfs = IncrementLock(3).checker().spawn_dfs().join()
        assert dfs.unique_state_count() == 61

    def test_packed_contract(self):
        from stateright_tpu.examples.increment_lock import IncrementLock
        from stateright_tpu.models.packed import validate_packed_model
        assert validate_packed_model(IncrementLock(2)) > 0


class TestSingleCopyRegister:
    def test_one_server_is_linearizable(self):
        """`single-copy-register.rs:84-100`: 2 clients + 1 server = 93
        unique states, linearizable, with the documented witness."""
        from stateright_tpu.examples.single_copy_register import \
            SingleCopyModelCfg
        checker = (SingleCopyModelCfg(
            client_count=2, server_count=1,
            network=Network.new_unordered_nonduplicating())
            .into_model().checker().spawn_dfs().join())
        checker.assert_properties()
        checker.assert_discovery("value chosen", [
            Deliver(src=Id(2), dst=Id(0), msg=Put(2, 'B')),
            Deliver(src=Id(0), dst=Id(2), msg=PutOk(2)),
            Deliver(src=Id(2), dst=Id(0), msg=Get(4)),
        ])
        assert checker.unique_state_count() == 93

    def test_two_servers_break_linearizability(self):
        """`single-copy-register.rs:102-122`: with 2 servers the checker
        catches the linearizability violation (reference stops at 20
        states; our deterministic order stops at 26 — early-exit counts
        are order-dependent since envelopes are explored in stable-
        fingerprint order; the witnesses below are not)."""
        from stateright_tpu.examples.single_copy_register import \
            SingleCopyModelCfg
        checker = (SingleCopyModelCfg(
            client_count=2, server_count=2,
            network=Network.new_unordered_nonduplicating())
            .into_model().checker().spawn_bfs().join())
        checker.assert_discovery("linearizable", [
            Deliver(src=Id(3), dst=Id(1), msg=Put(3, 'B')),
            Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
            Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
            Deliver(src=Id(0), dst=Id(3), msg=GetOk(6, '\0')),
        ])
        checker.assert_discovery("value chosen", [
            Deliver(src=Id(3), dst=Id(1), msg=Put(3, 'B')),
            Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
            Deliver(src=Id(2), dst=Id(0), msg=Put(2, 'A')),
            Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
        ])
        assert checker.unique_state_count() == 26


class TestLinearizableRegister:
    def test_abd_is_linearizable(self):
        """`linearizable-register.rs:234-282`: ABD with 2 clients + 2
        servers = 544 unique states under BFS and DFS, always linearizable,
        with the documented value-chosen witness."""
        from stateright_tpu.examples.linearizable_register import (AbdModelCfg,
                                                                   AckQuery,
                                                                   AckRecord,
                                                                   Query,
                                                                   Record)
        witness = [
            Deliver(src=Id(3), dst=Id(1), msg=Put(3, 'B')),
            Deliver(src=Id(1), dst=Id(0), msg=Internal(Query(3))),
            Deliver(src=Id(0), dst=Id(1),
                    msg=Internal(AckQuery(3, (0, 0), '\0'))),
            Deliver(src=Id(1), dst=Id(0),
                    msg=Internal(Record(3, (1, 1), 'B'))),
            Deliver(src=Id(0), dst=Id(1), msg=Internal(AckRecord(3))),
            Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
            Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
            Deliver(src=Id(0), dst=Id(1), msg=Internal(Query(6))),
            Deliver(src=Id(1), dst=Id(0),
                    msg=Internal(AckQuery(6, (1, 1), 'B'))),
            Deliver(src=Id(0), dst=Id(1),
                    msg=Internal(Record(6, (1, 1), 'B'))),
            Deliver(src=Id(1), dst=Id(0), msg=Internal(AckRecord(6))),
        ]
        for spawn in ("spawn_bfs", "spawn_dfs"):
            checker = getattr(
                AbdModelCfg(client_count=2, server_count=2,
                            network=Network.new_unordered_nonduplicating())
                .into_model().checker(), spawn)().join()
            checker.assert_properties()
            checker.assert_discovery("value chosen", witness)
            assert checker.unique_state_count() == 544, spawn


class TestScriptedActor:
    def test_sends_in_sequence(self):
        """`src/actor.rs:415-437`: a scripted actor sends its next message
        after each delivery it receives."""
        from stateright_tpu.actor import ActorModel
        from stateright_tpu.actor.core import Actor, Out, ScriptedActor

        class Echo(Actor):
            def on_start(self, id, o):
                return 0

            def on_msg(self, id, state, src, msg, o):
                o.send(src, ("ack", msg))
                return state + 1

        from stateright_tpu.core import Expectation
        model = (ActorModel()
                 .actor(Echo())
                 .actor(ScriptedActor([(Id(0), "a"), (Id(0), "b")]))
                 .init_network(Network.new_unordered_nonduplicating())
                 .property(Expectation.SOMETIMES, "done",
                           lambda _, s: s.actor_states[0] == 2
                           and s.actor_states[1] == 2))
        checker = model.checker().spawn_bfs().join()
        checker.assert_properties()


@pytest.mark.slow
class TestSlowOracles:
    def test_paxos_16668(self):
        """`paxos.rs:291`: 2 clients + 3 servers = 16,668 unique states."""
        from stateright_tpu.examples.paxos import PaxosModelCfg
        checker = (PaxosModelCfg(
            client_count=2, server_count=3,
            network=Network.new_unordered_nonduplicating())
            .into_model().checker().spawn_bfs().join())
        checker.assert_properties()
        assert checker.unique_state_count() == 16668

    def test_2pc_symmetry_665(self):
        """`2pc.rs:136-139`: 5 RMs under symmetry reduction = 665."""
        from stateright_tpu.models.twopc import TwoPhaseSys
        model = TwoPhaseSys(5)
        checker = (model.checker().symmetry_fn(model.representative)
                   .spawn_dfs().join())
        checker.assert_properties()
        assert checker.unique_state_count() == 665
