"""Online (incremental) linearizability + the strict history recorder
+ the iterative serialization search (PR 15's semantics tentpole).

Pins, all host-only (no JAX):

* **strict recorder** — a return (or re-invoke) on a retired
  (abandoned) logical thread id is rejected with a clear error instead
  of silently corrupting the per-thread bookkeeping; the resend-after-
  abandon client pattern the soak driver uses (abandon → fresh epoch
  id) records cleanly and round-trips through the JSONL artifact
  (including the new ``abd`` retirement events; pre-retirement
  artifacts still load).
* **iterative search** — both testers serialize multi-thousand-op
  histories WITHOUT touching ``sys.setrecursionlimit`` (the old
  recursive search burned one Python frame per op and needed the
  limit raised past ~10k ops; burn-in histories get there).
* **online checker** — verdict parity with the post-hoc
  ``LinearizabilityTester`` on the committed ``tests/soak_seeds/``
  corpus plus randomized recorded histories (accepts AND rejects),
  violation flagged AT the offending op (index pinned strictly before
  the end of the history), abandoned-op canonicalization keeping the
  configuration set bounded, and the overflow → ``None`` (unknown)
  degradation.
"""

import os
import sys
from random import Random

import pytest

from stateright_tpu.semantics import (HistoryRecorder,
                                      LinearizabilityTester,
                                      OnlineLinearizabilityChecker,
                                      Read, ReadOk, RecordedHistory,
                                      Register,
                                      SequentialConsistencyTester,
                                      WORegister, Write, WriteOk,
                                      replay_online)

_SEEDS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "soak_seeds")


def _soak():
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        import soak
    finally:
        sys.path.pop(0)
    return soak


# --- the strict recorder ---------------------------------------------------

class TestStrictRecorder:
    def test_return_on_retired_thread_rejected(self):
        rec = HistoryRecorder()
        rec.invoke("c0.0", Write("A"))
        rec.abandon("c0.0")
        with pytest.raises(ValueError, match="retired"):
            rec.ret("c0.0", WriteOk())
        # ...and re-invoking the retired id is just as dead
        with pytest.raises(ValueError, match="retired"):
            rec.invoke("c0.0", Read())

    def test_double_invoke_and_orphan_return_rejected(self):
        rec = HistoryRecorder()
        rec.invoke("t", Write("A"))
        with pytest.raises(ValueError, match="in flight"):
            rec.invoke("t", Read())
        with pytest.raises(ValueError, match="without an in-flight"):
            rec.ret("other", WriteOk())
        with pytest.raises(ValueError, match="no in-flight"):
            rec.abandon("other")

    def test_resend_after_abandon_pattern_roundtrips(self):
        # the soak client pattern: abandon the timed-out op, bump the
        # epoch, resend under the fresh id — the history keeps the
        # abandoned op in flight forever and stays well-formed
        rec = HistoryRecorder()
        rec.invoke("c0.0", Write("A"))
        rec.abandon("c0.0")
        rec.invoke("c0.1", Write("A"))
        rec.ret("c0.1", WriteOk())
        rec.invoke("c0.1", Read())
        rec.ret("c0.1", ReadOk("A"))
        assert (rec.invoked, rec.returned, rec.abandoned) == (3, 2, 1)
        history = rec.history()
        assert [k for k, _t, _p in history.events()] \
            == ["inv", "abd", "inv", "ret", "inv", "ret"]
        # JSONL round-trip preserves the abd retirement event and the
        # content digest
        meta, loaded = RecordedHistory.from_jsonl(
            history.to_jsonl({"spec": "woregister"}))
        assert loaded.events() == history.events()
        assert loaded.ops_digest() == history.ops_digest()
        assert loaded.op_count() == 3
        # the batch tester skips retirements (op stays in flight)
        assert loaded.check(LinearizabilityTester(WORegister()))

    def test_pre_retirement_artifact_still_loads(self):
        # an old-format artifact (no "abd" lines, no "v"-less lines)
        text = ('{"k":"inv","th":"a","v":["W","x"]}\n'
                '{"k":"ret","th":"a","v":["WOk"]}\n')
        meta, history = RecordedHistory.from_jsonl(text)
        assert meta is None and len(history) == 2
        assert history.check(LinearizabilityTester(Register(None)))

    def test_observer_streams_in_recorded_order(self):
        checker = OnlineLinearizabilityChecker(WORegister())
        rec = HistoryRecorder(observer=checker)
        rec.invoke("w", Write("A"))
        rec.ret("w", WriteOk())
        assert checker.verdict() is True
        rec.invoke("r", Read())
        rec.ret("r", ReadOk(None))  # reads the unwritten register
        assert checker.verdict() is False
        assert checker.violation["op_index"] == 1


# --- the iterative search --------------------------------------------------

class TestIterativeSearch:
    @pytest.fixture(autouse=True)
    def _no_recursionlimit_games(self, monkeypatch):
        def bomb(_n):
            raise AssertionError(
                "the serialization search must not touch "
                "sys.setrecursionlimit")
        monkeypatch.setattr(sys, "setrecursionlimit", bomb)

    def _long_history(self, n_ops: int) -> RecordedHistory:
        events = []
        for i in range(n_ops // 2):
            events.append(("inv", "a", Write(i)))
            events.append(("ret", "a", WriteOk()))
            events.append(("inv", "a", Read()))
            events.append(("ret", "a", ReadOk(i)))
        return RecordedHistory(events)

    def test_linearizability_12k_ops_no_recursion(self):
        history = self._long_history(12_000)
        assert history.check(LinearizabilityTester(Register(0)))

    def test_sequential_consistency_12k_ops_no_recursion(self):
        history = self._long_history(12_000)
        assert history.check(SequentialConsistencyTester(Register(0)))

    def test_rejection_verdicts_unchanged(self):
        # stale read after a completed write: both testers' canonical
        # reject case survives the iterative rewrite
        events = [("inv", "w", Write(1)), ("ret", "w", WriteOk()),
                  ("inv", "r", Read()), ("ret", "r", ReadOk(0))]
        history = RecordedHistory(events)
        assert not history.check(LinearizabilityTester(Register(0)))
        # sequential consistency has no real-time constraint, but a
        # read of 0 is still serializable (read before the write)
        assert history.check(SequentialConsistencyTester(Register(0)))

    def test_concurrent_interleavings_still_found(self):
        # two concurrent writers + a read observing the second value:
        # the search must find the interleaving (exercises the
        # iterative backtracking, not just the linear fast path)
        events = [("inv", "w1", Write(1)), ("inv", "w2", Write(2)),
                  ("ret", "w2", WriteOk()), ("ret", "w1", WriteOk()),
                  ("inv", "r", Read()), ("ret", "r", ReadOk(1))]
        history = RecordedHistory(events)
        assert history.check(LinearizabilityTester(Register(0)))


# --- the online checker ----------------------------------------------------

class TestOnlineChecker:
    def test_accepts_concurrent_overlap_both_orders(self):
        for seen in (0, 1):
            ck = OnlineLinearizabilityChecker(Register(0))
            ck.on_invoke("w", Write(1))
            ck.on_invoke("r", Read())
            ck.on_return("r", ReadOk(seen))
            ck.on_return("w", WriteOk())
            assert ck.verdict() is True, seen

    def test_violation_pinned_at_offending_op(self):
        ck = OnlineLinearizabilityChecker(Register(0))
        ck.on_invoke("w", Write(1))
        ck.on_return("w", WriteOk())
        ck.on_invoke("r", Read())
        ck.on_return("r", ReadOk(0))  # stale: flagged HERE
        assert ck.verdict() is False
        assert ck.violation["op_index"] == 1
        assert ck.violation["thread_id"] == "r"
        # later (even valid) events never un-flag it
        ck.on_invoke("r2", Read())
        ck.on_return("r2", ReadOk(1))
        assert ck.verdict() is False
        assert ck.violation["op_index"] == 1

    def test_abandoned_op_may_or_may_not_take_effect(self):
        ck = OnlineLinearizabilityChecker(Register(0))
        ck.on_invoke("w", Write(7))
        ck.abandon("w")
        ck.on_invoke("r", Read())
        ck.on_return("r", ReadOk(7))  # the abandoned write took effect
        assert ck.verdict() is True
        ck.on_invoke("r2", Read())
        ck.on_return("r2", ReadOk(0))  # ...and cannot un-take it
        assert ck.verdict() is False

    def test_abandon_canonicalization_bounds_configs(self):
        # hundreds of interchangeable abandoned writes collapse onto
        # the applied-multiset canonical form — without it this would
        # be 2^300 configurations
        ck = OnlineLinearizabilityChecker(Register(0))
        for i in range(300):
            ck.on_invoke(f"t{i}", Write("X"))
            ck.abandon(f"t{i}")
        ck.on_invoke("r", Read())
        ck.on_return("r", ReadOk(0))
        assert ck.verdict() is True
        assert ck.config_count < 10

    def test_overflow_degrades_to_unknown(self):
        ck = OnlineLinearizabilityChecker(Register(0), max_configs=2)
        for i in range(6):  # distinct concurrent writes: real blowup
            ck.on_invoke(f"w{i}", Write(i))
        ck.on_invoke("r", Read())
        ck.on_return("r", ReadOk(3))
        assert ck.overflowed
        assert ck.verdict() is None  # unknown -> post-hoc fallback

    def test_malformed_stream_matches_tester_contract(self):
        ck = OnlineLinearizabilityChecker(Register(0))
        ck.on_invoke("t", Write(1))
        with pytest.raises(ValueError, match="in flight"):
            ck.on_invoke("t", Read())
        with pytest.raises(ValueError, match="invalid"):
            ck.on_return("t", WriteOk())


def random_history(seed: int, steps: int = 60,
                   corrupt: bool = False) -> RecordedHistory:
    """A randomized concurrent register history: ops linearized at
    return against a ground-truth register (always linearizable),
    abandons that may or may not take effect, and — with ``corrupt`` —
    occasional reads returning a wrong value (usually, not always,
    non-linearizable). The generator emits well-formed streams only."""
    rng = Random(seed * 0x9E3779B1 + 17)
    value = 0
    past = [0]  # every value the register ever held
    pending = {}  # thread -> op
    events = []
    epoch = {}
    threads = [f"c{i}" for i in range(4)]
    for _step in range(steps):
        tid = rng.choice(threads)
        thread = f"{tid}.{epoch.get(tid, 0)}"
        if thread not in pending:
            op = Write(rng.randrange(1, 5)) if rng.random() < 0.45 \
                else Read()
            pending[thread] = op
            events.append(("inv", thread, op))
            continue
        op = pending.pop(thread)
        if rng.random() < 0.15:  # abandon: effect is a coin flip
            events.append(("abd", thread, None))
            epoch[tid] = epoch.get(tid, 0) + 1
            if isinstance(op, Write) and rng.random() < 0.5:
                value = op.value
            continue
        if isinstance(op, Write):
            value = op.value
            past.append(value)
            events.append(("ret", thread, WriteOk()))
        else:
            seen = value
            if corrupt and rng.random() < 0.3:
                # a STALE (previously held) value: often a real-time
                # violation, but sometimes saved by a concurrent or
                # abandoned write — both verdicts occur across seeds
                seen = rng.choice(past)
            events.append(("ret", thread, ReadOk(seen)))
    return RecordedHistory(events)


class TestOnlineParity:
    """ACCEPTANCE: the incremental checker's accept/reject verdicts are
    identical to the post-hoc tester on the committed soak corpus plus
    randomized recorded histories — and on the volatile write-once
    seed it flags the violation BEFORE the full history is consumed."""

    @pytest.mark.parametrize("seed", range(12))
    def test_parity_on_clean_random_histories(self, seed):
        history = random_history(seed, corrupt=False)
        posthoc = history.check(LinearizabilityTester(Register(0)))
        online = replay_online(history, Register(0))
        assert online is not None and online.verdict() is not None
        assert online.verdict() == posthoc
        assert posthoc  # linearized-at-return is always accepted

    @pytest.mark.parametrize("seed", range(12))
    def test_parity_on_corrupted_random_histories(self, seed):
        history = random_history(seed + 500, corrupt=True)
        posthoc = history.check(LinearizabilityTester(Register(0)))
        online = replay_online(history, Register(0))
        assert online is not None and online.verdict() is not None
        assert online.verdict() == posthoc

    def test_corrupted_seeds_cover_both_verdicts(self):
        verdicts = {random_history(s + 500, corrupt=True).check(
            LinearizabilityTester(Register(0))) for s in range(12)}
        assert verdicts == {True, False}, \
            "the corrupted generator must exercise accepts AND rejects"

    def test_parity_on_committed_corpus(self):
        soak = _soak()
        paths = sorted(p for p in os.listdir(_SEEDS_DIR)
                       if p.endswith(".jsonl"))
        assert paths, "committed soak corpus is empty"
        for name in paths:
            meta, history = RecordedHistory.load(
                os.path.join(_SEEDS_DIR, name))
            spec = soak.spec_for(meta or {})
            posthoc = history.check(soak.tester_for(
                "linearizability", spec))
            online = replay_online(history, spec)
            assert online is not None
            assert online.verdict() is not None, name
            assert online.verdict() == posthoc, name
            assert posthoc is False  # the corpus is rejections only

    def test_corpus_violations_flagged_before_history_end(self):
        # the ONLINE property the post-hoc tester cannot give you: the
        # violation lands at the offending op, strictly before the
        # last operation of the history
        for name in sorted(os.listdir(_SEEDS_DIR)):
            if not name.endswith(".jsonl"):
                continue
            soak = _soak()
            meta, history = RecordedHistory.load(
                os.path.join(_SEEDS_DIR, name))
            online = replay_online(history, soak.spec_for(meta or {}))
            assert online.violation is not None, name
            assert online.violation["op_index"] \
                < history.op_count(), name
