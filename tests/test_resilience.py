"""Resilience layer (checker/resilience.py + README § Resilience).

A transient backend fault injected mid-run must change NOTHING the
checker reports: discoveries, unique counts, and reached fingerprint
sets are pinned against an uninterrupted run across the single-chip and
sharded engines, pipelined and synchronous. Exhausted retries degrade
instead of dying — an ``autosave=`` checkpoint loads and completes via
``resume_from``; a raced run fails over to an un-budgeted host BFS; a
hung chunk sync is converted to a classified fault by the watchdog —
and ``bench.py`` always lands a valid JSON contract line, even with
every device workload forced to fail.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.checker.resilience import (  # noqa: E402
    CAPACITY_MARKERS, ChunkDeadlineError, FaultKind, RetryPolicy,
    classify_error, match_device, resolve_grant, select_survivors)
from stateright_tpu.examples.paxos_packed import PackedPaxos  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _unavailable(msg="UNAVAILABLE: fake tunnel drop (injected)"):
    return RuntimeError(msg)


def _hook_at(k):
    """Raise a fake transient backend fault when chunk ``k`` syncs."""

    def hook(chunk):
        if chunk == k:
            raise _unavailable()

    return hook


def _run(mk, **opts):
    return (mk().checker().tpu_options(race=False, **opts)
            .spawn_tpu().join())


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("shards",))


def _dead_above(shards_alive, device=None):
    """A chip that is gone FOR GOOD: every chunk sync faults while the
    mesh is wider than ``shards_alive`` (two-parameter hooks receive
    the current mesh width, so the fault disappears once the ladder
    has dropped the dead chip). ``device`` names the blamed chip in
    the error message, like a real PJRT status string."""
    msg = ("UNAVAILABLE: fake permanent chip death (injected)"
           if device is None else
           f"UNAVAILABLE: device {device} fell off the mesh (injected)")

    def hook(chunk, shards):
        if shards > shards_alive:
            raise RuntimeError(msg)

    return hook


def _dead_above_after(shards_alive, k):
    """Like :func:`_dead_above`, but the chip only dies at chunk ``k``
    (chunk ordinals are cumulative across recoveries) — lets the run
    make real progress, e.g. through growth passes, first."""

    def hook(chunk, shards):
        if chunk >= k and shards > shards_alive:
            raise RuntimeError(
                "UNAVAILABLE: fake permanent chip death (injected)")

    return hook


def _assert_parity(faulty, clean):
    assert faulty.unique_state_count() == clean.unique_state_count()
    assert (faulty.generated_fingerprints()
            == clean.generated_fingerprints())
    assert set(faulty.discoveries()) == set(clean.discoveries())


class TestClassification:
    def test_transient_markers(self):
        for msg in ("UNAVAILABLE: TPU backend setup/compile error",
                    "DEADLINE_EXCEEDED: slice op",
                    "connection reset by peer",
                    "the tunnel collapsed"):
            assert classify_error(RuntimeError(msg)) \
                is FaultKind.TRANSIENT, msg
        assert classify_error(ChunkDeadlineError("hung")) \
            is FaultKind.TRANSIENT
        assert classify_error(ConnectionResetError()) \
            is FaultKind.TRANSIENT

    def test_capacity_markers(self):
        for msg in ("RESOURCE_EXHAUSTED: out of memory while trying",
                    "device hash table overflow while seeding",
                    "packed-state capacity overflow: ..."):
            assert classify_error(RuntimeError(msg)) \
                is FaultKind.CAPACITY, msg
        # the engines' real overflow messages stay capacity-classified
        for marker in CAPACITY_MARKERS:
            assert classify_error(RuntimeError(marker)) \
                is FaultKind.CAPACITY

    def test_programming_default_and_cause_chain(self):
        assert classify_error(ValueError("a model bug")) \
            is FaultKind.PROGRAMMING
        # a wrapper raised `from` a transient error keeps the class
        # (the degrade path's RuntimeError must stay failover-eligible)
        try:
            try:
                raise _unavailable()
            except RuntimeError as inner:
                raise RuntimeError("run failed after retries") from inner
        except RuntimeError as wrapped:
            assert classify_error(wrapped) is FaultKind.TRANSIENT

    def test_retry_policy_bounds(self):
        with pytest.raises(ValueError):
            RetryPolicy(retries=-1)
        p = RetryPolicy(retries=3, backoff=1.0)
        assert p.enabled
        for attempt in (1, 2, 3, 8):
            d = p.delay(attempt)
            assert 0.0 < d <= p.cap * (1 + p.jitter)
        assert RetryPolicy(retries=0).enabled is False
        assert RetryPolicy(retries=2, backoff=0.0).delay(1) == 0.0

    def test_retry_policy_seeded_jitter_deterministic(self):
        # tpu_options(retry_seed=...) pins the jitter to a private RNG
        # stream: same seed -> same delay sequence, independent of the
        # global RNG state, PYTHONHASHSEED, and reruns
        def seq(p):
            return [p.delay(i) for i in (1, 2, 3, 4)]

        assert seq(RetryPolicy(retries=3, backoff=1.0, seed=42)) \
            == seq(RetryPolicy(retries=3, backoff=1.0, seed=42))
        assert seq(RetryPolicy(retries=3, backoff=1.0, seed=42)) \
            != seq(RetryPolicy(retries=3, backoff=1.0, seed=7))
        opts = {"retries": 2, "backoff": 1.0, "retry_seed": 5}
        assert seq(RetryPolicy.from_options(opts)) \
            == seq(RetryPolicy.from_options(dict(opts)))
        import random
        random.seed(0)
        a = seq(RetryPolicy(retries=3, backoff=1.0, seed=9))
        random.seed(12345)
        assert a == seq(RetryPolicy(retries=3, backoff=1.0, seed=9))

    def test_blamed_device_attribution(self):
        from stateright_tpu.checker.resilience import blamed_device
        assert blamed_device(RuntimeError(
            "UNAVAILABLE: device 3 heartbeat lost")) == 3
        assert blamed_device(RuntimeError(
            "UNAVAILABLE: TPU_2 tunnel reset")) == 2
        assert blamed_device(RuntimeError(
            "ABORTED: chip 1 power fault")) == 1
        assert blamed_device(RuntimeError(
            "UNAVAILABLE: backend gone")) is None
        err = RuntimeError("UNAVAILABLE: gone")
        err.device_index = 5
        assert blamed_device(err) == 5
        # attribution walks the cause chain like classify_error
        try:
            try:
                raise RuntimeError("UNAVAILABLE: device 4 dead")
            except RuntimeError as inner:
                raise RuntimeError("retries exhausted") from inner
        except RuntimeError as wrapped:
            assert blamed_device(wrapped) == 4

    def test_fault_attributor_streak(self):
        from stateright_tpu.checker.resilience import FaultAttributor
        a = FaultAttributor(blame_after=2)
        assert not a.note(3)
        assert a.note(3)          # same chip twice in a row
        a.clear()
        assert not a.note(3)
        assert not a.note(2)      # a different chip resets the streak
        assert a.note(2)
        assert not a.note(None)   # unattributable faults break streaks
        assert a.totals == {3: 3, 2: 2}  # lifetime totals survive clear()

    def test_degrade_policy_bounds(self):
        from stateright_tpu.checker.resilience import DegradePolicy
        assert DegradePolicy.from_options({}).enabled
        assert DegradePolicy.from_options({}).min_mesh == 1
        assert not DegradePolicy.from_options({"degrade": False}).enabled
        with pytest.raises(ValueError, match="min_mesh"):
            DegradePolicy(min_mesh=3)
        with pytest.raises(ValueError, match="min_mesh"):
            (TwoPhaseSys(3).checker()
             .tpu_options(race=False, min_mesh=3).spawn_tpu())


@pytest.fixture(scope="module")
def clean_paxos1():
    """One uninterrupted single-chip paxos run (host-evaluated
    linearizability), shared by the retry and degrade parity tests."""
    return _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                chunk_steps=2)


class TestRetryParity:
    """Acceptance: an injected transient UNAVAILABLE on chunk k leaves
    discoveries and unique/generated fingerprint sets identical to the
    uninterrupted run, with profile()['retries'] == 1."""

    def test_single_chip_pipelined(self):
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2)
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, retries=2, backoff=0.0,
                      fault_hook=_hook_at(2))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1

    def test_single_chip_sync(self):
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2, pipeline=False)
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, pipeline=False, retries=2,
                      backoff=0.0, fault_hook=_hook_at(2))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1

    def test_sharded(self):
        mesh = _mesh(2)
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2, mesh=mesh)
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, mesh=mesh, retries=2, backoff=0.0,
                      fault_hook=_hook_at(2))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1

    def test_host_props_and_witness_paths(self, clean_paxos1):
        # paxos: 'linearizable' is host-evaluated — the recovery must
        # re-arm the in-carry history dedup and keep memoized results
        faulty = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                      chunk_steps=2, retries=2, backoff=0.0,
                      fault_hook=_hook_at(2))
        _assert_parity(faulty, clean_paxos1)
        faulty.assert_properties()

    def test_mid_growth_recovery(self):
        # a fault landing after table growths: the re-seeded table must
        # re-insert the whole (grown) mirror
        clean = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16,
                     chunk_steps=2)
        faulty = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16,
                      chunk_steps=2, retries=2, backoff=0.0,
                      fault_hook=_hook_at(3))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1
        assert clean.profile().get("grows", 0) > 0

    def test_retry_trace_events(self):
        trace = []
        _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
             chunk_steps=2, retries=2, backoff=0.0,
             fault_hook=_hook_at(2), trace=trace)
        retries = [e for e in trace if e["ev"] == "retry"]
        assert len(retries) == 1
        assert retries[0]["attempt"] == 1
        assert "UNAVAILABLE" in retries[0]["error"]
        from stateright_tpu.obs import validate_event
        for ev in trace:
            validate_event(ev)

    def test_sound_eventually_retry(self):
        # the lasso sweep must rebuild from the shadow's cross-run edge
        # records, not the (epoch-only) device logs
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph

        def cyc():
            return (PackedDGraph.with_property(
                Property.eventually("odd", lambda _, s: s % 2 == 1))
                .with_path([0, 2, 4, 2]))

        clean = (cyc().checker().sound_eventually()
                 .tpu_options(race=False, capacity=1 << 10,
                              chunk_steps=1).spawn_tpu().join())
        assert "odd" in clean.discoveries()
        faulty = (cyc().checker().sound_eventually()
                  .tpu_options(race=False, capacity=1 << 10,
                               chunk_steps=1, retries=2, backoff=0.0,
                               fault_hook=_hook_at(2))
                  .spawn_tpu().join())
        assert "odd" in faulty.discoveries()
        assert (faulty.generated_fingerprints()
                == clean.generated_fingerprints())

    def test_non_transient_faults_not_retried(self):
        def hook(chunk):
            if chunk == 2:
                raise ValueError("a genuine model bug")

        with pytest.raises(ValueError, match="model bug"):
            _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                 chunk_steps=2, retries=2, backoff=0.0, fault_hook=hook)


@pytest.fixture(scope="module")
def clean_2pc3_d2():
    """One uninterrupted D=2 oracle run shared by the degrade parity
    tests (set-semantics parity is pipeline-agnostic)."""
    return _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                chunk_steps=2, mesh=_mesh(2))


@pytest.fixture(scope="module")
def clean_2pc3_single():
    """One uninterrupted single-chip oracle run (the ladder's bottom
    rung parity target)."""
    return _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                chunk_steps=2)


class TestDegrade:
    """Acceptance: a permanently failing chip shrinks the mesh instead
    of ending the run — D=4 degrades to D=2 (virtual CPU mesh) with
    discoveries and unique/generated fingerprint sets bit-identical to
    an uninterrupted D=2 run, pipelined and synchronous; the ladder
    descends to the single-chip rung; raced mesh runs prefer a
    degraded device finish over the host-BFS failover."""

    def test_permanent_fault_degrades_to_half_mesh_pipelined(
            self, clean_2pc3_d2):
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, mesh=_mesh(4), retries=1,
                      backoff=0.0, fault_hook=_dead_above(2))
        _assert_parity(faulty, clean_2pc3_d2)
        prof = faulty.profile()
        assert prof["degrades"] == 1
        assert prof["mesh_shards"] == 2
        assert prof["retries"] == 1  # the budget was spent, then the rung

    def test_permanent_fault_degrades_to_half_mesh_sync(
            self, clean_2pc3_d2):
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, mesh=_mesh(4), pipeline=False,
                      retries=1, backoff=0.0, fault_hook=_dead_above(2))
        _assert_parity(faulty, clean_2pc3_d2)
        prof = faulty.profile()
        assert prof["degrades"] == 1
        assert prof["mesh_shards"] == 2

    def test_blamed_chip_is_dropped_without_burning_budget(
            self, clean_2pc3_d2):
        # consecutive faults naming ONE chip drop a rung after
        # blame_after=2, not after the full retries=5 budget — and the
        # blamed device leaves the surviving mesh
        trace = []
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, mesh=_mesh(4), retries=5,
                      backoff=0.0, fault_hook=_dead_above(2, device=3),
                      trace=trace)
        _assert_parity(faulty, clean_2pc3_d2)
        prof = faulty.profile()
        assert prof["degrades"] == 1
        assert prof["retries"] == 1  # one retry, then the blame streak
        assert prof["fault_device"] == 3
        assert jax.devices()[3] not in list(faulty._mesh.devices.flat)
        degrades = [e for e in trace if e["ev"] == "degrade"]
        assert len(degrades) == 1
        assert degrades[0]["from_shards"] == 4
        assert degrades[0]["to_shards"] == 2
        assert degrades[0]["device"] == 3
        retries = [e for e in trace if e["ev"] == "retry"]
        assert retries and retries[0]["device"] == 3
        assert retries[0]["shards"] == 4
        from stateright_tpu.obs import validate_event
        for ev in trace:
            validate_event(ev)

    def test_ladder_descends_to_single_chip(self, clean_2pc3_single):
        # D=4 -> D=2 -> the single-chip rung (TpuChecker._run_device
        # adopting the shadow handoff); parity against an uninterrupted
        # single-chip run
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, mesh=_mesh(4), retries=1,
                      backoff=0.0, fault_hook=_dead_above(1))
        _assert_parity(faulty, clean_2pc3_single)
        prof = faulty.profile()
        assert prof["degrades"] == 2
        assert prof["mesh_shards"] == 1

    @pytest.mark.slow
    def test_late_fault_reinserts_accumulated_mirror(self):
        # a fault landing chunks into the run: the degraded mesh must
        # re-route the mid-flight frontier AND re-insert the whole
        # accumulated mirror at the new D (preload-aware limits)
        clean = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16,
                     chunk_steps=2, mesh=_mesh(2))
        faulty = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16,
                      chunk_steps=2, mesh=_mesh(4), retries=1,
                      backoff=0.0, fault_hook=_dead_above_after(2, 3))
        _assert_parity(faulty, clean)
        assert faulty.profile()["degrades"] == 1

    @pytest.mark.slow
    def test_sound_degrade_to_single_chip_keeps_lasso(self):
        # sound mode across a rung: the post-exhaustion SCC sweep must
        # rebuild from the shadow's cross-RUNG insert/edge records
        # (resharded down the ladder), not any single epoch's logs
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph

        def cyc():
            return (PackedDGraph.with_property(
                Property.eventually("odd", lambda _, s: s % 2 == 1))
                .with_path([0, 2, 4, 2]))

        clean = (cyc().checker().sound_eventually()
                 .tpu_options(race=False, capacity=1 << 10,
                              chunk_steps=1).spawn_tpu().join())
        assert "odd" in clean.discoveries()
        faulty = (cyc().checker().sound_eventually()
                  .tpu_options(race=False, capacity=1 << 10, fmax=16,
                               chunk_steps=1, mesh=_mesh(2), retries=1,
                               backoff=0.0, fault_hook=_dead_above(1))
                  .spawn_tpu().join())
        assert "odd" in faulty.discoveries()
        assert (faulty.generated_fingerprints()
                == clean.generated_fingerprints())
        assert faulty.profile()["degrades"] == 1
        assert faulty.profile()["mesh_shards"] == 1

    @pytest.mark.slow
    def test_host_props_degrade_to_single_chip(self, clean_paxos1):
        # paxos: 'linearizable' is host-evaluated — the sharded rung
        # uses the post-hoc per-shard reduction, the single-chip rung
        # the in-carry history dedup; the handoff must keep memoized
        # results and carry prior discoveries across the switch
        faulty = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                      chunk_steps=2, mesh=_mesh(2), retries=1,
                      backoff=0.0, fault_hook=_dead_above(1))
        _assert_parity(faulty, clean_paxos1)
        assert faulty.profile()["degrades"] == 1
        faulty.assert_properties()

    def test_min_mesh_floors_the_ladder(self, tmp_path,
                                        clean_2pc3_single):
        # min_mesh=2: the ladder stops at D=2; a fault persisting there
        # takes the old ending (autosave checkpoint + actionable raise)
        path = tmp_path / "floor.npz"
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, mesh=_mesh(4), retries=1,
                           backoff=0.0, min_mesh=2,
                           autosave=os.fspath(path),
                           fault_hook=_dead_above(1))
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="resume_from"):
            ck.join()
        assert ck.profile()["degrades"] == 1  # 4 -> 2, then the floor
        assert path.exists()
        # the autosave written at the DEGRADED width resumes anywhere
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path).spawn_tpu().join())
        assert (resumed.generated_fingerprints()
                == clean_2pc3_single.generated_fingerprints())

    def test_degrade_opt_out_keeps_old_ending(self):
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, mesh=_mesh(4), retries=1,
                           backoff=0.0, degrade=False,
                           fault_hook=_dead_above(2))
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="autosave"):
            ck.join()
        assert "degrades" not in ck.profile()

    def test_mesh_races_only_on_explicit_opt_in(self):
        from stateright_tpu.checker.race import race_eligible
        assert not race_eligible(
            TwoPhaseSys(3).checker().tpu_options(mesh=_mesh(2)))
        assert race_eligible(
            TwoPhaseSys(3).checker().tpu_options(mesh=_mesh(2),
                                                 race=True))
        assert not race_eligible(
            TwoPhaseSys(3).checker().tpu_options(mesh=_mesh(2),
                                                 race=False))

    def test_raced_mesh_prefers_ladder_over_failover(self):
        # acceptance: a raced run under a permanent D=4 fault finishes
        # on the DEGRADED device engine, not the host fallback
        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, fmax=64, chunk_steps=2,
                           mesh=_mesh(4), race=True, race_budget=0.0,
                           retries=1, backoff=0.0,
                           fault_hook=_dead_above(2))
              .spawn_tpu().join())
        host = TwoPhaseSys(4).checker().spawn_bfs().join()
        assert ck.unique_state_count() == host.unique_state_count()
        assert (ck.generated_fingerprints()
                == host.generated_fingerprints())
        prof = ck.profile()
        assert prof["engine"] == "device"
        assert prof["degrades"] >= 1
        assert prof.get("failovers", 0) == 0
        ck.assert_properties()

    def test_raced_mesh_ladder_exhaustion_still_fails_over(self):
        # every rung dead (the hook faults at every width, single chip
        # included): the ladder exhausts and the race's un-budgeted
        # host BFS remains the last rung
        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, fmax=64, chunk_steps=2,
                           mesh=_mesh(4), race=True, race_budget=0.0,
                           retries=1, backoff=0.0,
                           fault_hook=_dead_above(0))
              .spawn_tpu().join())
        host = TwoPhaseSys(4).checker().spawn_bfs().join()
        assert ck.unique_state_count() == host.unique_state_count()
        prof = ck.profile()
        assert prof["engine"] == "host"
        assert prof["failovers"] == 1


class TestAutosave:
    def test_exhausted_retries_write_loadable_checkpoint(self, tmp_path):
        path = tmp_path / "auto.npz"

        def hook(chunk):
            if chunk >= 2:
                raise _unavailable()

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, retries=1, backoff=0.0,
                           autosave=os.fspath(path), fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="resume_from"):
            ck.join()
        assert path.exists()
        assert ck.profile()["retries"] == 1
        assert ck.profile()["autosaves"] >= 1

        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12)
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path).spawn_tpu().join())
        assert resumed.unique_state_count() == 288
        assert (resumed.generated_fingerprints()
                == clean.generated_fingerprints())

    def test_periodic_autosave(self, tmp_path):
        path = tmp_path / "periodic.npz"
        trace = []
        ck = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                  chunk_steps=2, autosave=os.fspath(path),
                  autosave_interval=1, trace=trace)
        assert ck.profile()["autosaves"] >= 1
        assert path.exists()
        saves = [e for e in trace if e["ev"] == "autosave"]
        assert saves and all("path" in e and "unique" in e
                             for e in saves)
        # the final autosave resumes to the full reached set
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path).spawn_tpu().join())
        assert (resumed.generated_fingerprints()
                == ck.generated_fingerprints())

    @pytest.mark.slow
    def test_sharded_autosave_round_trips_across_mesh_sizes(
            self, tmp_path, clean_2pc3_single):
        # the shard-agnostic checkpoint claim (parallel/engine.py)
        # pinned ACROSS D changes — the degrade path depends on it: an
        # autosave written on a D=4 mesh must resume on D=2 and on a
        # single chip, converging to the same reached set
        path = tmp_path / "auto4.npz"

        def hook(chunk):  # legacy one-parameter hook shape
            if chunk >= 2:
                raise _unavailable()

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, mesh=_mesh(4), retries=1,
                           backoff=0.0, degrade=False,
                           autosave=os.fspath(path), fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="resume_from"):
            ck.join()
        assert path.exists()
        assert ck.profile()["retries"] == 1
        for opts in ({"mesh": _mesh(2)}, {}):
            resumed = (TwoPhaseSys(3).checker()
                       .tpu_options(capacity=1 << 12, **opts)
                       .resume_from(path).spawn_tpu().join())
            assert resumed.unique_state_count() == 288, opts
            assert (resumed.generated_fingerprints()
                    == clean_2pc3_single.generated_fingerprints()), opts

    def test_degrade_without_autosave_names_the_knob(self):
        def hook(chunk):
            raise _unavailable()

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, retries=1,
                           backoff=0.0, fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="autosave"):
            ck.join()

    def test_corrupt_newest_generation_resumes_from_previous(
            self, tmp_path, clean_2pc3_single):
        # ACCEPTANCE (silent-corruption defense, artifact leg): the
        # newest autosave is TRUNCATED on disk — the integrity chain
        # rejects it and ``resume_from`` rolls back to the previous
        # generation (``<path>.g1`` kept by rotation), completing to
        # full parity instead of resuming from garbage
        path = tmp_path / "auto.npz"
        ck = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                  chunk_steps=2, autosave=os.fspath(path),
                  autosave_interval=1)
        assert ck.profile()["autosaves"] >= 2
        prev = str(path) + ".g1"
        assert os.path.exists(prev)  # rotation kept the generation
        with open(path, "r+b") as f:  # truncate mid-payload
            f.truncate(max(os.path.getsize(path) // 2, 16))
        trace = []
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12, trace=trace)
                   .resume_from(path).spawn_tpu().join())
        # reached-set parity (the resume-idiom pin: discoveries that
        # fired AFTER the older generation's sync are not replayed)
        assert resumed.unique_state_count() == \
            clean_2pc3_single.unique_state_count()
        assert (resumed.generated_fingerprints()
                == clean_2pc3_single.generated_fingerprints())
        rolls = [e for e in trace if e["ev"] == "corruption"]
        assert rolls and ".g1" in rolls[0]["error"]
        # with BOTH generations gone, the failure is actionable
        with open(prev, "r+b") as f:
            f.truncate(16)
        with pytest.raises(RuntimeError, match="integrity|checkpoint"):
            (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
             .resume_from(path).spawn_tpu().join())


class TestAudit:
    """Acceptance (silent-corruption defense, compute leg): a chip
    that RETURNS WRONG RESULTS — one fingerprint bit flipped by
    ``corrupt_hook`` in a chunk the auditor samples — is caught by
    re-executing the frontier slice (host oracle single-chip, a
    different device sharded), blamed, quarantined, and the run
    replayed from the last audited boundary finishes with counts,
    fingerprint sets, and discoveries bit-identical to an
    uncorrupted run; ``audit=False`` (the default) stays free."""

    def test_lying_chip_caught_single_pipelined(self, clean_2pc3_single):
        trace = []
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                      fmax=64, chunk_steps=2, audit=1, retries=2,
                      backoff=0.0, trace=trace,
                      corrupt_hook=lambda o, d: 0 if o == 2 else None)
        _assert_parity(faulty, clean_2pc3_single)
        prof = faulty.profile()
        assert prof["audits"] >= 1
        assert prof["audit_mismatches"] >= 1
        assert prof["quarantined"] == 1
        from stateright_tpu.obs.trace import validate_event
        by_kind = {}
        for e in trace:
            validate_event(e)
            by_kind.setdefault(e["ev"], []).append(e)
        assert any(e["mismatches"] for e in by_kind["audit"])
        assert "chip is returning wrong results" \
            in by_kind["corruption"][0]["error"]
        assert by_kind["quarantine"][0]["quarantined"] == 1

    def test_lying_chip_caught_single_sync(self, clean_2pc3_single):
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                      fmax=64, chunk_steps=2, audit=1, retries=2,
                      backoff=0.0, pipeline=False,
                      corrupt_hook=lambda o, d: 0 if o == 2 else None)
        _assert_parity(faulty, clean_2pc3_single)
        assert faulty.profile()["audit_mismatches"] >= 1

    def test_lying_shard_quarantined_and_degraded(self, clean_2pc3_d2):
        # a PERSISTENT liar at mesh position 1 while D=4 (the hook is
        # width-pinned: one physical chip): the cross-device audit
        # catches it, the ladder excludes exactly that chip, and the
        # survivors converge to D=2 oracle parity
        trace = []
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                      fmax=64, chunk_steps=2, mesh=_mesh(4), audit=1,
                      retries=2, backoff=0.0, trace=trace,
                      corrupt_hook=lambda o, d: 1 if d == 4 else None)
        _assert_parity(faulty, clean_2pc3_d2)
        prof = faulty.profile()
        assert prof["audit_mismatches"] >= 1
        assert prof["quarantined"] >= 1
        assert prof["degrades"] >= 1
        assert faulty._quarantined  # never granted again this run
        bad = [e for e in trace if e["ev"] == "audit"
               and e.get("mismatches")]
        assert bad and all(e["device"] == 1 for e in bad)

    def test_clean_audited_run_reports_zero_mismatches(
            self, clean_2pc3_single):
        audited = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                       fmax=64, chunk_steps=2, audit=1)
        _assert_parity(audited, clean_2pc3_single)
        prof = audited.profile()
        assert prof["audits"] >= 1
        assert not prof.get("audit_mismatches")
        assert not prof.get("quarantined")

    def test_audit_off_default_adds_nothing(self, clean_2pc3_single):
        # satellite pin: audit=False (the default) must not change the
        # engine's behavior — no audit work, no new trace events, and
        # the reached set bit-identical to a plain run
        trace = []
        plain = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                     fmax=64, chunk_steps=2, trace=trace)
        _assert_parity(plain, clean_2pc3_single)
        assert not plain.profile().get("audits")
        assert not [e for e in trace if e["ev"] in
                    ("audit", "corruption", "quarantine")]

    def test_audit_policy_mapping(self):
        from stateright_tpu.checker.resilience import AuditPolicy

        def pol(raw):
            return AuditPolicy.from_options({"audit": raw})

        assert pol(False).every == 0 and not pol(False).enabled
        assert pol(None).every == 0
        assert pol(True).every == 1
        assert pol(4).every == 4
        assert pol(0.25).every == 4  # a fraction: every 4th chunk
        assert not pol(False).should_audit(0)
        assert [o for o in range(6)
                if pol(2).should_audit(o)] == [0, 2, 4]
        with pytest.raises(ValueError):
            pol(-1)
        with pytest.raises(ValueError):
            pol(1.5)

    def test_symmetry_with_audit_is_explicit(self):
        def mk():
            return TwoPhaseSys(3, complete_symmetry=True)

        with pytest.raises(NotImplementedError, match="audit"):
            (mk().checker().symmetry_fn(mk().representative)
             .tpu_options(race=False, capacity=1 << 12, audit=1)
             .spawn_tpu().join())


class TestBenchAuditSmoke:
    @pytest.mark.slow
    def test_contract_line_lands_rc0(self):
        # ACCEPTANCE: --audit-smoke runs the lying-chip storyline and
        # ALWAYS lands a JSON contract line, rc=0; a full (non-partial)
        # round pins the catch + quarantine + oracle-parity claims
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--audit-smoke"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        contract = json.loads(proc.stdout.strip().splitlines()[-1])
        assert contract["audit"] is True
        assert contract["unit"] == "uniq/s"
        if "partial" not in contract:
            assert contract["audited"] is True
            assert contract["audits"] >= 1
            assert contract["audit_mismatches"] >= 1
            assert contract["quarantined"] >= 1


class TestWatchdog:
    def test_stalled_sync_becomes_classified_fault(self):
        # the hook stalls one chunk's sync well past the deadline: the
        # watchdog must convert the hang into a transient fault the
        # retry loop recovers from
        def hook(chunk):
            if chunk == 2:
                time.sleep(5.0)

        trace = []
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2)
        ck = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                  chunk_steps=2, retries=2, backoff=0.0,
                  chunk_deadline=0.3, fault_hook=hook, trace=trace)
        _assert_parity(ck, clean)
        assert ck.profile()["retries"] >= 1
        evs = {e["ev"] for e in trace}
        assert "watchdog" in evs and "retry" in evs

    def test_invalid_deadline_rejected(self):
        with pytest.raises(ValueError, match="chunk_deadline"):
            (TwoPhaseSys(3).checker()
             .tpu_options(race=False, chunk_deadline=0).spawn_tpu())


class TestFailover:
    def test_raced_transient_failure_falls_over_to_host(self):
        # race budget 0 retires the budgeted racer immediately; the
        # device dies with a transient fault; the un-budgeted host BFS
        # fallback must still answer the check
        def hook(chunk):
            raise _unavailable("UNAVAILABLE: permanent tunnel death")

        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, race_budget=0.0,
                           fault_hook=hook)
              .spawn_tpu().join())
        host = TwoPhaseSys(4).checker().spawn_bfs().join()
        assert ck.unique_state_count() == host.unique_state_count()
        assert (ck.generated_fingerprints()
                == host.generated_fingerprints())
        prof = ck.profile()
        assert prof["engine"] == "host"
        assert prof["failovers"] == 1
        ck.assert_properties()

    def test_programming_error_still_surfaces(self):
        # TwoPhaseSys(4): big enough that the budgeted racer cannot
        # finish before the decider's first tick retires it — the
        # device's programming error must surface, not fail over
        def hook(chunk):
            raise ValueError("a genuine model bug")

        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, race_budget=0.0,
                           fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(ValueError, match="model bug"):
            ck.join()

    def test_failover_opt_out(self):
        def hook(chunk):
            raise _unavailable()

        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, race_budget=0.0,
                           failover=False, fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            ck.join()


def _run_bench(*flags):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         *flags],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)


def test_bench_degraded_tagging():
    # a degraded primary sample must tag the stdout contract line
    # ("degraded": true + final mesh size) so the perf trajectory can't
    # silently mix rates measured on fewer chips
    import bench

    class FakeCk:
        def profile(self):
            return {"degrades": 2, "mesh_shards": 2}

    class CleanCk:
        def profile(self):
            return {"chunks": 5}

    saved = dict(bench.DEGRADED)
    try:
        bench.DEGRADED.update(any=False, final_shards=None)
        assert bench._note_degraded(CleanCk()) == {}
        assert bench.DEGRADED["any"] is False
        assert bench._note_degraded(FakeCk()) == {}
        assert bench.DEGRADED == {"any": True, "final_shards": 2}
    finally:
        bench.DEGRADED.update(saved)


@pytest.mark.slow
class TestBenchContract:
    """bench.py must ALWAYS land a valid JSON contract line on stdout
    and exit 0 — the round-5 failure mode (rc=1, parsed=null) is
    pinned out in both the healthy and the all-device-workloads-dead
    shapes."""

    def test_smoke_contract_schema(self):
        proc = _run_bench()
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        payload = json.loads(line)
        for key in ("metric", "value", "unit", "vs_baseline", "backend",
                    "pipeline"):
            assert key in payload, key
        assert set(payload["pipeline"]) == {"on", "off"}
        assert payload["value"] is not None
        assert "partial" not in payload
        assert "init_fallback" not in payload  # backend came up clean
        # every workload row carries the fusion-proxy ratio and the
        # dedup-path tag, so the trajectory can't silently mix paths
        rows = [json.loads(ln) for ln in proc.stderr.splitlines()
                if ln.startswith("{")]
        samples = [r for r in rows if "samples" in r]
        assert samples
        for row in samples:
            assert "fused" in row, row["workload"]
            assert row["gen_per_uniq"] is None \
                or row["gen_per_uniq"] >= 1.0

    def test_backend_init_failure_falls_back_to_cpu(self):
        # ROADMAP item 3's hole (BENCH_r05: rc=1, no artifact, because
        # platform INIT raised before per-workload isolation): an
        # unusable configured backend must be classified, fall back to
        # CPU, run the matrix, and still land a tagged contract line
        env = dict(os.environ, JAX_PLATFORMS="definitely_not_a_backend")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        payload = json.loads(proc.stdout.strip().splitlines()[-1])
        assert payload["backend"] == "cpu"
        assert payload["init_fallback"] is True
        assert payload["init_cause"]  # classified, not just recorded
        assert payload["value"] is not None  # the matrix actually ran
        rows = [json.loads(ln) for ln in proc.stderr.splitlines()
                if ln.startswith("{")]
        fb = [r for r in rows if r.get("workload") == "backend"]
        assert fb and fb[0]["fallback"] == "cpu"

    def test_forced_failure_still_lands_artifact(self):
        proc = _run_bench("--inject-fault")
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        payload = json.loads(line)
        assert payload["partial"] is True
        assert isinstance(payload["failed"], list) and payload["failed"]
        assert "device-pipelined" in payload["failed"]


# --- elastic ladder, upward rung ---------------------------------------

class _Dev:
    """A stand-in ``jax.Device``: a global ``.id`` at a mesh position
    (the survivor-selection helpers never touch real hardware)."""

    def __init__(self, id):
        self.id = id

    def __repr__(self):
        return f"_Dev({self.id})"


class TestSurvivorHelpers:
    """The shared ladder arithmetic (checker/resilience.py): both
    ``degrade_step`` and ``promote_step`` resolve device references and
    pick survivor subsets through these, so the two directions cannot
    drift."""

    def test_match_device_by_object_then_id_then_position(self):
        devs = [_Dev(100), _Dev(101), _Dev(102)]
        assert match_device(devs, devs[1]) == 1      # object identity
        assert match_device(devs, 102) == 2          # global id
        assert match_device(devs, _Dev(100)) == 0    # foreign obj, .id
        assert match_device(devs, 1) == 1            # position fallback
        assert match_device(devs, None) is None
        assert match_device(devs, 999) is None
        assert match_device(devs, object()) is None  # no .id at all

    def test_select_survivors_single_host_drops_only_the_blamed_chip(
            self):
        devs = [_Dev(i) for i in range(4)]
        assert select_survivors(devs, 2, blamed_pos=3) == devs[:2]
        assert select_survivors(devs, 2, blamed_pos=0) == devs[1:3]
        assert select_survivors(devs, 2) == devs[:2]  # no blame: prefix

    def test_select_survivors_multi_host_drops_the_whole_host(self):
        # a blamed chip takes its HOST out (DCN partitions fault every
        # chip behind that NIC), keeping the halved mesh host-aligned
        devs = [_Dev(i) for i in range(4)]
        labels = ["a", "a", "b", "b"]
        assert select_survivors(devs, 2, blamed_pos=2,
                                labels=labels) == devs[:2]
        assert select_survivors(devs, 2, blamed_pos=1,
                                labels=labels) == devs[2:]

    def test_resolve_grant_dedups_and_excludes_the_current_mesh(self):
        universe = [_Dev(i + 100) for i in range(4)]
        got = resolve_grant(
            universe,
            [universe[2], 103, 0, 103, object()],  # obj, id, pos, dup
            exclude=(universe[0],))                # mesh already holds
        assert got == [universe[2], universe[3]]
        assert resolve_grant(universe, [999, None]) == []


@pytest.fixture(scope="module")
def clean_2pc3_d4():
    """One uninterrupted D=4 oracle run (the promote parity target)."""
    return _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                chunk_steps=2, mesh=_mesh(4))


def _promote_mid_run(ck, grant, timeout=180.0):
    """Drive ``ck`` one quantum, hand it ``grant``, and run to the end:
    the widening lands at the next chunk boundary, genuinely mid-run."""
    from stateright_tpu.service import RUNNING, StepDriver
    drv = StepDriver(ck).start()
    drv.step(1)
    ck.request_promote(list(grant))
    deadline = time.monotonic() + timeout
    while (drv.status == RUNNING and ck.promote_pending()
           and time.monotonic() < deadline):
        drv.step(1)
    drv.drain()
    return ck


class TestPromote:
    """Acceptance (elastic fleet): ``request_promote`` doubles a
    sharded run D=2 -> D=4 at a chunk boundary with discoveries and
    fingerprint sets bit-identical to an uninterrupted D=4 run,
    pipelined and synchronous; the widening composes with host-tier
    spill; and a run that degraded around a blame streak climbs BACK
    to its original width once the blamed chip is released healthy."""

    def test_promote_doubles_mesh_pipelined(self, clean_2pc3_d4):
        devices = jax.devices()
        if len(devices) < 4:
            pytest.skip("need 4 devices")
        trace = []
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, retries=1, backoff=0.0,
                           mesh=_mesh(2), trace=trace)
              .spawn_tpu())
        _promote_mid_run(ck, devices[2:4])
        _assert_parity(ck, clean_2pc3_d4)
        prof = ck.profile()
        assert prof["promotes"] == 1
        assert prof["mesh_shards"] == 4
        promotes = [e for e in trace if e["ev"] == "promote"]
        assert len(promotes) == 1
        assert promotes[0]["from_shards"] == 2
        assert promotes[0]["to_shards"] == 4
        from stateright_tpu.obs import validate_event
        for ev in trace:
            validate_event(ev)

    def test_promote_doubles_mesh_sync(self, clean_2pc3_d4):
        devices = jax.devices()
        if len(devices) < 4:
            pytest.skip("need 4 devices")
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, pipeline=False, retries=1,
                           backoff=0.0, mesh=_mesh(2))
              .spawn_tpu())
        _promote_mid_run(ck, devices[2:4])
        _assert_parity(ck, clean_2pc3_d4)
        prof = ck.profile()
        assert prof["promotes"] == 1
        assert prof["mesh_shards"] == 4

    @pytest.mark.slow
    def test_promote_composes_with_spill(self):
        # a budget-capped D=2 run spills to the host tier, THEN the
        # grant doubles the mesh and the run finishes wide — parity
        # (set semantics: shapes differ) vs an uncapped clean D=4 run
        devices = jax.devices()
        if len(devices) < 4:
            pytest.skip("need 4 devices")
        from stateright_tpu.service import RUNNING, StepDriver
        spilled = (TwoPhaseSys(4).checker()
                   .tpu_options(race=False, capacity=1 << 11,
                                max_capacity=1 << 11, fmax=8, kmax=64,
                                chunk_steps=2, retries=1, backoff=0.0,
                                mesh=_mesh(2))
                   .spawn_tpu())
        drv = StepDriver(spilled).start()
        deadline = time.monotonic() + 180.0
        while (drv.status == RUNNING
               and not spilled.profile().get("spills")
               and time.monotonic() < deadline):
            drv.step(1)
        spilled.request_promote(devices[2:4])
        drv.drain()
        clean = _run(lambda: TwoPhaseSys(4), capacity=1 << 12, fmax=16,
                     chunk_steps=2, mesh=_mesh(4))
        assert spilled.unique_state_count() == clean.unique_state_count()
        assert (set(spilled.generated_fingerprints())
                == set(clean.generated_fingerprints()))
        prof = spilled.profile()
        assert prof["promotes"] == 1
        assert prof["mesh_shards"] == 4
        assert prof["spills"] >= 1

    def test_degrade_then_promote_roundtrip(self, clean_2pc3_d4):
        # REGRESSION (elastic fleet): D=4 drops to D=2 on a transient
        # blame streak, then climbs back 2 -> 4 when the blamed chip is
        # released healthy — bit-identical to an uninterrupted D=4 run
        devices = jax.devices()
        if len(devices) < 4:
            pytest.skip("need 4 devices")

        faults = {"n": 0}

        def flaky(chunk, shards):
            # exactly two faults naming one chip: a blame streak at
            # D=4, inert afterwards so the climb back up stays clean
            if shards == 4 and faults["n"] < 2:
                faults["n"] += 1
                raise RuntimeError(
                    "UNAVAILABLE: device 3 fell off the mesh "
                    "(injected)")

        from stateright_tpu.service import RUNNING, StepDriver
        trace = []
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, retries=5, backoff=0.0,
                           blame_after=2, mesh=_mesh(4),
                           fault_hook=flaky, trace=trace)
              .spawn_tpu())
        drv = StepDriver(ck).start()
        deadline = time.monotonic() + 180.0
        while (drv.status == RUNNING
               and not ck.profile().get("degrades")
               and time.monotonic() < deadline):
            drv.step(1)
        assert ck.profile()["degrades"] == 1  # narrowed, still running
        # the blamed chip comes back: grant the dropped half back
        held = list(ck._mesh.devices.flat)
        gone = [d for d in devices[:4] if d not in held]
        assert len(gone) == 2
        ck.request_promote(gone)
        drv.drain()
        _assert_parity(ck, clean_2pc3_d4)
        prof = ck.profile()
        assert prof["degrades"] == 1
        assert prof["promotes"] == 1
        assert prof["mesh_shards"] == 4
        kinds = [e["ev"] for e in trace]
        assert kinds.index("degrade") < kinds.index("promote")
        from stateright_tpu.obs import validate_event
        for ev in trace:
            validate_event(ev)
