"""Resilience layer (checker/resilience.py + README § Resilience).

A transient backend fault injected mid-run must change NOTHING the
checker reports: discoveries, unique counts, and reached fingerprint
sets are pinned against an uninterrupted run across the single-chip and
sharded engines, pipelined and synchronous. Exhausted retries degrade
instead of dying — an ``autosave=`` checkpoint loads and completes via
``resume_from``; a raced run fails over to an un-budgeted host BFS; a
hung chunk sync is converted to a classified fault by the watchdog —
and ``bench.py`` always lands a valid JSON contract line, even with
every device workload forced to fail.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.checker.resilience import (  # noqa: E402
    CAPACITY_MARKERS, ChunkDeadlineError, FaultKind, RetryPolicy,
    classify_error)
from stateright_tpu.examples.paxos_packed import PackedPaxos  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _unavailable(msg="UNAVAILABLE: fake tunnel drop (injected)"):
    return RuntimeError(msg)


def _hook_at(k):
    """Raise a fake transient backend fault when chunk ``k`` syncs."""

    def hook(chunk):
        if chunk == k:
            raise _unavailable()

    return hook


def _run(mk, **opts):
    return (mk().checker().tpu_options(race=False, **opts)
            .spawn_tpu().join())


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("shards",))


def _assert_parity(faulty, clean):
    assert faulty.unique_state_count() == clean.unique_state_count()
    assert (faulty.generated_fingerprints()
            == clean.generated_fingerprints())
    assert set(faulty.discoveries()) == set(clean.discoveries())


class TestClassification:
    def test_transient_markers(self):
        for msg in ("UNAVAILABLE: TPU backend setup/compile error",
                    "DEADLINE_EXCEEDED: slice op",
                    "connection reset by peer",
                    "the tunnel collapsed"):
            assert classify_error(RuntimeError(msg)) \
                is FaultKind.TRANSIENT, msg
        assert classify_error(ChunkDeadlineError("hung")) \
            is FaultKind.TRANSIENT
        assert classify_error(ConnectionResetError()) \
            is FaultKind.TRANSIENT

    def test_capacity_markers(self):
        for msg in ("RESOURCE_EXHAUSTED: out of memory while trying",
                    "device hash table overflow while seeding",
                    "packed-state capacity overflow: ..."):
            assert classify_error(RuntimeError(msg)) \
                is FaultKind.CAPACITY, msg
        # the engines' real overflow messages stay capacity-classified
        for marker in CAPACITY_MARKERS:
            assert classify_error(RuntimeError(marker)) \
                is FaultKind.CAPACITY

    def test_programming_default_and_cause_chain(self):
        assert classify_error(ValueError("a model bug")) \
            is FaultKind.PROGRAMMING
        # a wrapper raised `from` a transient error keeps the class
        # (the degrade path's RuntimeError must stay failover-eligible)
        try:
            try:
                raise _unavailable()
            except RuntimeError as inner:
                raise RuntimeError("run failed after retries") from inner
        except RuntimeError as wrapped:
            assert classify_error(wrapped) is FaultKind.TRANSIENT

    def test_retry_policy_bounds(self):
        with pytest.raises(ValueError):
            RetryPolicy(retries=-1)
        p = RetryPolicy(retries=3, backoff=1.0)
        assert p.enabled
        for attempt in (1, 2, 3, 8):
            d = p.delay(attempt)
            assert 0.0 < d <= p.cap * (1 + p.jitter)
        assert RetryPolicy(retries=0).enabled is False
        assert RetryPolicy(retries=2, backoff=0.0).delay(1) == 0.0


class TestRetryParity:
    """Acceptance: an injected transient UNAVAILABLE on chunk k leaves
    discoveries and unique/generated fingerprint sets identical to the
    uninterrupted run, with profile()['retries'] == 1."""

    def test_single_chip_pipelined(self):
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2)
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, retries=2, backoff=0.0,
                      fault_hook=_hook_at(2))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1

    def test_single_chip_sync(self):
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2, pipeline=False)
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, pipeline=False, retries=2,
                      backoff=0.0, fault_hook=_hook_at(2))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1

    def test_sharded(self):
        mesh = _mesh(2)
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2, mesh=mesh)
        faulty = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                      chunk_steps=2, mesh=mesh, retries=2, backoff=0.0,
                      fault_hook=_hook_at(2))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1

    def test_host_props_and_witness_paths(self):
        # paxos: 'linearizable' is host-evaluated — the recovery must
        # re-arm the in-carry history dedup and keep memoized results
        clean = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                     chunk_steps=2)
        faulty = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                      chunk_steps=2, retries=2, backoff=0.0,
                      fault_hook=_hook_at(2))
        _assert_parity(faulty, clean)
        faulty.assert_properties()

    def test_mid_growth_recovery(self):
        # a fault landing after table growths: the re-seeded table must
        # re-insert the whole (grown) mirror
        clean = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16,
                     chunk_steps=2)
        faulty = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16,
                      chunk_steps=2, retries=2, backoff=0.0,
                      fault_hook=_hook_at(3))
        _assert_parity(faulty, clean)
        assert faulty.profile()["retries"] == 1
        assert clean.profile().get("grows", 0) > 0

    def test_retry_trace_events(self):
        trace = []
        _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
             chunk_steps=2, retries=2, backoff=0.0,
             fault_hook=_hook_at(2), trace=trace)
        retries = [e for e in trace if e["ev"] == "retry"]
        assert len(retries) == 1
        assert retries[0]["attempt"] == 1
        assert "UNAVAILABLE" in retries[0]["error"]
        from stateright_tpu.obs import validate_event
        for ev in trace:
            validate_event(ev)

    def test_sound_eventually_retry(self):
        # the lasso sweep must rebuild from the shadow's cross-run edge
        # records, not the (epoch-only) device logs
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph

        def cyc():
            return (PackedDGraph.with_property(
                Property.eventually("odd", lambda _, s: s % 2 == 1))
                .with_path([0, 2, 4, 2]))

        clean = (cyc().checker().sound_eventually()
                 .tpu_options(race=False, capacity=1 << 10,
                              chunk_steps=1).spawn_tpu().join())
        assert "odd" in clean.discoveries()
        faulty = (cyc().checker().sound_eventually()
                  .tpu_options(race=False, capacity=1 << 10,
                               chunk_steps=1, retries=2, backoff=0.0,
                               fault_hook=_hook_at(2))
                  .spawn_tpu().join())
        assert "odd" in faulty.discoveries()
        assert (faulty.generated_fingerprints()
                == clean.generated_fingerprints())

    def test_non_transient_faults_not_retried(self):
        def hook(chunk):
            if chunk == 2:
                raise ValueError("a genuine model bug")

        with pytest.raises(ValueError, match="model bug"):
            _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                 chunk_steps=2, retries=2, backoff=0.0, fault_hook=hook)


class TestAutosave:
    def test_exhausted_retries_write_loadable_checkpoint(self, tmp_path):
        path = tmp_path / "auto.npz"

        def hook(chunk):
            if chunk >= 2:
                raise _unavailable()

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, fmax=64,
                           chunk_steps=2, retries=1, backoff=0.0,
                           autosave=os.fspath(path), fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="resume_from"):
            ck.join()
        assert path.exists()
        assert ck.profile()["retries"] == 1
        assert ck.profile()["autosaves"] >= 1

        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12)
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path).spawn_tpu().join())
        assert resumed.unique_state_count() == 288
        assert (resumed.generated_fingerprints()
                == clean.generated_fingerprints())

    def test_periodic_autosave(self, tmp_path):
        path = tmp_path / "periodic.npz"
        trace = []
        ck = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                  chunk_steps=2, autosave=os.fspath(path),
                  autosave_interval=1, trace=trace)
        assert ck.profile()["autosaves"] >= 1
        assert path.exists()
        saves = [e for e in trace if e["ev"] == "autosave"]
        assert saves and all("path" in e and "unique" in e
                             for e in saves)
        # the final autosave resumes to the full reached set
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path).spawn_tpu().join())
        assert (resumed.generated_fingerprints()
                == ck.generated_fingerprints())

    def test_degrade_without_autosave_names_the_knob(self):
        def hook(chunk):
            raise _unavailable()

        ck = (TwoPhaseSys(3).checker()
              .tpu_options(race=False, capacity=1 << 12, retries=1,
                           backoff=0.0, fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="autosave"):
            ck.join()


class TestWatchdog:
    def test_stalled_sync_becomes_classified_fault(self):
        # the hook stalls one chunk's sync well past the deadline: the
        # watchdog must convert the hang into a transient fault the
        # retry loop recovers from
        def hook(chunk):
            if chunk == 2:
                time.sleep(5.0)

        trace = []
        clean = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                     chunk_steps=2)
        ck = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                  chunk_steps=2, retries=2, backoff=0.0,
                  chunk_deadline=0.3, fault_hook=hook, trace=trace)
        _assert_parity(ck, clean)
        assert ck.profile()["retries"] >= 1
        evs = {e["ev"] for e in trace}
        assert "watchdog" in evs and "retry" in evs

    def test_invalid_deadline_rejected(self):
        with pytest.raises(ValueError, match="chunk_deadline"):
            (TwoPhaseSys(3).checker()
             .tpu_options(race=False, chunk_deadline=0).spawn_tpu())


class TestFailover:
    def test_raced_transient_failure_falls_over_to_host(self):
        # race budget 0 retires the budgeted racer immediately; the
        # device dies with a transient fault; the un-budgeted host BFS
        # fallback must still answer the check
        def hook(chunk):
            raise _unavailable("UNAVAILABLE: permanent tunnel death")

        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, race_budget=0.0,
                           fault_hook=hook)
              .spawn_tpu().join())
        host = TwoPhaseSys(4).checker().spawn_bfs().join()
        assert ck.unique_state_count() == host.unique_state_count()
        assert (ck.generated_fingerprints()
                == host.generated_fingerprints())
        prof = ck.profile()
        assert prof["engine"] == "host"
        assert prof["failovers"] == 1
        ck.assert_properties()

    def test_programming_error_still_surfaces(self):
        # TwoPhaseSys(4): big enough that the budgeted racer cannot
        # finish before the decider's first tick retires it — the
        # device's programming error must surface, not fail over
        def hook(chunk):
            raise ValueError("a genuine model bug")

        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, race_budget=0.0,
                           fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(ValueError, match="model bug"):
            ck.join()

    def test_failover_opt_out(self):
        def hook(chunk):
            raise _unavailable()

        ck = (TwoPhaseSys(4).checker()
              .tpu_options(capacity=1 << 12, race_budget=0.0,
                           failover=False, fault_hook=hook)
              .spawn_tpu())
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            ck.join()


def _run_bench(*flags):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         *flags],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)


@pytest.mark.slow
class TestBenchContract:
    """bench.py must ALWAYS land a valid JSON contract line on stdout
    and exit 0 — the round-5 failure mode (rc=1, parsed=null) is
    pinned out in both the healthy and the all-device-workloads-dead
    shapes."""

    def test_smoke_contract_schema(self):
        proc = _run_bench()
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        payload = json.loads(line)
        for key in ("metric", "value", "unit", "vs_baseline", "backend",
                    "pipeline"):
            assert key in payload, key
        assert set(payload["pipeline"]) == {"on", "off"}
        assert payload["value"] is not None
        assert "partial" not in payload

    def test_forced_failure_still_lands_artifact(self):
        proc = _run_bench("--inject-fault")
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        payload = json.loads(line)
        assert payload["partial"] is True
        assert isinstance(payload["failed"], list) and payload["failed"]
        assert "device-pipelined" in payload["failed"]
