"""Pipelined vs synchronous chunk-loop parity (``tpu_options(pipeline=...)``).

The double-buffered dispatch (PR 2) may only change WHEN the host learns
things, never WHAT the search finds: on full-enumeration and
counterexample workloads the two modes must agree bit-for-bit on unique
counts, reached fingerprint sets, discoveries, and replayed
counterexample paths — on both the single-chip and the sharded engine,
including a crash-restart fault config. Also covers the new
``profile()`` overlap timers and the refcounted visitor replay
(``_visit_reached`` drops decoded states at backtrack instead of
retaining one per unique state).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.core import Property  # noqa: E402
from stateright_tpu.models.packed import PackedModel  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


def _run(mk, **opts):
    return (mk().checker()
            .tpu_options(race=False, **opts)
            .spawn_tpu().join())


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("shards",))


def _assert_full_parity(on, off):
    assert on.unique_state_count() == off.unique_state_count()
    assert on.generated_fingerprints() == off.generated_fingerprints()
    assert set(on.discoveries()) == set(off.discoveries())


class TestSingleChipParity:
    def test_2pc_full_enumeration(self):
        # 288 unique states; no host props — the pure device-loop path
        on = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64)
        off = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                   pipeline=False)
        assert on.unique_state_count() == 288
        _assert_full_parity(on, off)
        for name, path in on.discoveries().items():
            assert (path.into_actions()
                    == off.discoveries()[name].into_actions())

    def test_paxos_full_enumeration_with_host_props(self):
        # 265 unique; 'linearizable' is host-evaluated, so this drives
        # the in-carry history dedup + the stats-window representative
        # consumption (offset-anchored under pipelining)
        from stateright_tpu.examples.paxos_packed import PackedPaxos

        on = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64)
        off = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                   pipeline=False)
        assert on.unique_state_count() == 265
        _assert_full_parity(on, off)
        on.assert_properties()
        off.assert_properties()

    def test_write_once_crash_restart_full(self):
        # the PR-1 fault config: durable write-once under crash_restart
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce

        def mk():
            return PackedWriteOnce(2, durable=True).crash_restart(
                1, actors=[0])

        on = _run(mk, capacity=1 << 12)
        off = _run(mk, capacity=1 << 12, pipeline=False)
        assert on.unique_state_count() == 51
        _assert_full_parity(on, off)

    def test_write_once_volatile_counterexample_path(self):
        # early exit through a host-property discovery: the replayed
        # counterexample must be action-identical across modes (counts
        # may differ — the pipeline's speculative chunk is documented
        # extra exploration past a host-only exit)
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce

        def mk():
            return PackedWriteOnce(2, durable=False).crash_restart(
                1, actors=[0])

        on = _run(mk, capacity=1 << 12)
        off = _run(mk, capacity=1 << 12, pipeline=False)
        p_on = on.assert_any_discovery("linearizable")
        p_off = off.assert_any_discovery("linearizable")
        assert p_on.into_actions() == p_off.into_actions()

    def test_growth_parity(self):
        # capacity (and fmax, which bounds the pre-loop headroom bump)
        # small enough to force mid-run growth in both modes
        on = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16)
        off = _run(lambda: TwoPhaseSys(4), capacity=1 << 8, fmax=16,
                   pipeline=False)
        assert on.profile().get("grow", 0) > 0
        _assert_full_parity(on, off)

    def test_profile_overlap_timers(self):
        on = _run(lambda: TwoPhaseSys(3), capacity=1 << 12)
        prof = on.profile()
        for key in ("dispatch", "sync_stall", "host_overlap", "chunks"):
            assert key in prof, key
        off = _run(lambda: TwoPhaseSys(3), capacity=1 << 12,
                   pipeline=False)
        assert "sync_stall" in off.profile()


class TestShardedParity:
    @pytest.mark.parametrize("n_shards", [2, 8])
    def test_2pc_full_enumeration(self, n_shards):
        mesh = _mesh(n_shards)
        on = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                  mesh=mesh)
        off = _run(lambda: TwoPhaseSys(3), capacity=1 << 12, fmax=64,
                   mesh=mesh, pipeline=False)
        assert on.unique_state_count() == 288
        _assert_full_parity(on, off)

    def test_paxos_host_props_sharded(self):
        from stateright_tpu.examples.paxos_packed import PackedPaxos

        mesh = _mesh(2)
        on = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                  mesh=mesh)
        off = _run(lambda: PackedPaxos(1), capacity=1 << 12, fmax=64,
                   mesh=mesh, pipeline=False)
        assert on.unique_state_count() == 265
        _assert_full_parity(on, off)
        on.assert_properties()

    def test_write_once_crash_restart_sharded(self):
        from stateright_tpu.examples.write_once_packed import \
            PackedWriteOnce

        def mk():
            return PackedWriteOnce(2, durable=True).crash_restart(
                1, actors=[0])

        mesh = _mesh(2)
        on = _run(mk, capacity=1 << 12, mesh=mesh)
        off = _run(mk, capacity=1 << 12, mesh=mesh, pipeline=False)
        assert on.unique_state_count() == 51
        _assert_full_parity(on, off)

    def test_hint_with_mesh_raises(self):
        # satellite: the sharded engine must not silently ignore the
        # single-chip per-row compaction knob
        with pytest.raises(ValueError, match="hint"):
            (TwoPhaseSys(3).checker()
             .tpu_options(mesh=_mesh(2), hint=4)
             .spawn_tpu())


class TestHostPropFnsGuard:
    def test_mismatched_fns_fail_loudly(self):
        # satellite: a subclass changing properties without updating the
        # packed fast-path evaluators must not silently use stale
        # lambdas. The canonical form is name-keyed: an unknown name
        # (e.g. a renamed property whose evaluator key was not updated)
        # fails at spawn
        from stateright_tpu.examples.paxos_packed import PackedPaxos

        model = PackedPaxos(1)
        assert isinstance(model.host_property_fns, dict)
        model.host_property_fns = {**model.host_property_fns,
                                   "bogus": lambda row: True}
        with pytest.raises(ValueError, match="host_property_fns"):
            model.checker().tpu_options(race=False).spawn_tpu()

    def test_legacy_positional_list_length_guard(self):
        # the legacy positional-list form keeps the PR 2 length guard
        from stateright_tpu.examples.paxos_packed import PackedPaxos

        model = PackedPaxos(1)
        model.host_property_fns = [lambda row: True, lambda row: True]
        with pytest.raises(ValueError, match="host_property_fns"):
            model.checker().tpu_options(race=False).spawn_tpu()

    def test_name_keyed_fns_bind_by_name(self):
        # a dict missing a declared host property also fails loudly
        from stateright_tpu.examples.paxos_packed import PackedPaxos

        model = PackedPaxos(1)
        model.host_property_fns = {"wrong name": lambda row: True}
        with pytest.raises(ValueError, match="missing"):
            model.checker().tpu_options(race=False).spawn_tpu()


class _CombModel(PackedModel):
    """Deep chain with one leaf per spine node: spine 0..depth, leaves
    depth+1+x. The adversarial shape for visitor-replay memory — the old
    ``_visit_reached`` retained one decoded state per unique state for
    the whole replay; the refcounted DFS drops each leaf (and each
    completed spine suffix) at backtrack."""

    packed_width = 1
    max_actions = 2

    def __init__(self, depth: int):
        self.depth = depth

    def cache_key(self):
        return ("comb", self.depth)

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        if state < self.depth:
            actions.extend(["step", "leaf"])

    def next_state(self, state, action):
        return state + 1 if action == "step" else state + self.depth + 1

    def properties(self):
        def at_end(model, state):
            return state == model.depth
        return [Property.sometimes("reaches end", at_end)]

    def encode(self, state):
        return np.array([state], dtype=np.uint32)

    def decode(self, words):
        return int(words[0])

    def packed_step(self, words):
        x = words[0]
        succ = jnp.stack([
            jnp.stack([x + 1]),
            jnp.stack([x + self.depth + 1]),
        ]).astype(jnp.uint32)
        on_spine = x < self.depth
        valid = jnp.stack([on_spine, on_spine])
        return succ, valid

    def packed_properties(self, words):
        return jnp.stack([words[0] == self.depth])


class TestVisitorReplayMemory:
    def test_deep_chain_refcounted_drop(self):
        from stateright_tpu.checker.visitor import StateRecorder

        depth = 96
        total = 2 * depth + 1  # spine 0..depth plus depth leaves
        rec, states = StateRecorder.new_with_accessor()
        ck = (_CombModel(depth).checker().visitor(rec)
              .tpu_options(race=False, capacity=1 << 12, fmax=32)
              .spawn_tpu().join())
        assert ck.unique_state_count() == total
        assert set(states()) == set(range(total))
        peak = ck.profile()["visit_peak_resident"]
        # the spine itself is a real path, so O(depth) states are live
        # at the deepest visit — but never one per unique state
        assert peak <= depth + 3
        assert peak < total

    def test_deep_chain_paths_valid(self):
        from stateright_tpu.checker.visitor import PathRecorder

        # PathRecorder re-validates every visited path on construction;
        # one path per reached state, each ending at its state
        rec, paths = PathRecorder.new_with_accessor()
        ck = (_CombModel(24).checker().visitor(rec)
              .tpu_options(race=False, capacity=1 << 10, fmax=16)
              .spawn_tpu().join())
        got = paths()
        assert len(got) == ck.unique_state_count()
        ends = {p.last_state() for p in got}
        assert ends == set(range(2 * 24 + 1))
