"""Multi-process host BFS tests: set-equality and verdict parity with the
sequential engine across model families (the reference's multithreaded
runs promise the same — `bfs.rs:29-30`, `:138-150`)."""

import pytest

from stateright_tpu.actor.test_util import PingPongCfg
from stateright_tpu.models.fixtures import DGraph, LinearEquation
from stateright_tpu.core import Property
from stateright_tpu.models.twopc import TwoPhaseSys


def par(model, n=4):
    return model.checker().threads(n).spawn_bfs().join()


class TestParallelBfs:
    def test_full_enumeration_matches_sequential(self):
        model = TwoPhaseSys(5)  # 8,832 (2pc.rs:133)
        p = par(model)
        s = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert p.unique_state_count() == 8832
        assert p.generated_fingerprints() == s.generated_fingerprints()

    def test_discovery_replays(self):
        p = par(LinearEquation(2, 10, 14))
        found = p.assert_any_discovery("solvable")
        x, y = found.last_state()
        assert (2 * x + 10 * y) & 0xFF == 14

    def test_actor_model_counts(self):
        # ping_pong lossless nondup max 5 = 11 states (model.rs:642); the
        # fixture deliberately includes falsifiable properties, so compare
        # verdicts with the sequential engine rather than asserting clean
        model = PingPongCfg(maintains_history=False, max_nat=5).into_model()
        p = par(model)
        s = (PingPongCfg(maintains_history=False, max_nat=5).into_model()
             .checker().spawn_bfs().join())
        assert p.unique_state_count() == 11
        assert set(p.discoveries()) == set(s.discoveries())

    def test_eventually_semantics_match(self):
        def eventually_odd():
            return Property.eventually("odd", lambda _, s: s % 2 == 1)
        g = (DGraph.with_property(eventually_odd())
             .with_path([0, 1]).with_path([0, 2]))
        p = par(g)
        assert p.discovery("odd").into_states() == [0, 2]
        # the fixme pin holds in parallel too (accepted unsoundness)
        g2 = DGraph.with_property(eventually_odd()).with_path([0, 2, 4, 2])
        assert par(g2).discovery("odd") is None

    def test_target_state_count(self):
        p = (LinearEquation(2, 4, 7).checker().threads(2)
             .target_state_count(500).spawn_bfs().join())
        assert p.state_count() >= 500

    def test_visitor_falls_back_to_sequential(self):
        from stateright_tpu.checker.bfs import BfsChecker
        from stateright_tpu.checker.visitor import StateRecorder
        ck = (LinearEquation(2, 10, 14).checker().threads(4)
              .visitor(StateRecorder()).spawn_bfs())
        assert isinstance(ck, BfsChecker)
