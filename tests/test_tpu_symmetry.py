"""Symmetry reduction on the device engines — a capability the reference
restricts to its DFS engine (`dfs.rs:260-285`). Dedup (and the host
mirror) work in canonical-orbit space via the model's
``packed_representative``; enqueued rows stay original, properties are
evaluated on originals, and witness paths replay in canonical-fingerprint
space."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.examples.increment import Increment  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices")
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("shards",))


class TestPackedRepresentative:
    """Device canonicalization must be bit-exact with the host's."""

    @pytest.mark.parametrize("model,n_states", [
        (TwoPhaseSys(3), 300), (Increment(2), 50)])
    def test_matches_host(self, model, n_states):
        seen, queue = set(), list(model.init_states())
        canon = jax.jit(model.packed_representative)
        while queue and len(seen) < n_states:
            s = queue.pop()
            fp = model.fingerprint(s)
            if fp in seen:
                continue
            seen.add(fp)
            host = model.encode(model.representative(s))
            dev = np.asarray(canon(jnp.asarray(model.encode(s))))
            assert np.array_equal(dev, host), s
            queue.extend(model.next_states(s))


class TestDeviceSymmetry:
    @pytest.mark.slow  # ~42s warm (5-RM 2pc under symmetry); the
    # complete_symmetry + sharded symmetry pins stay tier-1
    def test_2pc_sym_reduces(self):
        # 5 RMs: 8,832 plain states (2pc.rs:133); under symmetry the DFS
        # oracle reaches 665 (2pc.rs:138). 2pc's representative breaks
        # ties by original position, so the exact reduced count is
        # ORDER-specific: the sound range is [314, 1092] — 314 true
        # RM-permutation orbits and 1092 distinct representative keys
        # over the full reachable set (both computed by brute force over
        # all 120 permutations; the reference's 665 is just its DFS
        # order's value inside that range). The device engine must land
        # in the sound range, COVER EVERY REACHABLE ORBIT (the actual
        # soundness obligation), be deterministic, and reach the same
        # verdicts.
        from itertools import permutations

        from stateright_tpu.checker.representative import RewritePlan

        model = TwoPhaseSys(5)
        ck = (model.checker().symmetry_fn(model.representative)
              .tpu_options(capacity=1 << 12, fmax=64)
              .spawn_tpu().join())
        n = ck.unique_state_count()
        assert 314 <= n <= 1092, n
        ck.assert_properties()

        # soundness oracle: the canonical keys the engine reached must
        # cover all 314 reachable orbits
        plain = TwoPhaseSys(5).checker().spawn_bfs().join()
        states = [model.decode(model.encode(s))
                  for s in self._all_states(model)]
        assert len(states) == plain.unique_state_count() == 8832

        def apply_plan(s, plan):
            rm_state, tm_state, tm_prepared, msgs = s
            return (tuple(plan.reindex(rm_state)), tm_state,
                    tuple(plan.reindex(tm_prepared)),
                    frozenset(plan.rewrite(m) if m < 16 else m
                              for m in msgs))

        perms = [RewritePlan(list(p)) for p in permutations(range(5))]
        orbit_of_key = {}
        all_orbits = set()
        for s in states:
            okey = min(model.fingerprint(apply_plan(s, p)) for p in perms)
            all_orbits.add(okey)
            orbit_of_key[model.fingerprint(model.representative(s))] = okey
        assert len(all_orbits) == 314
        reached = {orbit_of_key[fp]
                   for fp in ck.generated_fingerprints()}
        assert reached == all_orbits
        # deterministic across runs
        ck2 = (TwoPhaseSys(5).checker()
               .symmetry_fn(TwoPhaseSys(5).representative)
               .tpu_options(capacity=1 << 12, fmax=64)
               .spawn_tpu().join())
        assert ck2.unique_state_count() == n
        # witnesses replay through canonical-fingerprint space
        for name in ("abort agreement", "commit agreement"):
            path = ck.discovery(name)
            prop = model.property(name)
            assert prop.condition(model, path.last_state())

    @staticmethod
    def _all_states(model):
        seen, out = set(), []
        frontier = list(model.init_states())
        while frontier:
            nxt = []
            for s in frontier:
                fp = model.fingerprint(s)
                if fp in seen:
                    continue
                seen.add(fp)
                out.append(s)
                acts = []
                model.actions(s, acts)
                for a in acts:
                    t = model.next_state(s, a)
                    if t is not None and model.within_boundary(t):
                        nxt.append(t)
            frontier = nxt
        return out

    def test_2pc_complete_symmetry_pins_orbit_count(self):
        # the orbit-invariant (complete per-RM record sort)
        # representative makes every engine reduce to EXACTLY the orbit
        # partition: 314 classes at n=5 (NOTES.md brute force), engine-
        # and order-independent — unlike the reference representative,
        # whose counts are exploration-order-specific
        def mk():
            return TwoPhaseSys(5, complete_symmetry=True)

        host = mk().checker().symmetry_fn(mk().representative) \
            .spawn_dfs().join()
        assert host.unique_state_count() == 314
        dev = (mk().checker().symmetry_fn(mk().representative)
               .tpu_options(capacity=1 << 12, fmax=64)
               .spawn_tpu().join())
        assert dev.unique_state_count() == 314
        sharded = (mk().checker().symmetry_fn(mk().representative)
                   .tpu_options(capacity=1 << 12, fmax=64,
                                mesh=_mesh(2))
                   .spawn_tpu().join())
        assert sharded.unique_state_count() == 314
        # same verdicts as the unreduced model
        dev.assert_properties()

    def test_increment_sym_8(self):
        # 13 plain states vs 8 canonical (increment.rs:36-105)
        plain = (Increment(2).checker()
                 .tpu_options(capacity=1 << 10, fmax=16)
                 .spawn_tpu().join())
        model = Increment(2)
        sym = (model.checker().symmetry_fn(model.representative)
               .tpu_options(capacity=1 << 10, fmax=16)
               .spawn_tpu().join())
        assert plain.unique_state_count() == 13
        assert sym.unique_state_count() == 8
        # the deliberate race is still caught under reduction
        assert sym.discovery("fin") is not None

    def test_level_mode_agrees(self):
        # increment(2) explores its whole 8-class reduced space, so both
        # single-chip modes and the DFS oracle agree exactly (early-exit
        # configs are engine-order-specific, like the reference's
        # multithreaded runs)
        model = Increment(2)
        dev = (model.checker().symmetry_fn(model.representative)
               .tpu_options(capacity=1 << 10, fmax=16, mode="device")
               .spawn_tpu().join())
        model2 = Increment(2)
        lvl = (model2.checker().symmetry_fn(model2.representative)
               .tpu_options(capacity=1 << 10, fmax=16, mode="level")
               .spawn_tpu().join())
        # (DFS stops at its own early-exit point — 6 here — since "fin"
        # is deliberately falsifiable; the level-ordered engines agree
        # with the doc's 8-class reduced space, increment.rs:36-105)
        assert (dev.unique_state_count() == lvl.unique_state_count() == 8)

    def test_sharded_sym(self):
        # value-complete representative + full enumeration: exact
        # agreement with the DFS oracle across shard counts
        model = Increment(2)
        sharded = (model.checker().symmetry_fn(model.representative)
                   .tpu_options(mesh=_mesh(2), capacity=1 << 10, fmax=16)
                   .spawn_tpu().join())
        assert sharded.unique_state_count() == 8
        assert sharded.discovery("fin") is not None

    def test_requires_packed_representative(self):
        from stateright_tpu.models.packed import PackedLinearEquation
        model = PackedLinearEquation(2, 10, 14)
        with pytest.raises(NotImplementedError, match="packed_repr"):
            (model.checker().symmetry_fn(lambda s: s)
             .spawn_tpu())
