"""Explorer tests — handler-level (like the reference's
`explorer.rs:242-447`, which invokes handlers directly) plus a live HTTP
smoke test on an ephemeral port."""

import json
import urllib.request

import pytest

from stateright_tpu.checker.explorer import (NotFound, Snapshot,
                                             parse_fingerprints, serve,
                                             state_views, status_view)
from stateright_tpu.models.fixtures import LinearEquation
from stateright_tpu.models.twopc import TwoPhaseSys


class TestParseFingerprints:
    def test_empty(self):
        assert parse_fingerprints("") == []
        assert parse_fingerprints("/") == []

    def test_path(self):
        assert parse_fingerprints("/12/34/") == [12, 34]

    def test_junk_404(self):
        with pytest.raises(NotFound):
            parse_fingerprints("/12/junk")


class TestStateViews:
    def test_init_states(self):
        model = TwoPhaseSys(2)
        views = state_views(model, [])
        assert len(views) == len(model.init_states())
        v = views[0]
        assert "state" in v and "fingerprint" in v
        assert "action" not in v
        assert int(v["fingerprint"]) == model.fingerprint(
            model.init_states()[0])

    def test_steps_from_init(self):
        model = TwoPhaseSys(2)
        init = model.init_states()[0]
        views = state_views(model, [model.fingerprint(init)])
        actions = []
        model.actions(init, actions)
        assert len(views) == len(actions)
        # every view carries the formatted action; reachable ones carry
        # the successor state + its fingerprint
        for v in views:
            assert "action" in v
        followed = [v for v in views if "state" in v]
        assert followed
        for v in followed:
            assert int(v["fingerprint"]) != 0

    def test_ignored_action_rows(self):
        # an actor that ignores a message makes its Deliver a no-op
        # (next_state -> None): the server must still return the action
        # row, without "state" (`explorer.rs:224-231`)
        from stateright_tpu.actor import ActorModel, Id, Out
        from stateright_tpu.actor.core import Actor, ScriptedActor

        class DeafActor(Actor):
            def on_start(self, id: Id, o: Out):
                return 0

            def on_msg(self, id, state, src, msg, o):
                return None  # ignore everything

        model = (ActorModel(cfg=None)
                 .actor(DeafActor())
                 .actor(ScriptedActor([(Id(0), "hello")])))
        init = model.init_states()[0]
        views = state_views(model, [model.fingerprint(init)])
        ignored = [v for v in views if "state" not in v]
        assert ignored, "expected the ignored delivery row"
        assert all("action" in v for v in ignored)

    def test_unknown_fingerprint_404(self):
        model = TwoPhaseSys(2)
        with pytest.raises(NotFound):
            state_views(model, [12345])  # no init state with this fp

    def test_deep_path_replay(self):
        model = TwoPhaseSys(2)
        init = model.init_states()[0]
        fp0 = model.fingerprint(init)
        first = state_views(model, [fp0])
        nxt = next(v for v in first if "state" in v)
        fp1 = int(nxt["fingerprint"])
        second = state_views(model, [fp0, fp1])
        assert any("state" in v for v in second)


class TestStatusView:
    def test_fields(self):
        checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
        snap = Snapshot()
        view = status_view(checker, snap)
        assert view["done"] is True
        assert view["model"] == "LinearEquation"
        assert view["state_count"] >= view["unique_state_count"] > 0
        (expectation, name, discovery) = view["properties"][0]
        assert (expectation, name) == ("sometimes", "solvable")
        # the discovery is an encoded fingerprint path that parses
        assert discovery is not None
        fps = [int(p) for p in discovery.split("/")]
        assert len(fps) >= 1

    def test_snapshot_visitor(self):
        snap = Snapshot()
        checker = (LinearEquation(2, 10, 14).checker()
                   .visitor(snap).spawn_bfs().join())
        assert checker.is_done()
        assert snap.actions is not None  # recorded one visited path


class TestHttpSmoke:
    def test_end_to_end(self):
        builder = TwoPhaseSys(2).checker()
        # block=False returns a ServeHandle: legacy tuple-unpack still
        # works, and .port/.shutdown() give a clean teardown
        handle = serve(builder, ("127.0.0.1", 0), block=False)
        checker, server = handle
        base = f"http://127.0.0.1:{handle.port}"
        try:
            checker.join()

            with urllib.request.urlopen(f"{base}/.status") as r:
                status = json.loads(r.read())
            assert status["done"] is True
            assert status["unique_state_count"] > 0

            with urllib.request.urlopen(f"{base}/.states/") as r:
                inits = json.loads(r.read())
            assert inits and "fingerprint" in inits[0]

            fp = inits[0]["fingerprint"]
            with urllib.request.urlopen(f"{base}/.states/{fp}") as r:
                steps = json.loads(r.read())
            assert steps and "action" in steps[0]

            with urllib.request.urlopen(f"{base}/") as r:
                page = r.read().decode()
            assert "Explorer" in page

            try:
                urllib.request.urlopen(f"{base}/.states/junk")
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as exc:
                assert exc.code == 404
        finally:
            handle.shutdown()

    def test_handle_shutdown_stops_checker_thread(self):
        # the satellite fix: tests used to have no clean way to stop
        # the server AND its background checking thread — shutdown()
        # cancels the run and joins the engine thread
        handle = serve(TwoPhaseSys(2).checker(), ("127.0.0.1", 0),
                       block=False)
        assert handle.port > 0
        assert handle.url.endswith(str(handle.port))
        handle.shutdown()
        thread = getattr(handle.checker, "_thread", None)
        assert thread is None or not thread.is_alive()
        # the socket is really closed: a fresh connection fails
        with pytest.raises(Exception):
            urllib.request.urlopen(f"{handle.url}/.status", timeout=2)


class TestTpuEngineExplorer:
    def test_device_run_behind_browser(self):
        # serve(engine="tpu"): /.status counts come live from the device
        # chunk loop; /.states replays through the host model
        import pytest

        pytest.importorskip("jax")
        builder = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12))
        handle = serve(builder, ("127.0.0.1", 0), block=False,
                       engine="tpu")
        checker = handle.checker
        base = handle.url
        try:
            # /.status responds mid-run too (counts may be partial)
            with urllib.request.urlopen(f"{base}/.status") as r:
                json.loads(r.read())
            checker.join()
            with urllib.request.urlopen(f"{base}/.status") as r:
                status = json.loads(r.read())
            assert status["done"] is True
            assert status["unique_state_count"] == 288
            # the sometimes-properties carry encoded discovery paths
            discs = [p for p in status["properties"] if p[2]]
            assert discs
            with urllib.request.urlopen(f"{base}/.states/") as r:
                inits = json.loads(r.read())
            fp = inits[0]["fingerprint"]
            with urllib.request.urlopen(f"{base}/.states/{fp}") as r:
                steps = json.loads(r.read())
            assert steps and "action" in steps[0]
        finally:
            handle.shutdown()

    def test_unknown_engine_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="unknown explorer engine"):
            serve(TwoPhaseSys(2).checker(), ("127.0.0.1", 0),
                  block=False, engine="warp")


class TestActorSvg:
    def test_sequence_diagram(self):
        # ping_pong: Deliver arrows + lifelines render; the svg reaches the
        # states endpoint (explorer.rs:200-232)
        from stateright_tpu.actor.test_util import PingPongCfg
        model = PingPongCfg(maintains_history=False,
                            max_nat=3).into_model()
        fp0 = model.fingerprint(model.init_states()[0])
        views = state_views(model, [fp0])
        with_state = [v for v in views if "state" in v]
        assert with_state
        v = with_state[0]
        assert "svg" in v and v["svg"].startswith("<svg")
        assert "svg-actor-timeline" in v["svg"]
        assert "marker-end" in v["svg"]  # at least one delivery arrow
