"""Checkpoint/resume tests (SURVEY.md §5): stop a bounded device run
mid-search, save, resume in a fresh checker, and converge to the same
reached set as an uninterrupted run."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.packed import PackedLinearEquation  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


class TestCheckpointResume:
    def test_resume_converges_to_same_set(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        model = TwoPhaseSys(5)  # 8,832 states (2pc.rs:133)
        partial = (model.checker()
                   .tpu_options(capacity=1 << 14, resumable=True, fmax=64,
                                chunk_steps=4)
                   .target_state_count(2000)
                   .spawn_tpu().join())
        assert partial.state_count() >= 2000
        assert partial.unique_state_count() < 8832
        partial.save(path)

        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14)
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832
        full = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert (resumed.generated_fingerprints()
                == full.generated_fingerprints())
        # resumed counts continue from the checkpoint
        assert resumed.state_count() >= partial.state_count()

    def test_resumed_paths_replay(self, tmp_path):
        # discoveries found after a resume reconstruct valid paths through
        # the stitched mirror (parents from both run segments)
        path = tmp_path / "ckpt.npz"
        model = PackedLinearEquation(3, 5, 81)
        partial = (model.checker()
                   .tpu_options(capacity=1 << 14, resumable=True, fmax=32,
                                chunk_steps=2)
                   .target_state_count(300)
                   .spawn_tpu().join())
        if partial.discovery("solvable") is None:
            partial.save(path)
            resumed = (PackedLinearEquation(3, 5, 81).checker()
                       .tpu_options(capacity=1 << 14)
                       .resume_from(path)
                       .spawn_tpu().join())
            found = resumed.assert_any_discovery("solvable")
        else:
            found = partial.assert_any_discovery("solvable")
        x, y = found.last_state()
        assert (3 * x + 5 * y) & 0xFF == 81

    def test_save_requires_resumable(self):
        ck = (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
              .spawn_tpu().join())
        with pytest.raises(RuntimeError, match="resumable"):
            ck.save("/tmp/nope.npz")

    def test_save_roundtrip_preserves_discoveries(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(capacity=1 << 12, resumable=True)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 288
        ck.save(path)
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path)
                   .spawn_tpu().join())
        # nothing left to search; counts and discoveries carry over
        assert resumed.unique_state_count() == 288
        assert set(resumed.discoveries()) == set(ck.discoveries())

    def test_resume_rejects_different_model(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(capacity=1 << 12, resumable=True)
              .spawn_tpu().join())
        ck.save(path)
        with pytest.raises(RuntimeError, match="different model"):
            (TwoPhaseSys(4).checker().tpu_options(capacity=1 << 12)
             .resume_from(path).spawn_tpu().join())


class TestShardedCheckpointResume:
    """Checkpoint/resume on the SPMD sharded engine: the format is
    shard-agnostic, so a checkpoint written on one mesh resumes on a
    different shard count (or single-chip) — the frontier re-routes by
    fingerprint ownership at seed time."""

    def _mesh(self, n):
        from jax.sharding import Mesh
        return Mesh(jax.devices("cpu")[:n], ("shards",))

    def _partial(self, path, n_shards):
        model = TwoPhaseSys(5)  # 8,832 states (2pc.rs:133)
        partial = (model.checker()
                   .tpu_options(capacity=1 << 14, resumable=True,
                                fmax=32, chunk_steps=4,
                                mesh=self._mesh(n_shards))
                   .target_state_count(2000)
                   .spawn_tpu().join())
        assert partial.unique_state_count() < 8832
        partial.save(path)
        return partial

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_sharded_partial_resumes_sharded(self, tmp_path, n_shards):
        path = tmp_path / "ckpt.npz"
        partial = self._partial(path, n_shards)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14,
                                mesh=self._mesh(n_shards))
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832
        full = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert (resumed.generated_fingerprints()
                == full.generated_fingerprints())
        assert resumed.state_count() >= partial.state_count()

    def test_two_shard_checkpoint_resumes_on_four(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        self._partial(path, 2)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14, mesh=self._mesh(4))
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832
        full = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert (resumed.generated_fingerprints()
                == full.generated_fingerprints())

    def test_sharded_checkpoint_resumes_single_chip(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        self._partial(path, 2)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14)
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832

    def test_single_chip_checkpoint_resumes_sharded(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        partial = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14, resumable=True,
                                fmax=64, chunk_steps=4)
                   .target_state_count(2000)
                   .spawn_tpu().join())
        assert partial.unique_state_count() < 8832
        partial.save(path)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14, mesh=self._mesh(2))
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832


class TestCheckpointModes:
    """Round-4 closure of the checkpoint matrix: save()/resume_from under
    symmetry reduction and sound_eventually (single-chip and sharded),
    with the canonical/node-key -> original-fp translation serialized."""

    def _mesh(self, n):
        import jax
        from jax.sharding import Mesh
        devices = jax.devices()
        if len(devices) < n:
            pytest.skip(f"need {n} devices")
        return Mesh(np.array(devices[:n]), ("shards",))

    def test_symmetry_roundtrip(self, tmp_path):
        # increment(2): value-complete representative -> deterministic 8
        # canonical classes (increment.rs:36-105), so the resumed run
        # must converge to exactly the uninterrupted reduced set
        from stateright_tpu.examples.increment import Increment
        path = tmp_path / "sym.npz"
        model = Increment(2)
        partial = (model.checker().symmetry_fn(model.representative)
                   .tpu_options(capacity=1 << 10, fmax=4, chunk_steps=1,
                                resumable=True)
                   .target_state_count(3)
                   .spawn_tpu().join())
        partial.save(path)
        m2 = Increment(2)
        resumed = (m2.checker().symmetry_fn(m2.representative)
                   .tpu_options(capacity=1 << 10, fmax=4)
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8
        # witnesses replay through concrete states via the restored
        # _orig_of translation
        assert resumed.discovery("fin") is not None

    def test_sound_roundtrip_finds_rejoin(self, tmp_path):
        # the rejoin counterexample the sound mode exists for must
        # survive a save/resume across the node-keyed mirror
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph

        # one shared property object: the fixture's cache key includes
        # its identity, and resume checks the model tag matches
        prop = Property.eventually("odd", lambda _, s: s % 2 == 1)

        def graph():
            return (PackedDGraph.with_property(prop)
                    .with_path([0, 2, 4]).with_path([1, 4, 6]))

        path = tmp_path / "sound.npz"
        partial = (graph().checker().sound_eventually()
                   .tpu_options(capacity=1 << 10, fmax=4, chunk_steps=1,
                                resumable=True)
                   .target_state_count(2)
                   .spawn_tpu().join())
        if partial.discovery("odd") is not None:
            pytest.skip("partial run already finished")  # nothing to pin
        partial.save(path)
        resumed = (graph().checker().sound_eventually()
                   .tpu_options(capacity=1 << 10, fmax=4)
                   .resume_from(path)
                   .spawn_tpu().join())
        found = resumed.assert_any_discovery("odd")
        # the counterexample path never satisfies the eventually property
        assert all(s % 2 == 0 for s in found.into_states())

    def test_sound_checkpoint_resumes_on_mesh(self, tmp_path):
        # a single-chip sound checkpoint re-routes onto a 2-shard mesh
        # (node-key owner routing must match the in-loop computation)
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph

        prop = Property.eventually("odd", lambda _, s: s % 2 == 1)

        def graph():
            return (PackedDGraph.with_property(prop)
                    .with_path([0, 2, 4]).with_path([1, 4, 6]))

        path = tmp_path / "sound_mesh.npz"
        partial = (graph().checker().sound_eventually()
                   .tpu_options(capacity=1 << 10, fmax=4, chunk_steps=1,
                                resumable=True)
                   .target_state_count(2)
                   .spawn_tpu().join())
        if partial.discovery("odd") is not None:
            pytest.skip("partial run already finished")
        partial.save(path)
        resumed = (graph().checker().sound_eventually()
                   .tpu_options(capacity=1 << 10, fmax=4,
                                mesh=self._mesh(2))
                   .resume_from(path)
                   .spawn_tpu().join())
        resumed.assert_any_discovery("odd")

    def test_mode_mismatch_rejected(self, tmp_path):
        # resuming a sound checkpoint without sound_eventually would
        # silently misinterpret node keys as state fingerprints
        from stateright_tpu.core import Property
        from stateright_tpu.models.fixtures import PackedDGraph

        prop = Property.eventually("odd", lambda _, s: s % 2 == 1)
        g = (PackedDGraph.with_property(prop)
             .with_path([0, 2, 4]).with_path([1, 4, 6]))
        path = tmp_path / "mismatch.npz"
        partial = (g.checker().sound_eventually()
                   .tpu_options(capacity=1 << 10, fmax=4, chunk_steps=1,
                                resumable=True)
                   .target_state_count(2)
                   .spawn_tpu().join())
        if partial.discovery("odd") is not None:
            pytest.skip("partial run already finished")
        partial.save(path)
        g2 = (PackedDGraph.with_property(prop)
              .with_path([0, 2, 4]).with_path([1, 4, 6]))
        with pytest.raises(RuntimeError, match="semantics"):
            (g2.checker()
             .tpu_options(capacity=1 << 10, fmax=4, race=False)
             .resume_from(path)
             .spawn_tpu().join())


def test_save_with_lasso_witness_roundtrips(tmp_path):
    # a lasso discovery is a list-valued fingerprint path; save()/resume
    # metadata must round-trip it (round-5 regression)
    import pytest
    pytest.importorskip("jax")
    from stateright_tpu.core import Property
    from stateright_tpu.models.fixtures import PackedDGraph

    # one property object: the model config tag keys on the condition's
    # identity, and resume requires matching tags
    prop = Property.eventually("odd", lambda _, s: s % 2 == 1)
    g = (PackedDGraph.with_property(prop).with_path([0, 2, 4, 2]))
    c = (g.checker().sound_eventually()
         .tpu_options(capacity=1 << 10, fmax=16, resumable=True)
         .spawn_tpu().join())
    assert c.discovery("odd") is not None
    p = tmp_path / "lasso.npz"
    c.save(str(p))
    g2 = (PackedDGraph.with_property(prop).with_path([0, 2, 4, 2]))
    c2 = (g2.checker().sound_eventually()
          .tpu_options(capacity=1 << 10, fmax=16)
          .resume_from(str(p)).spawn_tpu().join())
    states = c2.assert_any_discovery("odd").into_states()
    assert not any(s % 2 == 1 for s in states)


@pytest.mark.faults
class TestCheckpointIdentityAndCorruption:
    """A checkpoint must refuse to resume under ANY identity drift —
    different model config, different packed width, different fingerprint
    algorithm — and a damaged file must raise one actionable error, never
    a numpy/zipfile traceback."""

    def _saved(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(capacity=1 << 12, resumable=True)
              .spawn_tpu().join())
        ck.save(path)
        return path

    def test_different_packed_width_refused(self, tmp_path):
        from stateright_tpu.examples.write_once_packed import PackedWriteOnce

        path = tmp_path / "ckpt.npz"
        ck = (PackedWriteOnce(1, net_capacity=8).checker()
              .tpu_options(capacity=1 << 12, resumable=True, race=False)
              .spawn_tpu().join())
        ck.save(path)
        # net_capacity=4 shrinks the packed row: the saved rows cannot
        # be reinterpreted, so resume must refuse with the two tags
        with pytest.raises(RuntimeError, match="different model config"):
            (PackedWriteOnce(1, net_capacity=4).checker()
             .tpu_options(capacity=1 << 12, race=False)
             .resume_from(path).spawn_tpu().join())

    def test_different_fp_version_refused(self, tmp_path, monkeypatch):
        path = self._saved(tmp_path)
        import importlib

        fingerprint_mod = importlib.import_module(
            "stateright_tpu.fingerprint")
        monkeypatch.setattr(fingerprint_mod, "FP_VERSION", 999)
        # old-scheme fingerprints would silently fail to dedup against
        # newly computed ones; the tag embeds fpv and must refuse
        with pytest.raises(RuntimeError, match="different model config"):
            (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
             .resume_from(path).spawn_tpu().join())

    def test_different_model_config_refused(self, tmp_path):
        path = self._saved(tmp_path)
        with pytest.raises(RuntimeError, match="different model config"):
            (TwoPhaseSys(4).checker().tpu_options(capacity=1 << 12)
             .resume_from(path).spawn_tpu().join())

    def test_garbage_file_raises_clear_error(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        path.write_bytes(b"this is not a checkpoint")
        with pytest.raises(RuntimeError, match="corrupt, truncated"):
            (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
             .resume_from(path).spawn_tpu().join())

    def test_truncated_file_raises_clear_error(self, tmp_path):
        path = self._saved(tmp_path)
        blob = path.read_bytes()
        path.write_bytes(blob[:len(blob) // 2])
        with pytest.raises(RuntimeError, match="corrupt, truncated"):
            (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
             .resume_from(path).spawn_tpu().join())

    def test_interrupted_save_never_clobbers_good_checkpoint(
            self, tmp_path, monkeypatch):
        path = self._saved(tmp_path)
        good = path.read_bytes()
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(capacity=1 << 12, resumable=True)
              .spawn_tpu().join())

        def boom(*args, **kwargs):
            raise OSError("disk full")

        monkeypatch.setattr(np, "savez_compressed", boom)
        with pytest.raises(OSError):
            ck.save(path)
        # the good checkpoint is intact and no temp litter remains
        assert path.read_bytes() == good
        assert [p.name for p in tmp_path.iterdir()] == ["ckpt.npz"]
        monkeypatch.undo()
        ck.save(path)  # and a healthy save still lands atomically
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path).spawn_tpu().join())
        assert resumed.unique_state_count() == 288
