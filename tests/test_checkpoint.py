"""Checkpoint/resume tests (SURVEY.md §5): stop a bounded device run
mid-search, save, resume in a fresh checker, and converge to the same
reached set as an uninterrupted run."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.packed import PackedLinearEquation  # noqa: E402
from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402


class TestCheckpointResume:
    def test_resume_converges_to_same_set(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        model = TwoPhaseSys(5)  # 8,832 states (2pc.rs:133)
        partial = (model.checker()
                   .tpu_options(capacity=1 << 14, resumable=True, fmax=64,
                                chunk_steps=4)
                   .target_state_count(2000)
                   .spawn_tpu().join())
        assert partial.state_count() >= 2000
        assert partial.unique_state_count() < 8832
        partial.save(path)

        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14)
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832
        full = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert (resumed.generated_fingerprints()
                == full.generated_fingerprints())
        # resumed counts continue from the checkpoint
        assert resumed.state_count() >= partial.state_count()

    def test_resumed_paths_replay(self, tmp_path):
        # discoveries found after a resume reconstruct valid paths through
        # the stitched mirror (parents from both run segments)
        path = tmp_path / "ckpt.npz"
        model = PackedLinearEquation(3, 5, 81)
        partial = (model.checker()
                   .tpu_options(capacity=1 << 14, resumable=True, fmax=32,
                                chunk_steps=2)
                   .target_state_count(300)
                   .spawn_tpu().join())
        if partial.discovery("solvable") is None:
            partial.save(path)
            resumed = (PackedLinearEquation(3, 5, 81).checker()
                       .tpu_options(capacity=1 << 14)
                       .resume_from(path)
                       .spawn_tpu().join())
            found = resumed.assert_any_discovery("solvable")
        else:
            found = partial.assert_any_discovery("solvable")
        x, y = found.last_state()
        assert (3 * x + 5 * y) & 0xFF == 81

    def test_save_requires_resumable(self):
        ck = (TwoPhaseSys(3).checker().tpu_options(capacity=1 << 12)
              .spawn_tpu().join())
        with pytest.raises(RuntimeError, match="resumable"):
            ck.save("/tmp/nope.npz")

    def test_save_roundtrip_preserves_discoveries(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(capacity=1 << 12, resumable=True)
              .spawn_tpu().join())
        assert ck.unique_state_count() == 288
        ck.save(path)
        resumed = (TwoPhaseSys(3).checker()
                   .tpu_options(capacity=1 << 12)
                   .resume_from(path)
                   .spawn_tpu().join())
        # nothing left to search; counts and discoveries carry over
        assert resumed.unique_state_count() == 288
        assert set(resumed.discoveries()) == set(ck.discoveries())

    def test_resume_rejects_different_model(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(capacity=1 << 12, resumable=True)
              .spawn_tpu().join())
        ck.save(path)
        with pytest.raises(RuntimeError, match="different model"):
            (TwoPhaseSys(4).checker().tpu_options(capacity=1 << 12)
             .resume_from(path).spawn_tpu().join())


class TestShardedCheckpointResume:
    """Checkpoint/resume on the SPMD sharded engine: the format is
    shard-agnostic, so a checkpoint written on one mesh resumes on a
    different shard count (or single-chip) — the frontier re-routes by
    fingerprint ownership at seed time."""

    def _mesh(self, n):
        from jax.sharding import Mesh
        return Mesh(jax.devices("cpu")[:n], ("shards",))

    def _partial(self, path, n_shards):
        model = TwoPhaseSys(5)  # 8,832 states (2pc.rs:133)
        partial = (model.checker()
                   .tpu_options(capacity=1 << 14, resumable=True,
                                fmax=32, chunk_steps=4,
                                mesh=self._mesh(n_shards))
                   .target_state_count(2000)
                   .spawn_tpu().join())
        assert partial.unique_state_count() < 8832
        partial.save(path)
        return partial

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_sharded_partial_resumes_sharded(self, tmp_path, n_shards):
        path = tmp_path / "ckpt.npz"
        partial = self._partial(path, n_shards)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14,
                                mesh=self._mesh(n_shards))
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832
        full = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert (resumed.generated_fingerprints()
                == full.generated_fingerprints())
        assert resumed.state_count() >= partial.state_count()

    def test_two_shard_checkpoint_resumes_on_four(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        self._partial(path, 2)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14, mesh=self._mesh(4))
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832
        full = TwoPhaseSys(5).checker().spawn_bfs().join()
        assert (resumed.generated_fingerprints()
                == full.generated_fingerprints())

    def test_sharded_checkpoint_resumes_single_chip(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        self._partial(path, 2)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14)
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832

    def test_single_chip_checkpoint_resumes_sharded(self, tmp_path):
        path = tmp_path / "ckpt.npz"
        partial = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14, resumable=True,
                                fmax=64, chunk_steps=4)
                   .target_state_count(2000)
                   .spawn_tpu().join())
        assert partial.unique_state_count() < 8832
        partial.save(path)
        resumed = (TwoPhaseSys(5).checker()
                   .tpu_options(capacity=1 << 14, mesh=self._mesh(2))
                   .resume_from(path)
                   .spawn_tpu().join())
        assert resumed.unique_state_count() == 8832
