"""Packed ActorModel encoding: the actor bridge onto the TPU engine.

Differential oracles: the packed paxos model must agree with the plain
ActorModel paxos state-for-state (265 for 1 client, 16,668 for 2 — the
north-star oracle, `/root/reference/examples/paxos.rs:291`), and the
packed step must reproduce host successors exactly
(:func:`validate_packed_model` walks the contract state by state).
"""

import pytest

from stateright_tpu.examples.paxos_packed import PackedPaxos
from stateright_tpu.models.packed import validate_packed_model


class TestPackedPaxosContract:
    def test_validate_packed_model_full_n1(self):
        """Every state of the 1-client space: encode/decode round-trip,
        host/device fingerprint equality, successor-multiset equality,
        property agreement."""
        assert validate_packed_model(PackedPaxos(1), max_states=300) == 265

    def test_history_injective_n1(self):
        """The packed encoding separates exactly the states the host
        ActorModel separates (fingerprint count == host state count)."""
        m = PackedPaxos(1)
        seen = set()
        stack = list(m.init_states())
        while stack:
            s = stack.pop()
            fp = m.fingerprint(s)
            if fp in seen:
                continue
            seen.add(fp)
            stack.extend(m.next_states(s))
        assert len(seen) == 265


class TestPackedPaxosOnDevice:
    def test_spawn_tpu_n1(self):
        """1-client paxos on the device engine: 265 unique states,
        value-chosen example found, linearizability never violated."""
        ck = (PackedPaxos(1).checker()
              .tpu_options(capacity=1 << 12).spawn_tpu().join())
        assert ck.unique_state_count() == 265
        ck.assert_properties()
        assert ck.discovery("value chosen") is not None
        # witness replays through the host model (host/device agreement)
        path = ck.discoveries()["value chosen"]
        assert len(path.into_actions()) >= 1

    @pytest.mark.slow  # ~43s warm: level-mode + posthoc paxos runs
    def test_level_mode_agrees_with_posthoc(self):
        """The per-level engine (incremental host-prop eval) and the
        device engine (post-hoc eval over distinct histories) reach the
        same verdicts and counts."""
        level = (PackedPaxos(1).checker()
                 .tpu_options(capacity=1 << 12, mode="level")
                 .spawn_tpu().join())
        device = (PackedPaxos(1).checker()
                  .tpu_options(capacity=1 << 12, mode="device")
                  .spawn_tpu().join())
        assert level.unique_state_count() == 265
        assert device.unique_state_count() == 265
        assert set(level.discoveries()) == set(device.discoveries())
        device.assert_properties()

    @pytest.mark.slow
    def test_spawn_tpu_n2_16668(self):
        """The north-star oracle on the device engine."""
        ck = (PackedPaxos(2).checker()
              .tpu_options(capacity=1 << 17).spawn_tpu().join())
        assert ck.unique_state_count() == 16668
        ck.assert_properties()
