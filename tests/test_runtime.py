"""Actor runtime (UDP spawn) and ordered reliable link.

ORL model-checking tests port the reference's own
(`/root/reference/src/actor/ordered_reliable_link.rs:150-245`): a sender
pushes TestMsg(42) then TestMsg(43) through a lossy duplicating network;
the wrapper must prevent redelivery, preserve order, and allow eventual
delivery. The spawn test drives a real Paxos cluster over localhost UDP
with raw datagrams (the reference only documents this flow for `nc`;
here it is an automated smoke test).
"""

import socket
import time

import pytest

from stateright_tpu.actor import ActorModel, Id, Network, Out
from stateright_tpu.actor.core import Actor
from stateright_tpu.actor.model import Deliver as ModelDeliver
from stateright_tpu.actor.ordered_reliable_link import (Ack, ActorWrapper,
                                                        Deliver)
from stateright_tpu.core import Expectation


class OrlSender(Actor):
    def __init__(self, receiver_id):
        self.receiver_id = receiver_id

    def on_start(self, id, o):
        o.send(self.receiver_id, 42)
        o.send(self.receiver_id, 43)
        return ()

    def on_msg(self, id, state, src, msg, o):
        return None


class OrlReceiver(Actor):
    def on_start(self, id, o):
        return ()

    def on_msg(self, id, state, src, msg, o):
        return state + ((int(src), msg),)


def orl_model() -> ActorModel:
    model = (ActorModel()
             .actor(ActorWrapper.with_default_timeout(
                 OrlSender(Id(1))))
             .actor(ActorWrapper.with_default_timeout(OrlReceiver()))
             .init_network(Network.new_unordered_duplicating())
             .lossy_network(True))
    model.property(
        Expectation.ALWAYS, "no redelivery",
        lambda _, state:
        sum(1 for _s, v in state.actor_states[1].wrapped_state
            if v == 42) < 2
        and sum(1 for _s, v in state.actor_states[1].wrapped_state
                if v == 43) < 2)

    def ordered(_, state):
        values = [v for _s, v in state.actor_states[1].wrapped_state]
        return all(a <= b for a, b in zip(values, values[1:]))

    model.property(Expectation.ALWAYS, "ordered", ordered)
    model.property(
        Expectation.SOMETIMES, "delivered",
        lambda _, state: state.actor_states[1].wrapped_state
        == ((0, 42), (0, 43)))
    model.within_boundary_fn(lambda _, state: len(state.network) < 4)
    return model


class TestOrderedReliableLink:
    def test_messages_are_not_delivered_twice(self):
        orl_model().checker().spawn_bfs().join() \
            .assert_no_discovery("no redelivery")

    def test_messages_are_delivered_in_order(self):
        orl_model().checker().spawn_bfs().join() \
            .assert_no_discovery("ordered")

    def test_messages_are_eventually_delivered(self):
        checker = orl_model().checker().spawn_bfs().join()
        checker.assert_discovery("delivered", [
            ModelDeliver(src=Id(0), dst=Id(1), msg=Deliver(1, 42)),
            ModelDeliver(src=Id(0), dst=Id(1), msg=Deliver(2, 43)),
        ])

    def test_acks_clear_pending(self):
        wrapper = ActorWrapper.with_default_timeout(OrlSender(Id(1)))
        out = Out()
        state = wrapper.on_start(Id(0), out)
        assert len(state.msgs_pending_ack) == 2
        state = wrapper.on_msg(Id(0), state, Id(1), Ack(1), Out())
        assert len(state.msgs_pending_ack) == 1
        # resend timer re-sends what is still pending
        out = Out()
        wrapper.on_timeout(Id(0), state, out)
        sent = [c.msg for c in out if hasattr(c, "msg")]
        assert sent == [Deliver(2, 43)]


class TickProducer(Actor):
    """Uses its OWN timer while ORL-wrapped: ticks are sent through the
    link on each firing — the wrapped-timer arm the reference left as
    ``todo!()`` (`ordered_reliable_link.rs:130-148`)."""

    def __init__(self, receiver_id, max_ticks: int,
                 interval=(0.02, 0.04)):
        self.receiver_id = receiver_id
        self.max_ticks = max_ticks
        self.interval = interval

    def on_start(self, id, o):
        o.set_timer(self.interval)
        return 0

    def on_msg(self, id, state, src, msg, o):
        return None

    def on_timeout(self, id, state, o):
        o.send(self.receiver_id, 100 + state)
        nxt = state + 1
        if nxt < self.max_ticks:
            o.set_timer(self.interval)
        return nxt


def orl_timer_model() -> ActorModel:
    model = (ActorModel()
             .actor(ActorWrapper.with_default_timeout(
                 TickProducer(Id(1), 2)))
             .actor(ActorWrapper.with_default_timeout(OrlReceiver()))
             .init_network(Network.new_unordered_nonduplicating()))
    # the link suppresses out-of-order arrivals rather than reordering
    # them, so any non-decreasing subsequence of the ticks is legal
    model.property(
        Expectation.ALWAYS, "ordered",
        lambda _, state: [v for _s, v in
                          state.actor_states[1].wrapped_state]
        in ([], [100], [101], [100, 101]))
    model.property(
        Expectation.SOMETIMES, "both ticks delivered",
        lambda _, state: state.actor_states[1].wrapped_state
        == ((0, 100), (0, 101)))
    model.within_boundary_fn(lambda _, state: len(state.network) < 5)
    return model


class TestOrlWrappedTimers:
    def test_model_checks(self):
        # the wrapped actor's timer fires through the multiplexed
        # wrapper timer; ticks arrive exactly once, in order
        checker = orl_timer_model().checker().spawn_bfs().join()
        checker.assert_properties()

    def test_dfs_agrees(self):
        # early exit makes counts engine-dependent; verdicts must match
        b = orl_timer_model().checker().spawn_bfs().join()
        d = orl_timer_model().checker().spawn_dfs().join()
        assert set(b.discoveries()) == set(d.discoveries())
        d.assert_properties()

    def test_resend_and_wrapped_fire_are_separate_actions(self):
        """The firing that resends unacked messages and the firing that
        runs the wrapped on_timeout are distinct Timeout actions, so
        the checker can interleave deliveries of a resent message
        between them (a combined atomic firing would hide those
        interleavings — advisor r3, medium)."""
        w = ActorWrapper.with_default_timeout(TickProducer(Id(1), 1))
        out = Out()
        s0 = w.on_start(Id(0), out)
        assert s0.wrapped_fires_left == 1
        # firing 1: resend-only; the wrapped timer stays pending
        out = Out()
        s1 = w.on_timeout(Id(0), s0, out)
        assert s1.wrapped_timer is not None
        assert s1.wrapped_fires_left == 0
        assert not any(hasattr(c, "msg") for c in out)  # no tick yet
        # firing 2: the wrapped handler runs; its tick rides the link
        out = Out()
        s2 = w.on_timeout(Id(0), s1, out)
        sent = [c.msg for c in out if hasattr(c, "msg")]
        assert Deliver(1, 100) in sent
        assert s2.wrapped_timer is None

    def test_sub_millisecond_resend_interval(self):
        # countdown must not ZeroDivisionError on 0 < resend < 1 ms
        # (advisor r3, low); it stays a plain float ceiling
        w = ActorWrapper(TickProducer(Id(1), 1),
                         resend_interval=(0.0005, 0.001))
        assert w._countdown((0.02, 0.04)) == 39
        assert w._countdown((0.0001, 0.0002)) == 1

    def test_wrapped_cancel_timer(self):
        class OneShot(Actor):
            def on_start(self, id, o):
                o.set_timer((0.02, 0.04))
                return 0

            def on_msg(self, id, state, src, msg, o):
                o.cancel_timer()
                return state

            def on_timeout(self, id, state, o):
                return state + 1

        w = ActorWrapper.with_default_timeout(OneShot())
        out = Out()
        state = w.on_start(Id(0), out)
        assert state.wrapped_timer == (0.02, 0.04)
        # a message handler cancelling the wrapped timer clears it;
        # the physical (resend) timer stays armed (messages reach the
        # wrapped actor through the link's Deliver envelope)
        out = Out()
        state2 = w.on_msg(Id(0), state, Id(1), Deliver(1, "ping"), out)
        assert state2.wrapped_timer is None
        # a firing with no wrapped timer set only resends
        out = Out()
        assert w.on_timeout(Id(0), state2, out) is None

    def test_spawns_over_udp(self):
        """The same wrapped actors run on the real UDP runtime: the
        test plays the receiver as a raw socket, expecting sequenced
        Delivers driven by the wrapped actor's timer."""
        import pickle

        from stateright_tpu.actor.runtime import spawn

        base = _free_udp_port(span=2)
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", base + 1))
        recv.settimeout(5.0)
        loop = (127, 0, 0, 1)
        receiver_id = Id.from_socket_addr(loop, base + 1)
        handle = spawn(
            pickle.dumps, pickle.loads,
            [(Id.from_socket_addr(loop, base),
              ActorWrapper(TickProducer(receiver_id, 2),
                           resend_interval=(0.2, 0.3)))],
            background=True, seed=17)  # deterministic timer jitter
        try:
            got = {}
            deadline = time.monotonic() + 5.0
            while len(got) < 2 and time.monotonic() < deadline:
                data, addr = recv.recvfrom(65535)
                msg = pickle.loads(data)
                if isinstance(msg, Deliver):
                    got[msg.seq] = msg.msg
                    recv.sendto(pickle.dumps(Ack(msg.seq)), addr)
            assert got == {1: 100, 2: 101}
        finally:
            handle.stop()
            recv.close()


class TestSpawnRuntime:
    def test_paxos_cluster_over_udp(self):
        """End-to-end: spawn 3 checked PaxosActors on real sockets, then
        Put and Get a value as a raw-UDP client."""
        from stateright_tpu.examples.paxos_spawn import (msg_from_json,
                                                         msg_to_json,
                                                         spawn_paxos_cluster)
        from stateright_tpu.actor.register import (Get, GetOk, Put, PutOk)

        port = 4310
        handle = spawn_paxos_cluster(port=port, background=True)
        try:
            client = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            client.bind(("127.0.0.1", 0))
            client.settimeout(5.0)

            client.sendto(msg_to_json(Put(1, 'X')), ("127.0.0.1", port))
            data, _ = client.recvfrom(65535)
            assert msg_from_json(data) == PutOk(1)

            client.sendto(msg_to_json(Get(2)), ("127.0.0.1", port))
            deadline = time.monotonic() + 5.0
            value = None
            while time.monotonic() < deadline:
                data, _ = client.recvfrom(65535)
                msg = msg_from_json(data)
                if isinstance(msg, GetOk):
                    value = msg.value
                    break
            assert value == 'X'
        finally:
            handle.stop()


def _free_udp_port(span: int = 1) -> int:
    """A base port with ``span`` consecutive free UDP ports (probe-then-
    release; the tiny race is acceptable for tests)."""
    import socket as _socket
    for base in range(34500, 60000, span):
        socks = []
        try:
            for k in range(span):
                s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
                s.bind(("127.0.0.1", base + k))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free UDP port span found")


def _recv_for_request(sock, request_id):
    """Receive until a reply tagged with ``request_id`` (drains stale
    duplicate replies caused by the startup retry loop)."""
    import time as _time

    from stateright_tpu.examples.register_spawn import msg_from_json
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        msg = msg_from_json(sock.recv(1024))
        if getattr(msg, "request_id", None) == request_id:
            return msg
    raise AssertionError(f"no reply for request {request_id}")


class TestRegisterSpawn:
    def test_single_copy_over_udp(self):
        """Real Put/Get against a spawned single-copy server
        (`single-copy-register.rs:168-186`)."""
        import socket

        from stateright_tpu.examples.register_spawn import (
            msg_from_json, msg_to_json, spawn_single_copy)
        from stateright_tpu.actor.register import Get, GetOk, Put, PutOk

        port = _free_udp_port()
        handle = spawn_single_copy(port=port, background=True)
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.bind(("127.0.0.1", 0))
            sock.settimeout(1.0)
            reply = None
            for _attempt in range(5):  # ride out server startup
                sock.sendto(msg_to_json(Put(1, 'X')), ("127.0.0.1", port))
                try:
                    reply = msg_from_json(sock.recv(1024))
                    break
                except socket.timeout:
                    continue
            assert reply == PutOk(1)
            sock.settimeout(5.0)
            sock.sendto(msg_to_json(Get(2)), ("127.0.0.1", port))
            reply = _recv_for_request(sock, 2)  # skip stale retry PutOks
            assert reply == GetOk(2, 'X')
        finally:
            handle.stop()

    def test_abd_cluster_over_udp(self):
        """Real Put/Get against a spawned 3-replica ABD cluster
        (`linearizable-register.rs:328-349`)."""
        import socket

        from stateright_tpu.examples.register_spawn import (
            msg_from_json, msg_to_json, spawn_abd_cluster)
        from stateright_tpu.actor.register import Get, GetOk, Put, PutOk

        port = _free_udp_port(span=3)
        handle = spawn_abd_cluster(port=port, background=True)
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.bind(("127.0.0.1", 0))
            sock.settimeout(1.0)
            reply = None
            for _attempt in range(5):  # ride out server startup
                sock.sendto(msg_to_json(Put(1, 'Z')), ("127.0.0.1", port))
                try:
                    reply = msg_from_json(sock.recv(1024))
                    break
                except socket.timeout:
                    continue
            assert reply == PutOk(1)
            sock.settimeout(5.0)
            # read through a DIFFERENT replica: quorum replication must
            # surface the written value
            sock.sendto(msg_to_json(Get(2)), ("127.0.0.1", port + 1))
            reply = _recv_for_request(sock, 2)  # skip stale retry PutOks
            assert reply == GetOk(2, 'Z')
        finally:
            handle.stop()


@pytest.mark.faults
class TestSpawnFailures:
    """Actor-thread startup failures surface on the SpawnHandle instead
    of dying silently inside a daemon thread."""

    def test_duplicate_port_fails_loudly(self):
        import pickle

        from stateright_tpu.actor.core import ScriptedActor
        from stateright_tpu.actor.runtime import spawn

        base = _free_udp_port()
        loop = (127, 0, 0, 1)
        same_id = Id.from_socket_addr(loop, base)
        handle = spawn(
            pickle.dumps, pickle.loads,
            [(same_id, ScriptedActor([])),
             (same_id, ScriptedActor([]))],  # second bind must fail
            background=True)
        try:
            deadline = time.monotonic() + 5.0
            while not handle.failures() and time.monotonic() < deadline:
                time.sleep(0.01)
            failures = handle.failures()
            assert len(failures) == 1
            failed_id, exc = failures[0]
            assert failed_id == same_id
            assert isinstance(exc, OSError)
        finally:
            with pytest.raises(RuntimeError, match="actor thread"):
                handle.stop()

    def test_clean_cluster_reports_no_failures(self):
        import pickle

        from stateright_tpu.actor.core import ScriptedActor
        from stateright_tpu.actor.runtime import spawn

        base = _free_udp_port(span=2)
        loop = (127, 0, 0, 1)
        handle = spawn(
            pickle.dumps, pickle.loads,
            [(Id.from_socket_addr(loop, base), ScriptedActor([])),
             (Id.from_socket_addr(loop, base + 1), ScriptedActor([]))],
            background=True)
        assert handle.failures() == []
        handle.stop()  # must not raise
