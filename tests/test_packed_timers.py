"""Timeout actions on the TPU engine (``device_timers`` +
``packed_on_timeout``): timer firings are part of the packed action axis,
mirroring the host semantics (`/root/reference/src/actor/model.rs:288-306`
— the fired timer clears unless the handler re-sets it; a no-op handler
that keeps its timer is pruned)."""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.actor.test_util import PackedTimerCount  # noqa: E402
from stateright_tpu.models.packed import validate_packed_model  # noqa: E402


class TestPackedTimers:
    def test_contract_full_space(self):
        # host/device step agreement over every state, including all
        # Timeout successors and timer-bit updates
        assert validate_packed_model(PackedTimerCount(2, 3),
                                     max_states=100) == 16

    def test_device_counts_and_parity(self):
        host = PackedTimerCount(2, 3).checker().spawn_bfs().join()
        assert host.unique_state_count() == 16  # (max+1)^2 interleavings
        dev = (PackedTimerCount(2, 3).checker()
               .tpu_options(capacity=1 << 10, fmax=16).spawn_tpu().join())
        assert dev.unique_state_count() == 16
        assert (dev.generated_fingerprints()
                == host.generated_fingerprints())
        dev.assert_properties()

    def test_three_actors(self):
        dev = (PackedTimerCount(3, 2).checker()
               .tpu_options(capacity=1 << 10, fmax=16).spawn_tpu().join())
        assert dev.unique_state_count() == 27
        dev.assert_properties()

    def test_timer_models_without_optin_still_rejected(self):
        from stateright_tpu.actor.test_util import PackedPingPong

        # a model whose init states carry timers but has no Timeout lanes
        # must refuse device checking rather than under-explore
        m = PackedPingPong(3)
        state = m.init_states()[0]
        state = type(state)(actor_states=state.actor_states,
                            network=state.network,
                            is_timer_set=(True, False),
                            history=state.history)
        with pytest.raises(NotImplementedError):
            m.validate_device_state(state)


def test_noop_keep_handler_matches_host_selfloop():
    # the host (like the reference, model.rs:295) never prunes a Timeout:
    # a no-op handler that re-sets its timer yields a self-loop successor,
    # and the device contract must agree
    import jax.numpy as jnp

    from stateright_tpu.actor.test_util import (PackedTimerCount,
                                                TimerCountActor)

    class NoopKeep(PackedTimerCount):
        def __init__(self):
            super().__init__(1, 1)

        def cache_key(self):
            return ("noop_keep_timer",)

        def packed_on_timeout(self, actors, aidx):
            zmsg = jnp.zeros((self.msg_width,), jnp.uint32)
            return actors, jnp.bool_(False), \
                [(jnp.uint32(0), zmsg, jnp.bool_(False))], jnp.bool_(True)

    class NoopKeepActor(TimerCountActor):
        def on_timeout(self, id, state, o):
            o.set_timer((0.0, 0.0))
            return None

    m = NoopKeep()
    m.actors = [NoopKeepActor(1)]
    assert validate_packed_model(m, max_states=10) == 1
