"""Overlap-aware span profiler with critical-path stall attribution
(PR 18).

The load-bearing guarantees:

* **intervals, not durations** — engines emit ``span`` trace events
  (``name``/``t0``/``t1`` on the shared trace clock) for every phase
  of the chunk anatomy, schema-valid and identity-tagged;
* **buckets sum to wall** — :func:`stateright_tpu.obs.spans.analyze`
  sweeps the merged timeline and splits wall time into exclusively-
  attributed buckets (``device``/``xfer``/``exchange``, ``overlap``,
  ``host:<phase>``, ``idle``) that partition the wall interval by
  construction;
* **the pipeline shift is visible** — a ``pipeline=False`` run has
  zero ``overlap`` (nothing in flight while the host works), a
  ``pipeline=True`` run has ``overlap > 0`` (chunk N+1's device time
  hides chunk N's host time) — the end-to-end pin;
* **one consumer** — ``tools/stall_report.py`` renders single-run and
  ``--fleet`` merged reports from committed fixture traces, and
  ``bench_history --check`` tolerates pre-span rounds (informational)
  while failing rounds that LOSE attribution after it landed.
"""

import io
import json
import os
import sys
import time

import pytest

from stateright_tpu.obs import (RunTrace, SpanRecorder, analyze,
                                attach_attribution, ranked,
                                shard_imbalance, spans_from_events,
                                top_stalls, validate_event)

pytestmark = pytest.mark.obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
_DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

#: committed fixture traces (satellite: stall_report renders both a
#: single-run and a --fleet merged report from committed fixtures)
FIXTURE = os.path.join(_DATA, "span_trace.jsonl")
FLEET_DIR = os.path.join(_DATA, "span_fleet")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _span(name, t0, t1, **fields):
    s = {"name": name, "t0": float(t0), "t1": float(t1)}
    s.update(fields)
    return s


# --- the critical-path sweep on synthetic timelines ------------------------

class TestAnalyze:
    def test_empty_input_is_all_zeros(self):
        attr = analyze([])
        assert attr["wall_s"] == 0.0
        assert attr["buckets"] == {}
        assert attr["bubble_frac"] == 0.0
        assert attr["spans"] == 0

    def test_full_overlap_is_free(self):
        """Host work entirely hidden under an in-flight chunk is
        attributed to ``overlap`` — zero bubble."""
        attr = analyze([_span("device", 0.0, 6.0),
                        _span("host", 2.0, 6.0)])
        assert attr["buckets"] == {"device": 2.0, "overlap": 4.0}
        assert attr["overlap_s"] == 4.0
        assert attr["bubble_frac"] == 0.0
        assert attr["wall_s"] == 6.0

    def test_zero_overlap_is_all_bubble(self):
        """Strictly sequential device-then-host: every host second
        blocked an idle device."""
        attr = analyze([_span("device", 0.0, 5.0),
                        _span("host", 5.0, 9.0)])
        assert attr["buckets"] == {"device": 5.0, "host:host": 4.0}
        assert attr["overlap_s"] == 0.0
        assert attr["bubble_frac"] == pytest.approx(4.0 / 9.0)

    def test_innermost_device_span_wins(self):
        """A ``xfer`` nested inside the ``device`` interval names its
        own segment — the umbrella does not swallow it."""
        attr = analyze([_span("device", 0.0, 6.0),
                        _span("xfer", 2.0, 4.0)])
        assert attr["buckets"] == {"device": 4.0, "xfer": 2.0}

    def test_innermost_host_span_wins(self):
        attr = analyze([_span("host", 0.0, 10.0),
                        _span("props", 4.0, 6.0)])
        assert attr["buckets"] == {"host:host": 8.0, "host:props": 2.0}

    def test_idle_span_counts_for_neither_side(self):
        """The scheduler's queue-wait ``idle`` span marks dead air: it
        must not read as host work (that would fake a bubble source)
        nor suppress device attribution under it."""
        attr = analyze([_span("idle", 0.0, 5.0)])
        assert attr["buckets"] == {"idle": 5.0}
        assert attr["bubble_frac"] == 1.0
        attr = analyze([_span("idle", 0.0, 10.0),
                        _span("device", 2.0, 4.0)])
        assert attr["buckets"] == {"device": 2.0, "idle": 8.0}

    def test_gap_between_spans_is_idle(self):
        attr = analyze([_span("device", 0.0, 2.0),
                        _span("device", 5.0, 6.0)])
        assert attr["buckets"] == {"device": 3.0, "idle": 3.0}
        assert attr["idle_s"] == 3.0

    def test_buckets_sum_to_wall_on_messy_timeline(self):
        """The core invariant: buckets partition [min t0, max t1)
        exactly, whatever the nesting/overlap structure."""
        spans = [
            _span("dispatch", 0.0, 0.3),
            _span("device", 0.3, 2.1),
            _span("xfer", 2.1, 2.4),
            _span("host", 2.2, 3.7),          # partially overlapped
            _span("host_probe", 2.5, 3.0),    # nested host phase
            _span("device", 2.6, 4.8),        # next chunk in flight
            _span("idle", 5.0, 5.5),          # trailing dead air
            _span("exchange", 4.9, 5.0),
        ]
        attr = analyze(spans)
        assert sum(attr["buckets"].values()) == \
            pytest.approx(attr["wall_s"], rel=1e-12)
        assert attr["wall_s"] == pytest.approx(5.5)
        # every classification kind appears on this timeline
        kinds = set(attr["buckets"])
        assert "overlap" in kinds and "idle" in kinds
        assert any(k.startswith("host:") for k in kinds)
        assert kinds & {"device", "xfer", "exchange"}

    def test_pipeline_shift_synthetic(self):
        """The signature the e2e pin looks for, in miniature: same
        phase durations, sequential vs double-buffered schedule."""
        sequential = [
            _span("device", 0.0, 2.0), _span("host", 2.0, 3.0),
            _span("device", 3.0, 5.0), _span("host", 5.0, 6.0),
        ]
        pipelined = [
            _span("device", 0.0, 2.0), _span("host", 2.0, 3.0),
            _span("device", 2.0, 4.0), _span("host", 4.0, 5.0),
        ]
        a_seq = analyze(sequential)
        a_pipe = analyze(pipelined)
        assert a_seq["overlap_s"] == 0.0
        # host1 hides under chunk2's device time; the final host span
        # has nothing in flight, so it stays a bubble in both schedules
        assert a_pipe["overlap_s"] == pytest.approx(1.0)
        assert a_pipe["bubble_frac"] < a_seq["bubble_frac"]
        assert a_pipe["wall_s"] < a_seq["wall_s"]

    def test_ranked_and_top_stalls(self):
        attr = analyze([_span("device", 0.0, 5.0),
                        _span("host", 5.0, 9.0)])
        rows = ranked(attr)
        assert [r[0] for r in rows] == ["device", "host:host"]
        assert sum(share for _n, _s, share in rows) == \
            pytest.approx(1.0)
        assert top_stalls(attr, n=1) == [["device", 5.0]]


# --- the recorder: clock bridge, ring, trace emission ----------------------

class TestSpanRecorder:
    def test_record_emits_schema_valid_event(self):
        events = []
        rec = SpanRecorder(RunTrace(events, engine="E"))
        t = time.perf_counter()
        rec.record("device", t, t + 0.01, chunk=3, shard=None)
        assert len(rec) == 1
        spans = [e for e in events if e["ev"] == "span"]
        assert len(spans) == 1
        validate_event(spans[0])
        assert spans[0]["name"] == "device"
        assert spans[0]["chunk"] == 3
        assert "shard" not in spans[0]  # None identity is dropped
        assert spans[0]["t1"] >= spans[0]["t0"] >= 0.0

    def test_clock_bridge_lands_on_trace_axis(self):
        """perf_counter stamps must convert onto the trace's relative
        axis: the span's t1 lands near the emit-time event t."""
        events = []
        rec = SpanRecorder(RunTrace(events, engine="E"))
        t = time.perf_counter()
        rec.record("host", t, t)
        ev = [e for e in events if e["ev"] == "span"][0]
        assert abs(ev["t1"] - ev["t"]) < 0.25

    def test_span_context_records_on_exception(self):
        rec = SpanRecorder(None)
        with pytest.raises(RuntimeError):
            with rec.span("mirror"):
                raise RuntimeError("boom")
        assert [s["name"] for s in rec.spans()] == ["mirror"]

    def test_traceless_ring_still_feeds_attribution(self):
        rec = SpanRecorder(None)
        t = time.perf_counter()
        rec.record("device", t, t + 0.5)
        rec.record("host", t + 0.5, t + 0.7)
        snap = attach_attribution({"chunks": 2}, rec)
        assert "attribution" in snap
        assert snap["bubble_frac"] > 0.0
        assert snap["idle_s"] >= 0.0
        assert snap["chunks"] == 2  # existing keys untouched

    def test_spanless_snapshot_left_untouched(self):
        snap = attach_attribution({"chunks": 0}, SpanRecorder(None))
        assert "attribution" not in snap
        assert "bubble_frac" not in snap

    def test_ring_is_bounded(self):
        rec = SpanRecorder(None, limit=4)
        t = time.perf_counter()
        for i in range(10):
            rec.record("host", t + i, t + i + 0.1)
        assert len(rec) == 4


# --- the consumer side: event streams, imbalance, the CLI ------------------

def _load_fixture(path=FIXTURE):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestConsumers:
    def test_fixture_events_are_schema_valid(self):
        for ev in _load_fixture():
            validate_event(ev)

    def test_spans_from_events_filters_and_anchors(self):
        events = _load_fixture()
        spans = spans_from_events(events)
        assert len(spans) == 9
        assert all(s["t1"] >= s["t0"] for s in spans)
        # wall anchoring is a no-op request on a raw (un-merged)
        # stream: no "wall" annotation -> nothing joins the wall axis
        assert spans_from_events(events, wall=True) == []
        annotated = [dict(ev, wall=100.0 + ev["t"]) for ev in events]
        walled = spans_from_events(annotated, wall=True)
        assert len(walled) == 9
        assert all(s["t0"] >= 100.0 for s in walled)

    def test_shard_imbalance_from_chunk_vectors(self):
        imb = shard_imbalance(_load_fixture())
        assert imb["per_shard_new"] == [112, 48]
        assert imb["imbalance"] == pytest.approx(112 / 80.0)
        # width change mid-run (degradation) skips the odd vector
        events = [{"ev": "chunk", "shard_new": [4, 4]},
                  {"ev": "chunk", "shard_new": [8]}]
        assert shard_imbalance(events)["per_shard_new"] == [4, 4]
        assert shard_imbalance([{"ev": "chunk", "new": 5}]) is None

    def test_stall_report_single_run(self, capsys):
        sr = _tool("stall_report")
        assert sr.main([FIXTURE]) == 0
        out = capsys.readouterr().out
        assert "bucket" in out and "sum" in out
        assert "bubble_frac=" in out
        assert "overlap" in out
        assert "shard imbalance" in out and "1.40" in out

    def test_stall_report_fleet(self, capsys):
        sr = _tool("stall_report")
        assert sr.main(["--fleet", FLEET_DIR]) == 0
        out = capsys.readouterr().out
        assert "fleet summary" in out
        assert "job:j0" in out
        assert "merged (wall-anchored, all lanes)" in out
        # the scheduler's queue-wait idle span rides the service lane
        assert "idle" in out

    def test_stall_report_summary_line(self):
        sr = _tool("stall_report")
        attr, imb = sr.attribution_from_events(_load_fixture())
        line = sr.summary_line(attr, imb)
        assert line.startswith("stall: ")
        assert "bubble=" in line and "imbalance=" in line
        assert sr.summary_line({}, None) == "stall: no spans"

    def test_stall_report_pre_span_trace(self, tmp_path, capsys):
        """A pre-span trace (no span events) renders the explicit
        no-spans notice, not a crash or an empty table."""
        p = tmp_path / "old.jsonl"
        p.write_text(json.dumps(
            {"t": 0.0, "ev": "run_start", "engine": "E", "model": "M",
             "wall": 1.0}) + "\n")
        sr = _tool("stall_report")
        assert sr.main([str(p)]) == 0
        assert "no span events" in capsys.readouterr().out

    def test_attribution_sums_to_wall_on_fixture(self):
        sr = _tool("stall_report")
        attr, _imb = sr.attribution_from_events(_load_fixture())
        assert sum(attr["buckets"].values()) == \
            pytest.approx(attr["wall_s"], rel=1e-9)


# --- live consoles fold spans into a top-stall line ------------------------

class TestConsoles:
    def test_watch_progress_line_carries_top_stall(self):
        watch = _tool("watch")
        buf = io.StringIO()
        console = watch.Console(out=buf)
        for ev in _load_fixture():
            console.feed(ev)
        out = buf.getvalue()
        assert "stall=" in out and "bubble=" in out
        # spans accumulate; they never render as intervention lines
        assert console.rendered_events == 0
        assert console.rendered_progress == 2

    def test_fleetboard_stall_line(self):
        fleetboard = _tool("fleetboard")
        board = fleetboard.Board()
        out = board.feed({
            "jobs": [{"id": "j0", "state": "done",
                      "result": {"profile": {
                          "attribution": {"host:dispatch": 1.5,
                                          "overlap": 0.4,
                                          "device": 0.2},
                          "bubble_frac": 0.6}}}],
            "profile": {}, "utilization": {}})
        assert "stall: host:dispatch=1.50s" in out
        assert "bubble=60% mean" in out
        assert "overlap=" not in out  # overlap is not a stall


# --- bench_history tolerates pre-span rounds, flags regressions ------------

class TestBenchHistoryAttribution:
    @staticmethod
    def _art(tmp_path, name, metrics):
        row = {"workload": "tpu 2pc7 full 296448", "unit": "uniq/s",
               "best": 1000.0, "uniq": 1, "gen": 2, "gen_per_uniq": 2.0,
               "fused": False, "metrics": metrics}
        (tmp_path / name).write_text(json.dumps({
            "n": 1, "rc": 0, "tail": json.dumps(row),
            "parsed": {"metric": "m", "value": 100.0,
                       "unit": "uniq/s", "backend": "tpu"}}))

    def test_pre_span_rounds_flagged_informationally(self, tmp_path,
                                                     capsys):
        bench_history = _tool("bench_history")
        self._art(tmp_path, "BENCH_r01.json", {})
        self._art(tmp_path, "BENCH_r02.json",
                  {"stalls": [["host:dispatch", 1.2]],
                   "bubble_frac": 0.4})
        report = bench_history.build_report(
            [str(tmp_path / "BENCH_r01.json"),
             str(tmp_path / "BENCH_r02.json")])
        pre = [f for f in report["flags"] if f["kind"] == "pre_span"]
        assert len(pre) == 1 and pre[0]["round"] == "r01"
        assert pre[0]["info"] is True
        # informational flags never fail the gate
        assert bench_history.main([str(tmp_path), "--check"]) == 0
        out = io.StringIO()
        bench_history.render_markdown(report, out)
        assert "(informational)" in out.getvalue()
        capsys.readouterr()

    def test_losing_attribution_after_it_landed_is_fatal(self, tmp_path,
                                                         capsys):
        bench_history = _tool("bench_history")
        self._art(tmp_path, "BENCH_r01.json",
                  {"stalls": [["device", 0.8]], "bubble_frac": 0.2})
        self._art(tmp_path, "BENCH_r02.json", {})
        report = bench_history.build_report(
            [str(tmp_path / "BENCH_r01.json"),
             str(tmp_path / "BENCH_r02.json")])
        missing = [f for f in report["flags"]
                   if f["kind"] == "missing_attribution"]
        assert len(missing) == 1 and missing[0]["round"] == "r02"
        assert bench_history.main([str(tmp_path), "--check"]) == 1
        capsys.readouterr()

    def test_all_pre_span_history_stays_green(self, tmp_path, capsys):
        """The committed pre-span BENCH artifacts: no attribution
        anywhere means no flags at all — the gate must not punish
        history for predating the instrument."""
        bench_history = _tool("bench_history")
        self._art(tmp_path, "BENCH_r01.json", {})
        self._art(tmp_path, "BENCH_r02.json", {})
        report = bench_history.build_report(
            [str(tmp_path / "BENCH_r01.json"),
             str(tmp_path / "BENCH_r02.json")])
        kinds = {f["kind"] for f in report["flags"]}
        assert "pre_span" not in kinds
        assert "missing_attribution" not in kinds
        assert bench_history.main([str(tmp_path), "--check"]) == 0
        capsys.readouterr()


# --- end-to-end: a real pipelined run on the device engine -----------------

@pytest.fixture(scope="module")
def pipeline_runs():
    """One 2pc run per pipeline mode (shapes shared with
    tests/test_fleetobs.py for compile-cache reuse): (events, profile)
    keyed by the pipeline flag."""
    pytest.importorskip("jax")
    from stateright_tpu.models.twopc import TwoPhaseSys
    runs = {}
    for pipeline in (False, True):
        events = []
        ck = (TwoPhaseSys(3).checker()
              .tpu_options(capacity=1 << 12, race=False, trace=events,
                           pipeline=pipeline, chunk_steps=2)
              .spawn_tpu().join())
        runs[pipeline] = (events, ck.profile())
    return runs


class TestEndToEnd:
    def test_spans_validate_and_cover_the_chunk_anatomy(self,
                                                        pipeline_runs):
        events, _prof = pipeline_runs[True]
        spans = [e for e in events if e["ev"] == "span"]
        assert spans, "pipelined run emitted no span events"
        for ev in spans:
            validate_event(ev)
        names = {e["name"] for e in spans}
        assert {"dispatch", "device", "xfer", "host"} <= names
        # device/xfer spans carry the chunk ordinal for correlation
        assert all("chunk" in e for e in spans
                   if e["name"] in ("device", "xfer"))

    def test_attribution_sums_to_wall(self, pipeline_runs):
        """Acceptance: buckets sum to within 5% of wall on the
        pipelined CPU smoke (exact by construction; 5% is the
        acceptance bound)."""
        for pipeline in (False, True):
            events, _prof = pipeline_runs[pipeline]
            attr = analyze(spans_from_events(events))
            assert attr["spans"] > 0
            total = sum(attr["buckets"].values())
            assert total == pytest.approx(attr["wall_s"], rel=1e-6)
            assert abs(total - attr["wall_s"]) <= 0.05 * attr["wall_s"]

    def test_pipeline_toggle_shifts_overlap(self, pipeline_runs):
        """Acceptance pin: pipeline=False has NO overlap (nothing in
        flight while the host works), pipeline=True hides host time
        under the next chunk's device time."""
        a_off = analyze(spans_from_events(pipeline_runs[False][0]))
        a_on = analyze(spans_from_events(pipeline_runs[True][0]))
        assert a_off["overlap_s"] == 0.0
        assert a_on["overlap_s"] > 0.0

    def test_profile_carries_attribution(self, pipeline_runs):
        for pipeline in (False, True):
            _events, prof = pipeline_runs[pipeline]
            attr = prof.get("attribution")
            assert isinstance(attr, dict) and attr
            assert 0.0 <= prof["bubble_frac"] <= 1.0
            assert prof["idle_s"] >= 0.0
        # the pipelined profile attributes some overlap; the
        # sequential one attributes none
        assert "overlap" in pipeline_runs[True][1]["attribution"]
        assert "overlap" not in pipeline_runs[False][1]["attribution"]

    def test_stall_report_exits_zero_on_run_artifact(self, tmp_path,
                                                     pipeline_runs,
                                                     capsys):
        events, _prof = pipeline_runs[True]
        p = tmp_path / "run_trace.jsonl"
        p.write_text("".join(json.dumps(e) + "\n" for e in events))
        sr = _tool("stall_report")
        assert sr.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "wall" in out and "bucket" in out
        assert "overlap" in out
