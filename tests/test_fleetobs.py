"""Fleet observability plane (PR 14): correlated tracing, the
timeline aggregator, Prometheus exposition, and utilization/SLO
accounting.

The load-bearing guarantees:

* **identity** — every trace stream (engine run_start, service/fleet
  trace_header, batch lanes) carries run_id / t0_unix / host / rank
  (+ job/lane when service-driven), so any artifact is
  self-describing;
* **one timeline** — a 2-process launcher fleet AND a concurrent
  2-job service run merge via ``obs/aggregate.py`` into a single
  wall-ordered timeline with non-decreasing fleet time and every
  event resolvable to its run;
* **scrapeable** — the service's ``GET /metrics`` serves valid
  Prometheus text exposition (strict line-format validator) merging
  the scheduler registry with live per-job registries under job/host
  labels;
* **SLOs** — submit→grant→start→first-chunk→done stamps land in
  ``job_*`` events and ``result.json``; queue-wait / first-chunk /
  jobs-per-min / pool-busy-fraction aggregates ride the scheduler
  registry and ``tools/fleetboard.py``.
"""

import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twopc import TwoPhaseSys  # noqa: E402
from stateright_tpu.obs import (EVENT_SCHEMA, GLOSSARY,  # noqa: E402
                                FlightRecorder, Metrics, MetricsRing,
                                RunTrace, emit_trace_header,
                                validate_event)
from stateright_tpu.obs import aggregate, prom  # noqa: E402
from stateright_tpu.service import (JobSpec, JobStore,  # noqa: E402
                                    Scheduler, serve_jobs)

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: pinned engine shapes shared with tests/test_service.py and
#: tests/test_cluster.py (persistent compile cache reuse)
OPTS = {"capacity": 1 << 12, "fmax": 64, "chunk_steps": 2}


def _tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import importlib
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


# --- identity headers -------------------------------------------------------

class TestIdentityHeader:
    def test_run_start_carries_header(self):
        events = []
        ck = (TwoPhaseSys(2).checker()
              .tpu_options(race=False, trace=events, **OPTS)
              .spawn_tpu().join())
        rs = [e for e in events if e["ev"] == "run_start"][0]
        assert rs["run_id"] == ck.run_id()
        assert rs["run_id"].startswith("run-")
        assert isinstance(rs["t0_unix"], float)
        # t0_unix + t must land within the run's wall window
        assert abs((rs["t0_unix"] + rs["t"]) - rs["wall"]) < 0.25
        assert isinstance(rs["host"], str) and rs["host"]
        assert rs["rank"] == 0

    def test_host_engine_header_without_backend_init(self):
        events = []
        (TwoPhaseSys(2).checker().tpu_options(trace=events)
         .spawn_bfs().join())
        rs = [e for e in events if e["ev"] == "run_start"][0]
        assert rs["run_id"].startswith("run-")
        assert rs["rank"] == 0

    def test_trace_header_event(self, tmp_path):
        events = []
        tr = RunTrace(events, engine="service")
        run_id = emit_trace_header(tr, prefix="svc", procs=2)
        assert run_id.startswith("svc-")
        hd = events[0]
        assert hd["ev"] == "trace_header"
        assert hd["run_id"] == run_id
        assert hd["t0_unix"] == tr.t0_unix
        assert hd["procs"] == 2
        validate_event(hd)

    def test_flight_ring_pins_header_past_eviction(self):
        rec = FlightRecorder(limit=16)
        rec.record({"t": 0.0, "ev": "run_start", "engine": "E",
                    "model": "M", "wall": 1.0, "run_id": "run-x"})
        for i in range(100):
            rec.record({"t": float(i), "ev": "compile", "engine": "E",
                        "reason": "x"})
        snap = rec.snapshot()
        # the ring evicted run_start long ago; the header is pinned
        assert snap[0]["ev"] == "run_start"
        assert snap[0]["run_id"] == "run-x"
        assert len(snap) == 17  # header + the 16 ring slots


# --- the aggregator (unit) --------------------------------------------------

def _write_stream(path, events):
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _engine_stream(run_id, t0, host="h0", rank=0, job=None, n=3):
    head = {"t": 0.001, "ev": "run_start", "engine": "TpuChecker",
            "model": "M", "wall": t0 + 0.001, "run_id": run_id,
            "t0_unix": t0, "host": host, "rank": rank}
    if job is not None:
        head["job"] = job
    evs = [head]
    for i in range(n):
        evs.append({"t": 0.1 * (i + 1), "ev": "chunk", "engine":
                    "TpuChecker", "chunk": i + 1, "gen": 10, "unique":
                    5, "q_size": 1, "new": 5, "dedup_hit": 0.0,
                    "load": 0.1})
    evs.append({"t": 0.1 * (n + 1), "ev": "done", "engine":
                "TpuChecker", "gen": 10, "unique": 5})
    return evs


class TestAggregate:
    def test_wall_anchored_interleave(self, tmp_path):
        # stream B starts 0.15s after A: its events interleave between
        # A's, strictly by wall clock, not file order
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        _write_stream(a, _engine_stream("run-a", 100.0))
        _write_stream(b, _engine_stream("run-b", 100.15, rank=1))
        tl = aggregate.merge([str(a), str(b)])
        walls = [e["wall"] for e in tl.events]
        assert walls == sorted(walls)
        order = [(e["run_id"], e["ev"]) for e in tl.events]
        # A's first chunk (100.1) before B's run_start? run-b head is
        # at 100.151 — after a's chunk 1, before a's chunk 2 (100.2)
        ia = order.index(("run-a", "chunk"))
        ib = order.index(("run-b", "run_start"))
        assert ia < ib < order.index(("run-a", "done"))
        assert all(e["run_id"] in ("run-a", "run-b")
                   for e in tl.events)
        assert {"h0/r0:TpuChecker", "h0/r1:TpuChecker"} == \
            set(tl.lanes())

    def test_flight_duplicates_collapse(self, tmp_path):
        evs = _engine_stream("run-a", 50.0)
        _write_stream(tmp_path / "trace.jsonl", evs)
        _write_stream(tmp_path / "flight.jsonl", evs[:3])  # a subset
        tl = aggregate.merge([str(tmp_path)])
        assert len(tl.events) == len(evs)  # no duplicates

    def test_legacy_run_start_wall_fallback(self, tmp_path):
        # pre-PR-14 artifact: no run_id/t0_unix — anchored off the
        # run_start's wall field, id synthesized from the filename
        evs = [{"t": 0.5, "ev": "run_start", "engine": "E",
                "model": "M", "wall": 200.5},
               {"t": 1.0, "ev": "done", "engine": "E", "gen": 1,
                "unique": 1}]
        path = tmp_path / "old.jsonl"
        _write_stream(path, evs)
        tl = aggregate.merge([str(path)])
        assert tl.events[0]["anchored"]
        assert abs(tl.events[0]["wall"] - 200.5) < 1e-6
        assert abs(tl.events[1]["wall"] - 201.0) < 1e-6
        assert tl.events[0]["run_id"] == "anon:old.jsonl"

    def test_headerless_stream_is_flagged_not_fabricated(self,
                                                         tmp_path):
        path = tmp_path / "raw.jsonl"
        _write_stream(path, [{"t": 1.0, "ev": "compile",
                              "engine": "E", "reason": "x"}])
        tl = aggregate.merge([str(path)])
        assert not tl.events[0]["anchored"]
        assert tl.events[0]["wall"] is None

    def test_second_header_starts_new_segment(self, tmp_path):
        # a resumed job appends a second run to the same trace.jsonl
        evs = _engine_stream("run-a", 10.0) + \
            _engine_stream("run-b", 20.0)
        path = tmp_path / "trace.jsonl"
        _write_stream(path, evs)
        segs = aggregate.read_segments(path)
        assert [s.run_id for s in segs] == ["run-a", "run-b"]
        tl = aggregate.merge([str(path)])
        assert {e["run_id"] for e in tl.events} == {"run-a", "run-b"}

    def test_skew_bound_from_mesh_init(self, tmp_path):
        evs = _engine_stream("run-a", 10.0)
        evs.insert(1, {"t": 0.05, "ev": "mesh_init", "engine":
                       "ShardedTpuChecker", "shards": 4, "hosts": 2,
                       "procs": 2, "dcn_exchange_s": 0.0042})
        path = tmp_path / "trace.jsonl"
        _write_stream(path, evs)
        tl = aggregate.merge([str(path)])
        assert tl.skew_bound_s == pytest.approx(0.0042)

    def test_service_events_route_to_job_lanes(self, tmp_path):
        evs = [{"t": 0.0, "ev": "trace_header", "engine": "service",
                "run_id": "svc-1", "t0_unix": 30.0, "host": "h0",
                "rank": 0},
               {"t": 0.1, "ev": "job_submit", "engine": "service",
                "job": "j1", "model": "m", "priority": 0},
               {"t": 0.2, "ev": "pool_util", "engine": "service",
                "busy_frac": 0.5, "per_host": {"0": 0.5}}]
        path = tmp_path / "service.jsonl"
        _write_stream(path, evs)
        tl = aggregate.merge([str(path)])
        by_ev = {e["ev"]: e for e in tl.events}
        assert by_ev["job_submit"]["lane_key"] == "job:j1"
        assert by_ev["pool_util"]["lane_key"] == "h0/r0:service"


# --- Prometheus exposition (unit) ------------------------------------------

class TestProm:
    def test_render_types_and_labels(self):
        text = prom.render([
            ({}, {"chunks": 3, "queue_depth": 2, "vmax": 7,
                  "engine": "device"}),
            ({"job": "j1", "host": "0"}, {"chunks": 5}),
        ])
        samples = prom.validate_exposition(text)
        assert samples[("stateright_chunks", ())] == 3
        assert samples[("stateright_chunks",
                        (("host", "0"), ("job", "j1")))] == 5
        assert samples[("stateright_queue_depth", ())] == 2
        # string gauges are JSON-only, never exposition samples
        assert not any(n == "stateright_engine"
                       for n, _ in samples)
        # typing: counters vs gauges vs maxima-as-gauges
        assert "# TYPE stateright_chunks counter" in text
        assert "# TYPE stateright_queue_depth gauge" in text
        assert "# TYPE stateright_vmax gauge" in text
        # HELP comes from the canonical glossary
        assert "# HELP stateright_chunks " in text

    def test_label_escaping_round_trips(self):
        text = prom.render(
            [({"job": 'a"b\\c'}, {"chunks": 1})])
        samples = prom.validate_exposition(text)
        ((_name, labels),) = samples.keys()
        assert labels == (("job", 'a\\"b\\\\c'),)

    def test_duplicate_series_raise(self):
        with pytest.raises(ValueError, match="duplicate series"):
            prom.render([({}, {"chunks": 1}), ({}, {"chunks": 2})])

    def test_validator_rejects_malformed(self):
        with pytest.raises(ValueError, match="before its TYPE"):
            prom.validate_exposition("stateright_x 1\n")
        with pytest.raises(ValueError, match="bad sample"):
            prom.validate_exposition(
                "# TYPE stateright_x counter\nstateright_x one\n")
        with pytest.raises(ValueError, match="reopened"):
            prom.validate_exposition(
                "# TYPE a counter\na 1\n# TYPE b counter\nb 1\n"
                "# HELP a again\n")
        with pytest.raises(ValueError, match="duplicate TYPE"):
            prom.validate_exposition(
                "# TYPE a counter\n# TYPE a counter\na 1\n")


# --- the service: /metrics, /utilization, SLO lifecycle ---------------------

@pytest.fixture(scope="module")
def service_run(tmp_path_factory):
    """Two concurrent jobs on a 2-device pool run to completion behind
    the HTTP API; yields the root, the final scheduler profile, the
    per-job results, and the served /metrics + /utilization payloads
    (captured live, before shutdown)."""
    root = tmp_path_factory.mktemp("svc")
    sched = Scheduler(JobStore(str(root)), devices=jax.devices()[:2])
    handle = serve_jobs(sched)
    try:
        j1 = sched.submit(JobSpec(model="twopc", args=[3],
                                  options=OPTS))
        j2 = sched.submit(JobSpec(model="twopc", args=[2],
                                  options=OPTS))
        assert sched.wait(j1.id, 180.0) == "done"
        assert sched.wait(j2.id, 180.0) == "done"
        profile = sched.profile()
        results = {j.id: j.read_result() for j in (j1, j2)}
        with urllib.request.urlopen(handle.url + "/metrics",
                                    timeout=30) as r:
            ctype = r.headers["Content-Type"]
            metrics_body = r.read().decode()
        with urllib.request.urlopen(handle.url + "/utilization",
                                    timeout=30) as r:
            util = json.loads(r.read())
    finally:
        handle.shutdown()
    return {"root": str(root), "profile": profile,
            "results": results, "metrics_body": metrics_body,
            "metrics_ctype": ctype, "utilization": util}


class TestServiceSlo:
    def test_lifecycle_stamps_in_result(self, service_run):
        results = service_run["results"]
        for result in results.values():
            lc = result["lifecycle"]
            assert lc["submit"] <= lc["grant"] <= lc["start"]
            assert lc["start"] <= lc["first_chunk"] <= lc["done"]
            assert lc["queue_wait_s"] >= 0
            assert lc["first_chunk_s"] > 0
            assert lc["run_s"] > 0
            assert result["run_id"].startswith("run-")

    def test_scheduler_slo_aggregates(self, service_run):
        profile = service_run["profile"]
        assert profile["queue_wait_s"] >= 0
        assert profile["first_chunk_s"] > 0
        assert profile["jobs_per_min"] == 2
        assert profile["jobs_done"] == 2
        assert "pool_busy_frac" in profile

    def test_service_stream_has_header_and_lifecycle(self,
                                                     service_run):
        evs = [json.loads(l) for l in
               open(os.path.join(service_run["root"],
                                 "service.jsonl"))]
        for ev in evs:
            validate_event(ev)
        kinds = [e["ev"] for e in evs]
        assert kinds[0] == "trace_header"
        for jid_kinds in ("job_submit", "job_grant", "job_start",
                          "job_first_chunk", "job_done", "pool_util"):
            assert jid_kinds in kinds
        # grant precedes start precedes first_chunk, per job
        for jid in {e.get("job") for e in evs if e.get("job")}:
            ks = [e["ev"] for e in evs if e.get("job") == jid]
            assert (ks.index("job_grant") < ks.index("job_start")
                    < ks.index("job_first_chunk")
                    < ks.index("job_done"))

    def test_metrics_endpoint_round_trips(self, service_run):
        assert service_run["metrics_ctype"].startswith(
            "text/plain; version=0.0.4")
        samples = prom.validate_exposition(service_run["metrics_body"])
        assert samples[("stateright_jobs_submitted", ())] == 2
        assert samples[("stateright_jobs_done", ())] == 2
        assert ("stateright_queue_wait_s", ()) in samples
        assert ("stateright_first_chunk_s", ()) in samples
        util = service_run["utilization"]
        assert set(util) >= {"busy_frac", "per_host", "samples",
                             "width"}
        assert util["samples"], "utilization sampler recorded nothing"

    def test_live_job_registries_labeled(self, tmp_path):
        """Mid-run, /metrics carries per-job series under job/host
        labels merged with the scheduler's own registry."""
        sched = Scheduler(JobStore(str(tmp_path)),
                          devices=jax.devices()[:1])
        handle = serve_jobs(sched)
        job = sched.submit(JobSpec(model="twopc", args=[3],
                                   options=OPTS, step_delay=0.05))
        try:
            deadline = time.monotonic() + 60.0
            labeled = None
            while time.monotonic() < deadline:
                with urllib.request.urlopen(handle.url + "/metrics",
                                            timeout=30) as r:
                    samples = prom.validate_exposition(
                        r.read().decode())
                labeled = [k for k in samples
                           if dict(k[1]).get("job") == job.id]
                if labeled:
                    break
                if sched.job(job.id).state == "done":
                    break
                time.sleep(0.02)
            assert labeled, "no job-labeled series appeared mid-run"
            assert ("stateright_jobs_submitted", ()) in samples
            assert sched.wait(job.id, 120.0) == "done"
        finally:
            handle.shutdown()


# --- acceptance: fleet + service artifacts merge into ONE timeline ----------

class TestFleetTimelineAcceptance:
    def test_two_proc_fleet_and_service_merge(self, service_run,
                                              tmp_path):
        """A 2-process launcher mesh run AND the concurrent-jobs
        service run aggregate into one causally-ordered timeline:
        non-decreasing fleet time, every event resolvable to a run
        id, both fleets' lanes present."""
        out = tmp_path / "fleet"
        cmd = [sys.executable,
               os.path.join(REPO, "tools", "mesh_launch.py"),
               "--procs", "2", "--devices-per-proc", "2",
               "--model", "twopc", "--args", "3",
               "--capacity", "4096", "--fmax", "64",
               "--chunk-steps", "2", "--out", str(out)]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        svc_root = service_run["root"]
        results = service_run["results"]

        tl = aggregate.merge([str(out), svc_root])
        assert tl.skew_bound_s > 0  # the 2-proc dcn_probe round trip
        assert len(tl.segments) >= 4  # fleet + rank0 + service + jobs
        # non-decreasing fleet time over the whole merged timeline
        ts = [e["fleet_t"] for e in tl.events if e["anchored"]]
        assert ts == sorted(ts)
        assert all(e["anchored"] for e in tl.events)
        # every event id-resolvable (a real header, not a synthesized
        # anon id)
        assert all(e["run_id"] and not e["run_id"].startswith("anon:")
                   for e in tl.events)
        lanes = tl.lanes()
        assert any(l.startswith("job:") for l in lanes)
        assert any(":fleet" in l or "fleet-" in l or
                   "r0" in l for l in lanes)
        # the service jobs' engine streams are job-resolved lanes
        for jid in results:
            assert f"job:{jid}" in lanes
        # schema: every merged event still validates (annotations are
        # supersets; required fields intact)
        for ev in tl.events:
            validate_event(ev)

    def test_trace_report_fleet_render(self, service_run, capsys):
        trace_report = _tool("trace_report")
        assert trace_report.main(["--fleet", service_run["root"],
                                  "--validate"]) == 0
        out = capsys.readouterr().out
        assert "=== fleet timeline:" in out
        assert "interventions (fleet_t):" in out
        for jid in service_run["results"]:
            assert f"job:{jid}" in out


# --- satellite: watch.follow_url reconnect ----------------------------------

class _SseScript:
    """A fake SSE endpoint: first connection drops mid-stream, the
    second replays the full backlog (the flight-ring contract) and
    finishes with done."""

    def __init__(self):
        self.events = [
            {"t": 0.1 * i, "ev": "chunk", "engine": "E", "chunk": i,
             "gen": i, "unique": i, "q_size": 0, "new": 1,
             "dedup_hit": 0.0, "load": 0.1} for i in range(5)
        ] + [{"t": 0.9, "ev": "done", "engine": "E", "gen": 5,
              "unique": 5}]
        self.connections = 0

    def serve(self):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        script = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                script.connections += 1
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()
                n = 3 if script.connections == 1 else len(script.events)
                for ev in script.events[:n]:
                    self.wfile.write(
                        b"data: " + json.dumps(ev).encode() + b"\n\n")
                self.wfile.flush()
                # first connection: drop abruptly, mid-run

        server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        return server


class TestWatchReconnect:
    def test_reconnect_resumes_without_duplicates(self):
        watch = _tool("watch")
        script = _SseScript()
        server = script.serve()
        host, port = server.server_address
        sleeps = []
        try:
            got = list(watch.follow_url(
                f"http://{host}:{port}/.events",
                _sleep=sleeps.append))
        finally:
            server.shutdown()
            server.server_close()
        assert script.connections == 2
        # every event exactly once, in order, ending at done — the
        # reconnect replayed the backlog without re-rendering it
        assert got == script.events
        # the gap was a jittered backoff, not a hot spin
        assert len(sleeps) == 1
        assert 0.25 <= sleeps[0] <= 0.5  # base 0.5 x jitter [0.5, 1)

    def test_clean_finished_replay_ends_without_retrying(self):
        watch = _tool("watch")
        script = _SseScript()
        script.events = script.events[:-1]  # no terminal done event
        server = script.serve()
        script.connections = 1  # second-connection script: full replay
        host, port = server.server_address
        sleeps = []
        try:
            got = list(watch.follow_url(
                f"http://{host}:{port}/.events",
                _sleep=sleeps.append))
        finally:
            server.shutdown()
            server.server_close()
        # full stream once, then one clean re-poll delivering nothing
        # new ends the follow — no retry spin on a finished replay
        assert got == script.events
        assert len(sleeps) == 1


# --- satellite: SSE slow-client drops are counted and surfaced --------------

class TestSseDropped:
    def test_drop_counts_metric_and_single_warning(self, capsys):
        from stateright_tpu.checker.explorer import _SseClient
        metrics = Metrics()
        client = _SseClient(qsize=2, metrics=metrics, label="t")
        for i in range(5):
            client.feed({"i": i})
        assert client.dropped == 3
        assert metrics.get("sse_dropped") == 3
        err = capsys.readouterr().err
        assert err.count("slow; dropping events") == 1  # once, not 3
        assert "sse_dropped" in err

    def test_serve_events_still_streams(self):
        # the Explorer SSE path still works end-to-end on top of the
        # refactored client (regression guard for the _SseClient move)
        from stateright_tpu.checker.explorer import serve
        handle = serve(TwoPhaseSys(2).checker(), ("127.0.0.1", 0),
                       block=False)
        try:
            handle.checker.join()
            with urllib.request.urlopen(
                    f"{handle.url}/.events", timeout=30) as r:
                body = r.read().decode()
            evs = [json.loads(l[len("data:"):])
                   for l in body.splitlines()
                   if l.startswith("data:")]
            assert evs and evs[0]["ev"] == "run_start"
            assert evs[0]["run_id"].startswith("run-")
        finally:
            handle.shutdown()


# --- satellite: MetricsRing lives in obs now --------------------------------

class TestMetricsRingMove:
    def test_reexport_is_same_class(self):
        from stateright_tpu.checker import explorer
        assert explorer.MetricsRing is MetricsRing

    def test_generic_sampler_surface(self):
        ring = MetricsRing(limit=8, interval=0.01)
        state = {"n": 0}

        def sample():
            state["n"] += 1
            return {"n": state["n"]}

        ring.sample_until(sample, lambda: state["n"] >= 3)
        samples = ring.snapshot()
        # done_fn latches at n=3; one final post-done sample lands so
        # the series ends at the terminal value
        assert [s["n"] for s in samples] == [1, 2, 3, 4]
        assert all("wall" in s for s in samples)


# --- the fleetboard console -------------------------------------------------

class TestFleetboard:
    def _snapshot(self, uniq):
        return {
            "jobs": [
                {"id": "j0001-twopc", "state": "running",
                 "granted_width": 2, "hosts": ["0"], "unique": uniq},
                {"id": "j0002-twopc", "state": "queued", "width": 1},
                {"id": "j0003-twopc", "state": "done"},
            ],
            "profile": {"jobs_submitted": 3, "jobs_done": 1,
                        "jobs_per_min": 1, "queue_wait_s": 0.8,
                        "first_chunk_s": 2.0, "preemptions": 1,
                        "sse_dropped": 2},
            "utilization": {"busy_frac": 0.5, "width": 4,
                            "queue_depth": 1,
                            "per_host": {"0": 0.5},
                            "samples": [{"busy_frac": 0.25},
                                        {"busy_frac": 0.5}]},
        }

    def test_board_renders_and_rates(self):
        fleetboard = _tool("fleetboard")
        board = fleetboard.Board()
        first = board.feed(self._snapshot(1000))
        assert "run=1 queued=1" in first
        assert "50% busy" in first and "[0]" in first
        assert "uniq=1,000" in first
        assert "queue_wait 0.27s/job" in first  # 0.8 / 3 submitted
        assert "preemptions=1" in first and "sse_dropped=2" in first
        assert "trend" in first
        second = board.feed(self._snapshot(3000))
        assert "uniq=3,000" in second
        assert "+" in second and "/s" in second  # throughput delta

    def test_offline_board_from_service_root(self, service_run,
                                             capsys):
        fleetboard = _tool("fleetboard")
        assert fleetboard.main([service_run["root"], "--once"]) == 0
        out = capsys.readouterr().out
        assert "== fleetboard" in out
        assert "done=2" in out
        assert "pool" in out
